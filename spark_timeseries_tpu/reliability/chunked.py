"""Chunked fit execution: pipelined commits, OOM backoff, journal, watchdog.

The north-star workload (ROADMAP: 1M series x 1k obs) cannot always fit one
monolithic batch in HBM — and the right chunk size depends on the model,
the dtype, and what else is resident on the chip.  Rather than making the
caller guess, :func:`fit_chunked` walks the panel in row chunks and treats
``RESOURCE_EXHAUSTED`` as a recoverable signal: the chunk size is halved
(bounded retries) and the degradation is recorded in the result metadata,
the batch analog of Spark re-running a too-big task after an executor OOM.

Only allocation failures trigger backoff; every other error propagates
unchanged (halving a chunk cannot fix a shape bug, and silently retrying
would bury it).

Above the backoff sit the two *job-level* durability layers Spark provided
for free and a single Python process does not:

- ``checkpoint_dir=`` attaches a write-ahead **chunk journal**
  (:mod:`.journal`): every finished chunk is committed as an npz shard
  plus an atomically updated manifest, and a restarted run SKIPS committed
  chunks, producing results bitwise-identical to an uninterrupted run.
- ``chunk_budget_s=`` / ``job_budget_s=`` arm the **deadline watchdog**
  (:mod:`.watchdog`): a chunk that overruns its wall-clock budget is
  marked ``FitStatus.TIMEOUT`` (rows NaN, journal entry ``TIMEOUT``) and
  the walk continues; once the job budget is spent, remaining chunks are
  marked TIMEOUT without dispatch.  The job always terminates with exact
  per-row status counts instead of hanging past its SLO, and a later
  resume retries only the TIMEOUT/pending chunks.

**Pipelined execution** (``pipeline=True``, the default): the serial walk
paid the full journal-commit latency — host fetch, npz shard, fsync,
manifest rewrite — between every two chunk dispatches, idling the device
for all of it.  Spark never did: per-partition compute pipelined with
shuffle/persist I/O under lazy RDD execution (PAPER.md §3).  The rebuild
of that overlap: finished chunks are handed to a bounded background
committer (:class:`~.committer.ChunkCommitter`, at most ``pipeline_depth``
commits in flight) that preserves the journal's single-writer,
shard-before-manifest, in-order protocol, while the driver thread is
already slicing and dispatching the next chunk — and, for non-resilient
fits, JAX async dispatch lets that dispatch land while the previous
chunk's device computation is still in flight.  Results are
bitwise-identical to ``pipeline=False`` (same chunk boundaries, same
compiled programs, same bytes — only where the host fetch and disk I/O
happen moves), a crash with commits in flight resumes exactly like a
serial crash (in-order commits: everything after the first in-flight
commit recomputes), and the OOM-backoff/watchdog paths drain the queue
deterministically before touching the journal.  ``meta["pipeline"]``
reports how much commit wall time the overlap hid.

**Dispatch-ahead input** (ISSUE 5) closes the other half: a static
align-mode plan (computed once per walk, threaded into every chunk fit)
removes the per-chunk NaN-probe host sync, and a bounded background
:class:`~.prefetcher.ChunkPrefetcher` stages chunk N+1's device slice
while chunk N computes — the steady state is stage N+1 ∥ compute N ∥
commit N−1, with the input-side overlap accounted next to the commit-side
numbers in ``meta["pipeline"]``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import memory as memory_probe
from . import committer as committer_mod
from . import journal as journal_mod
from . import prefetcher as prefetcher_mod
from . import watchdog as watchdog_mod
from .runner import ResilientFitResult, _accepted_kwargs, resilient_fit
from .status import STATUS_DTYPE, FitStatus, status_counts

__all__ = ["OOMBackoffExceeded", "is_resource_exhausted", "fit_chunked"]

# substrings the XLA runtime uses for allocation failure; the simulated OOM
# of reliability.faultinject raises with the same marker so tier-1 CPU tests
# drive this path without a real HBM exhaustion
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


class OOMBackoffExceeded(RuntimeError):
    """Raised when the minimum chunk size still exhausts device memory."""


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA RESOURCE_EXHAUSTED-style allocation failures.

    ``jaxlib``'s ``XlaRuntimeError`` subclasses ``RuntimeError``, so the
    check is message-based on RuntimeError/MemoryError rather than pinned
    to a jaxlib exception type that moves between releases.
    """
    if isinstance(e, MemoryError):
        return True
    if not isinstance(e, RuntimeError):
        return False
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def _span_times(sp) -> dict:
    """Wall/process times of a closed chunk span, or ``{}`` when the plane
    was disabled mid-run (the span degraded to the shared no-op whose
    times are None — telemetry may lose a row's timings but must never
    crash the fit it observes)."""
    if sp.wall_s is None:
        return {}
    out = {"wall_s": round(sp.wall_s, 6)}
    if sp.process_s is not None:
        out["process_s"] = round(sp.process_s, 6)
    return out


class _TimeoutChunk:
    """Placeholder for a chunk whose fit never finished; materialized into
    NaN-param / ``TIMEOUT``-status rows once the parameter width is known
    (from any finished chunk) at assembly time."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi


def _commit_arrays(piece) -> dict:
    """Host-side arrays of one finished chunk, in the journal shard schema.

    Under the pipelined driver this runs on the committer thread, so for
    non-resilient fits the device->host fetch itself overlaps the next
    chunk's device compute."""
    return {
        "params": np.asarray(piece.params),
        "nll": np.asarray(piece.neg_log_likelihood),
        "converged": np.asarray(piece.converged),
        "iters": np.asarray(piece.iters),
        "status": _piece_status(piece),
    }


@obs.dump_on_failure("fit_chunked")
def fit_chunked(
    fit_fn: Callable,
    y,
    *,
    chunk_rows: Optional[int] = None,
    min_chunk_rows: int = 256,
    max_backoffs: int = 8,
    resilient: bool = True,
    policy: str = "impute",
    ladder=None,
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    align_mode: Optional[str] = None,
    process_index: Optional[int] = None,
    journal_extra: Optional[dict] = None,
    _journal_commit_hook=None,
    **fit_kwargs,
) -> ResilientFitResult:
    """Fit ``y [B, T]`` in row chunks of at most ``chunk_rows``.

    Each chunk runs through :func:`~.runner.resilient_fit` (sanitize +
    retry ladder) unless ``resilient=False``, in which case ``fit_fn`` is
    called directly and per-row status comes from the model's own status
    output.  On a ``RESOURCE_EXHAUSTED`` failure the chunk size halves
    (never below ``min_chunk_rows``) and the chunk is retried, at most
    ``max_backoffs`` times across the whole run; exhausting the budget (or
    OOMing at the floor) raises :class:`OOMBackoffExceeded`.

    **Durability** (``checkpoint_dir=``): finished chunks are committed to
    a write-ahead journal (:class:`~.journal.ChunkJournal`) — npz shard
    first, then an atomic manifest update recording the row range, per-row
    ``FitStatus`` counts, wall time, peak device memory, and the run's
    config hash / panel fingerprint.  A restarted call with the same panel
    and config (``resume="auto"``, the default) loads committed chunks
    from their shards and recomputes only what is missing, so the final
    result is bitwise-identical to an uninterrupted run; a journal written
    under a different panel or config is rejected
    (:class:`~.journal.StaleJournalError`), as is a torn manifest
    (:class:`~.journal.TornManifestError`) — under EVERY resume mode: a
    journal directory belongs to one (panel, config) job for its lifetime,
    and a different job must claim a fresh directory (or the operator
    removes the old one explicitly).  ``resume="never"`` reruns the same
    job from scratch, ignoring its committed chunks; ``"require"`` demands
    a resumable manifest.  Under
    ``jax.distributed`` every process journals into its own namespace and
    only process 0 commits the job-level ``manifest.json``
    (``process_index`` defaults to ``jax.process_index()``).

    **Pipelining** (``pipeline=True``, default): with a journal attached,
    the host fetch + shard write + manifest update of a finished chunk run
    on a background committer thread (at most ``pipeline_depth`` commits
    in flight, in order) while the driver dispatches the next chunk, so
    the device no longer idles for the commit latency.  The pipeline
    changes WHERE the commit I/O happens, never what is computed: results
    are bitwise-identical to ``pipeline=False``, the journal's
    single-writer / shard-before-manifest / in-order protocol is
    preserved, and a crash with commits in flight resumes exactly as a
    serial crash would (uncommitted chunks recompute).  The pipeline
    knobs are deliberately EXCLUDED from the journal's config hash — a
    serial journal resumes under a pipelined run and vice versa.
    ``pipeline=False`` restores the fully serial walk.
    ``meta["pipeline"]`` reports the commit wall time, how much of it the
    driver never waited for (``hidden_commit_s``), and the resulting
    ``overlap_efficiency``.

    **Input staging** (the other half of the pipeline): while chunk N
    computes, a background :class:`~.prefetcher.ChunkPrefetcher` stages
    chunk N+1's device slice (at most ``prefetch_depth`` slices ahead,
    default 1 — the classic double buffer), so in steady state the walk
    runs stage N+1 ∥ compute N ∥ commit N−1.  The staged buffer is the
    SAME ``yb[lo:hi]`` the serial driver slices (identical bytes); the
    driver predicts the next span on the committed grid (resume clamping
    and torn-shard boundaries included) and invalidates staged slices
    whenever OOM backoff or a committer rollback re-chunks the walk, so a
    stale prediction degrades to an inline slice, never a wrong one.
    ``prefetch_depth=0`` (or ``pipeline=False``) disables staging.
    ``meta["pipeline"]`` gains the input-side accounting
    (``staging_wall_s`` / ``hidden_staging_s`` /
    ``input_overlap_efficiency``) and the combined
    ``end_to_end_overlap_efficiency``.

    **Static align-mode plan**: when ``fit_fn`` accepts the ``align_mode``
    hint (every bundled model fit does — ``models.base.resolve_align_mode``),
    a sliced walk computes the panel's alignment mode ONCE and threads it
    into every chunk fit as a static argument, eliminating the per-chunk
    NaN-probe host sync and the per-array-identity align-cache misses on
    fresh slice buffers.  The panel-level mode is a row-wise property, so
    it is exact for every row slice.  Pass ``align_mode=`` to skip even
    the one probe (the journal's config hash covers the resolved mode, so
    a resumed run must use the same plan); a hint too strong for the data
    flags the violating rows instead of silently misfitting them (see
    ``resolve_align_mode``).  Resilient walks downgrade the hint to
    ``"general"`` for chunks the sanitizer actually modified
    (``runner.resilient_fit``), keeping the hint sound when repairs
    change a chunk's NaN pattern.  ``meta["align_mode"]`` records the
    plan.

    **Deadlines**: ``chunk_budget_s`` bounds each chunk's fit (overrun ->
    rows flagged ``TIMEOUT``, walk continues — the compiled computation is
    abandoned, not cancelled; with the budget armed, non-resilient fits
    block on device completion inside the watchdog window so the budget
    covers compute, not just async dispatch); ``job_budget_s`` bounds the
    whole walk (once spent, remaining chunks are marked TIMEOUT without
    dispatch).  Both paths drain the commit queue before touching the
    journal, so the TIMEOUT mark always lands after every earlier commit.
    Partial results always carry exact status counts, and TIMEOUT chunks
    are retried on a journaled resume.

    ``meta`` records ``chunk_rows_initial`` / ``chunk_rows_final``, every
    backoff and timeout event, ``degraded=True`` whenever a backoff or
    timeout happened, and — when journaled — the journal accounting
    (``meta["journal"]``: run id, chunks committed/resumed/timeout).

    **Telemetry** (``obs.enable()``): each chunk dispatch runs under an
    ``obs.span("chunk")`` whose first dispatch per (fit, shape, dtype) is
    tagged ``compile+execute`` (JAX pays trace+compile there) and the rest
    ``execute``; backoffs, timeouts, and per-row status totals feed the
    metrics registry; the committer reports a ``committer.queue_depth``
    gauge, per-commit ``commit.overlap`` spans, and a
    ``committer.hidden_commit_ms`` counter; and the per-run summary —
    per-chunk span times, counters, peak memory (never null: host-RSS
    fallback) — lands in ``meta["telemetry"]`` and, when journaled, the
    manifest's ``telemetry`` block.  Disabled (the default), none of this
    runs and the result is bitwise-identical to the uninstrumented driver.
    """
    yb = jnp.asarray(y)
    if yb.ndim != 2:
        raise ValueError(f"fit_chunked expects [batch, time], got {yb.shape}")
    b = yb.shape[0]
    chunk = int(chunk_rows) if chunk_rows else b
    chunk = max(1, min(chunk, b))
    chunk0 = chunk

    # static align-mode plan: resolve the panel's alignment mode ONCE (or
    # take the caller's hint) and thread it into every chunk fit as a
    # static argument — the per-chunk NaN probe (one host sync per sliced
    # chunk) disappears.  The mode is a row-wise property of the panel, so
    # the panel-level answer is exact for every row slice.  Injected
    # BEFORE the journal's config hash is computed: the plan changes which
    # compiled program fits the chunks, so a resume must run the same one.
    from ..models import base as model_base

    import inspect as _inspect

    def _explicit_align_param(fn) -> bool:
        try:
            return "align_mode" in _inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False

    fit_takes_align = "align_mode" in _accepted_kwargs(
        fit_fn, {"align_mode": None})
    if align_mode is not None:
        # a caller-provided hint is an explicit opt-in: a **kwargs fit_fn
        # is trusted to forward it (the caller asserted it can)
        if not fit_takes_align:
            raise TypeError(
                "align_mode= was given but fit_fn does not accept an "
                "align_mode keyword (the hint would be silently dropped)")
        fit_kwargs = {**fit_kwargs,
                      "align_mode": model_base.resolve_align_mode(
                          yb, align_mode)}
    elif (_explicit_align_param(fit_fn) and chunk < b
          and "align_mode" not in fit_kwargs):
        # AUTO-injection requires align_mode as an explicitly NAMED
        # parameter — a bare **kwargs does not count (a third-party
        # `def my_fit(y, **opts)` forwarding to a strict solver would
        # blow up on, or silently absorb, a keyword it never asked for).
        # Only sliced walks benefit: a whole-panel chunk hands the
        # caller's array through and the model's own per-array probe
        # cache holds
        fit_kwargs = {**fit_kwargs,
                      "align_mode": model_base.align_mode_on_host(yb)}
    plan_mode = fit_kwargs.get("align_mode") if fit_takes_align else None

    journal = None
    if checkpoint_dir is not None:
        if process_index is None:
            try:
                process_index = jax.process_index()
            except Exception:  # noqa: BLE001 - no backend yet: single process
                process_index = 0
        # pipeline knobs deliberately NOT hashed: they move I/O between
        # threads without changing a byte of the result, and a serial
        # journal must resume under a pipelined run (and vice versa)
        cfg = journal_mod.config_hash(
            fit_fn, fit_kwargs,
            extra={"chunk_rows": chunk0, "min_chunk_rows": min_chunk_rows,
                   "resilient": resilient, "policy": policy,
                   "ladder": "default" if ladder is None else repr(ladder)})
        journal = journal_mod.ChunkJournal(
            checkpoint_dir,
            config_hash=cfg,
            panel_fingerprint=journal_mod.panel_fingerprint(yb),
            n_rows=b,
            chunk_rows=chunk0,
            resume=resume,
            process_index=process_index,
            extra=journal_extra,
            commit_hook=_journal_commit_hook,
        )
    committer = None
    if journal is not None and pipeline:
        committer = committer_mod.ChunkCommitter(
            journal, _commit_arrays, depth=pipeline_depth,
            probe=memory_probe.peak_memory, status_counts=status_counts)
    # input-side pipeline: stage chunk N+1's slice while chunk N computes.
    # Only sliced walks stage (a whole-panel chunk has no next slice), and
    # pipeline=False stays the fully serial escape hatch for BOTH halves
    prefetcher = None
    if pipeline and prefetch_depth and chunk < b:
        prefetcher = prefetcher_mod.ChunkPrefetcher(yb, depth=prefetch_depth)
    deadline = watchdog_mod.Deadline(job_budget_s)

    import time as _time

    # per-chunk telemetry rows for meta["telemetry"] / the manifest block;
    # None (not empty) when disabled so the disabled path allocates nothing
    # and meta stays byte-identical to the uninstrumented driver
    tele = obs.enabled()
    tele_chunks = [] if tele else None
    # counter baseline at fit start: the registry is run-wide (one
    # obs.enable() can span many fits), but THIS fit's summary must report
    # its own activity — counters are emitted as deltas from here, so fit
    # B's manifest does not inherit fit A's DIVERGED rows or OOM backoffs.
    # Known limit: a watchdog-ABANDONED worker (timed-out chunk) may still
    # be incrementing counters after its fit returns; those late increments
    # land in whichever delta window is open (XLA dispatch cannot be
    # cancelled, so this is inherent to abandonment, and data-quality only)
    counters0 = (obs.snapshot() or {}).get("counters") if tele else None
    # compile-affecting identity of this fit config, computed ONCE: the
    # first dispatch per (config, chunk-rows) pays JAX trace+compile, and a
    # later job with the same shape but different static config (order,
    # max_iters, backend, ladder) compiles anew — reuse the journal's
    # config_hash (fit identity + every kwarg + driver knobs) so the
    # compile-identity ingredients live in ONE place
    fit_key = journal_mod.config_hash(
        fit_fn, fit_kwargs,
        extra={"resilient": resilient, "policy": policy,
               "ladder": "default" if ladder is None else repr(ladder),
               "time": int(yb.shape[1]), "dtype": str(yb.dtype)},
    ) if tele else None

    pieces = []  # (lo, hi, piece) in walk order; piece may be _TimeoutChunk
    oom_events = []
    timeout_events = []
    # boundaries of committed-but-unloadable (torn-shard) chunks: the
    # recompute must cover the EXACT recorded [lo, hi) — deriving hi from
    # the current chunk size could overlap a later committed chunk and
    # break the bitwise-identical-boundaries contract
    lost_boundaries: dict = {}
    lo = 0

    def _record_oom(at_row: int, rows: int, e: BaseException) -> int:
        """Shared backoff bookkeeping for fit-time, staging-time, and
        commit-time OOMs; returns the halved chunk size (or raises when
        the budget/floor is spent).  Every staged slice is invalidated
        first: the halved boundary makes every prefetch prediction wrong,
        and a freed staged buffer is exactly the HBM the retry needs."""
        if prefetcher is not None:
            prefetcher.invalidate()
        oom_events.append({
            "at_row": at_row, "chunk_rows": rows,
            "error": f"{type(e).__name__}: {e}"[:200],
        })
        obs.counter("chunked.oom_backoffs").inc()
        obs.event("chunk.oom_backoff", at_row=at_row, chunk_rows=rows)
        if rows <= min_chunk_rows or len(oom_events) > max_backoffs:
            raise OOMBackoffExceeded(
                f"chunk of {rows} rows still RESOURCE_EXHAUSTED after "
                f"{len(oom_events)} backoffs (floor {min_chunk_rows})"
            ) from e
        return max(min_chunk_rows, rows // 2)

    def _rollback(err):
        """Handle a committer-detected failure (the fetch/commit of an
        async-dispatched chunk raised on the worker thread).

        Non-OOM errors re-raise unchanged.  An OOM rolls the walk back to
        the failed chunk: everything at/after it is uncommitted (in-order
        queue), so its pieces are dropped, the chunk size halves, and the
        walk re-enters at the failed row — the pipelined twin of the
        fit-time backoff.  Returns the (lo, chunk) to continue from."""
        e, flo, fhi = err
        if not is_resource_exhausted(e):
            raise e
        new_chunk = _record_oom(flo, fhi - flo, e)
        pieces[:] = [p for p in pieces if p[0] < flo]
        if tele:
            tele_chunks[:] = [r for r in tele_chunks if r["lo"] < flo]
        return flo, new_chunk

    def _next_span(nlo: int, cur_chunk: int):
        """The span the walk will visit after the current chunk — the
        prefetcher's prediction.  Mirrors the walk's own boundary logic
        exactly: torn-shard forced boundaries, then the committed-grid
        clamp (a staged slice must never sail past a committed chunk's
        ``lo``).  Returns None at the panel end or when the next span is
        already committed (the resume path loads it from its shard — no
        device slice needed)."""
        if nlo >= b:
            return None
        if journal is not None and journal.committed(nlo) is not None:
            return None
        forced = lost_boundaries.get(nlo)
        if forced:
            return nlo, forced[0]
        nhi = min(nlo + cur_chunk, b)
        if journal is not None:
            nxt = journal.next_committed_lo(nlo)
            if nxt is not None and nxt < nhi:
                nhi = nxt
        return nlo, nhi

    def _drain_for_journal_write():
        """Synchronize with the committer before the driver itself writes
        the journal (TIMEOUT marks, forced torn-shard recommits): after
        this, every earlier commit is durable and the driver is the only
        writer.  Returns a pending error tuple instead of raising so the
        caller can roll back."""
        if committer is None:
            return None
        return committer.drain(raise_pending=False)

    try:
        while True:
            if committer is not None:
                err = committer.take_error()
                if err is not None:
                    lo, chunk = _rollback(err)
                    continue
            if lo >= b:
                # final drain: a commit of one of the last chunks may still
                # fail (or OOM at fetch) — that must surface (or roll the
                # walk back) BEFORE assembly reads the pieces
                err = _drain_for_journal_write()
                if err is not None:
                    lo, chunk = _rollback(err)
                    continue
                break
            if journal is not None:
                entry = journal.committed(lo)
                if entry is not None:
                    piece = journal.load_chunk(entry)
                    if piece is not None:
                        pieces.append((lo, int(entry["hi"]), piece))
                        if tele:
                            tele_chunks.append({"lo": lo,
                                                "hi": int(entry["hi"]),
                                                "phase": "resumed"})
                        lo = entry["hi"]
                        # replay the backoff state in effect when the chunk
                        # committed, so the resumed walk visits the SAME
                        # boundaries the uninterrupted run would have
                        chunk = int(entry.get("chunk_rows_after", chunk))
                        continue
                    lost_boundaries[lo] = (
                        int(entry["hi"]),
                        int(entry.get("chunk_rows_after", chunk)))
            forced = lost_boundaries.get(lo)
            hi = forced[0] if forced else min(lo + chunk, b)
            if journal is not None and not forced:
                # keep the walk on the committed grid: after an OOM backoff
                # whose halving does not divide the original chunk size, a
                # free-running hi would sail past the next committed chunk's
                # lo, orphaning it (never matched again) and double-counting
                # its rows in the manifest — clamp to the boundary instead
                nxt = journal.next_committed_lo(lo)
                if nxt is not None and nxt < hi:
                    hi = nxt
            if deadline.exceeded():
                err = _drain_for_journal_write()
                if err is not None:
                    lo, chunk = _rollback(err)
                    continue
                if forced:
                    chunk = forced[1]
                    lost_boundaries.pop(lo, None)
                timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": False,
                    "budget_s": deadline.budget_s, "scope": "job"})
                obs.counter("chunked.timeouts.job").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="job",
                          dispatched=False)
                if tele:
                    tele_chunks.append({"lo": lo, "hi": hi,
                                        "phase": "timeout", "scope": "job"})
                pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="job",
                                         budget_s=deadline.budget_s,
                                         chunk_rows_after=chunk)
                lo = hi
                continue
            def run_chunk(lo=lo, hi=hi, chunk=chunk):
                # lo/hi/chunk are DEFAULT-ARG SNAPSHOTS, not closure reads:
                # a watchdog-abandoned thread keeps running after the driver
                # has mutated the loop variables, and it must keep operating
                # on ITS chunk's span — never take() the live chunk's staged
                # slice or slice a torn lo/hi pair mid-update (the pre-
                # prefetcher code snapshotted `vals` itself for the same
                # reason).
                # acquire this chunk's values INSIDE the watchdog window:
                # the whole-panel chunk hands the caller's array through
                # untouched (a slice would be a fresh device buffer — an
                # extra HBM copy, and a miss in the per-array-identity
                # align-mode cache callers pre-warm); sliced chunks come
                # from the prefetcher when the staged prediction matched.
                # A staged slice can be queued behind an ABANDONED
                # (timed-out) computation, so the wait on it must be
                # bounded by the same budget as the compute it feeds — and
                # a staging-time RESOURCE_EXHAUSTED surfaces here, through
                # the watchdog, into the same backoff ladder as a fit-time
                # one.
                if lo == 0 and hi == b:
                    vals = yb
                elif prefetcher is not None:
                    vals = prefetcher.take(lo, hi)
                else:
                    vals = yb[lo:hi]
                if prefetcher is not None:
                    # stage the next spans now (up to depth ahead — take()
                    # just freed this chunk's slot), so they materialize
                    # while this chunk computes (and, for resilient fits,
                    # while the ladder blocks on host work)
                    nlo = hi
                    for _ in range(prefetcher.depth):
                        nxt = _next_span(nlo, chunk)
                        if nxt is None:
                            break
                        prefetcher.schedule(*nxt)
                        nlo = nxt[1]
                if resilient:
                    return resilient_fit(
                        fit_fn, vals, policy=policy, ladder=ladder,
                        **fit_kwargs)
                out = fit_fn(vals, **fit_kwargs)
                if chunk_budget_s is not None:
                    # with a deadline armed the budget must cover the device
                    # computation, not just its async dispatch — block here,
                    # INSIDE the watchdog window
                    jax.block_until_ready(out)
                return out

            phase = None
            if tele:
                # first dispatch of this (fit config, chunk rows) pays JAX
                # trace+compile; later dispatches of the same shape execute a
                # cached program — the split BENCH scraped ad hoc, now
                # recorded per chunk (a backoff-halved chunk is a NEW shape =
                # new compile)
                phase = ("compile+execute"
                         if obs.first_dispatch((fit_key, hi - lo))
                         else "execute")
            sp = obs.span("chunk", lo=lo, hi=hi, phase=phase)
            t0 = _time.perf_counter()
            try:
                with sp:
                    piece = watchdog_mod.call_with_deadline(
                        run_chunk, chunk_budget_s,
                        label=f"chunk rows [{lo}, {hi})")
            except watchdog_mod.DeadlineExceeded:
                err = _drain_for_journal_write()
                if err is not None:
                    lo, chunk = _rollback(err)
                    continue
                if forced:
                    chunk = forced[1]
                    lost_boundaries.pop(lo, None)
                timeout_events.append({
                    "at_row": lo, "chunk_rows": hi - lo, "dispatched": True,
                    "budget_s": chunk_budget_s, "scope": "chunk"})
                obs.counter("chunked.timeouts.chunk").inc()
                obs.event("chunk.timeout", lo=lo, hi=hi, scope="chunk",
                          dispatched=True, budget_s=chunk_budget_s)
                if tele:
                    tele_chunks.append({"lo": lo, "hi": hi,
                                        "phase": "timeout", "scope": "chunk",
                                        **_span_times(sp)})
                pieces.append((lo, hi, _TimeoutChunk(lo, hi)))
                if journal is not None:
                    journal.mark_timeout(lo, hi, scope="chunk",
                                         budget_s=chunk_budget_s,
                                         chunk_rows_after=chunk)
                lo = hi
                continue
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not is_resource_exhausted(e):
                    raise
                # drain before re-entering backoff: the journal state is
                # then deterministic at every backoff decision, and a
                # failed commit of an EARLIER chunk takes precedence over
                # this chunk's fit-time OOM (it is earlier in the walk)
                err = _drain_for_journal_write()
                if err is not None:
                    lo, chunk = _rollback(err)
                    continue
                if forced:
                    # a torn-shard recompute is pinned to the committed
                    # [lo, hi): halving `chunk` would not shrink the dispatch
                    # (hi stays forced), so retrying is futile — fail with
                    # the actionable cause instead of burning the backoff
                    # budget
                    raise OOMBackoffExceeded(
                        f"recompute of torn-shard chunk [{lo}, {hi}) hit "
                        "RESOURCE_EXHAUSTED; its boundaries are fixed by the "
                        "journal, so backoff cannot help. Free device "
                        "memory, or restart the job under a fresh "
                        "checkpoint_dir (or remove this journal explicitly) "
                        "to let the walk re-chunk."
                    ) from e
                chunk = _record_oom(lo, chunk, e)
                continue
            if forced:  # torn-shard recompute done: restore the recorded walk
                chunk = forced[1]
                lost_boundaries.pop(lo, None)
            if tele:
                tele_chunks.append({"lo": lo, "hi": hi, "phase": phase,
                                    **_span_times(sp)})
            if journal is not None:
                wall_s = round(_time.perf_counter() - t0, 4)
                if committer is not None and not forced:
                    # background commit: the fetch + shard + manifest update
                    # overlap the next chunk's dispatch/compute.  chunk_rows
                    # _after is captured NOW (not at commit time) so the
                    # recorded backoff state matches the serial walk exactly
                    try:
                        committer.submit(lo, hi, piece, wall_s=wall_s,
                                         chunk_rows_after=chunk)
                    except BaseException as se:
                        err = committer.take_error()
                        # only the worker's OWN re-raised error enters the
                        # rollback path: an unrelated exception (e.g. a
                        # Ctrl-C landing while submit blocked) must abort,
                        # not be converted into an OOM retry
                        if err is None or err[0] is not se:
                            raise
                        lo, chunk = _rollback(err)
                        continue
                else:
                    # forced torn-shard recommits stay synchronous: they are
                    # rare, their boundaries are pinned by the journal, and
                    # the serial path keeps their edge semantics exact
                    err = _drain_for_journal_write()
                    if err is not None:
                        lo, chunk = _rollback(err)
                        continue
                    arrays = _commit_arrays(piece)
                    pm = memory_probe.peak_memory()
                    journal.commit_chunk(
                        lo, hi, arrays,
                        wall_s=wall_s,
                        peak_hbm_bytes=pm.bytes,
                        peak_hbm_source=pm.source,
                        chunk_rows_after=chunk,
                        status_counts=status_counts(arrays["status"]),
                    )
            pieces.append((lo, hi, piece))
            lo = hi
    except BaseException:
        if committer is not None:
            # the walk is failing: stop the worker without letting a second
            # (pending) commit error mask the original exception
            committer.close(raise_pending=False)
        if prefetcher is not None:
            prefetcher.close()
        raise
    pipe_stats = committer.close() if committer is not None else None
    pf_stats = prefetcher.close() if prefetcher is not None else None

    # parameter width for synthesized TIMEOUT rows comes from any finished
    # chunk; an all-TIMEOUT job degenerates to a single NaN column
    k = next((int(np.asarray(p.params).shape[-1]) for _, _, p in pieces
              if not isinstance(p, _TimeoutChunk)), 1)
    dtype = np.dtype(str(yb.dtype))

    def _mat(p):
        if isinstance(p, _TimeoutChunk):
            n = p.hi - p.lo
            return (np.full((n, k), np.nan, dtype),
                    np.full(n, np.nan, dtype),
                    np.zeros(n, bool),
                    np.zeros(n, np.int32),
                    np.full(n, FitStatus.TIMEOUT, STATUS_DTYPE))
        return (np.asarray(p.params), np.asarray(p.neg_log_likelihood),
                np.asarray(p.converged), np.asarray(p.iters),
                _piece_status(p))

    mats = [_mat(p) for _, _, p in pieces]
    params = np.concatenate([m[0] for m in mats])
    nll = np.concatenate([m[1] for m in mats])
    conv = np.concatenate([m[2] for m in mats])
    iters = np.concatenate([m[3] for m in mats])
    status = np.concatenate([m[4] for m in mats])

    meta = {
        "chunk_rows_initial": chunk0,
        "chunk_rows_final": chunk,
        "chunks_run": len(pieces),
        "oom_backoffs": len(oom_events),
        "oom_events": oom_events,
        "timeouts": len(timeout_events),
        "timeout_events": timeout_events,
        "degraded": bool(oom_events or timeout_events),
        "status_counts": status_counts(status),
    }
    if journal is not None:
        meta["journal"] = journal.accounting()
    if plan_mode is not None:
        meta["align_mode"] = plan_mode
    if pipe_stats is not None or pf_stats is not None:
        pipe_meta = {}
        if pipe_stats is not None:
            hidden = pipe_stats.hidden_s
            pipe_meta.update({
                "depth": committer.depth,
                "commits_background": pipe_stats.commits,
                "commit_wall_s": round(pipe_stats.commit_wall_s, 6),
                "driver_blocked_s": round(pipe_stats.blocked_s, 6),
                "hidden_commit_s": round(hidden, 6),
                "max_queue_depth": pipe_stats.max_queue_depth,
                # fraction of commit wall the driver never waited for — the
                # number the bench's journaled-vs-unjournaled pair publishes
                "overlap_efficiency": (
                    round(hidden / pipe_stats.commit_wall_s, 4)
                    if pipe_stats.commit_wall_s > 0 else None),
            })
            obs.gauge("committer.hidden_commit_s").set(round(hidden, 6))
            obs.counter("committer.hidden_commit_ms").add(int(hidden * 1000))
        if pf_stats is not None:
            ph = pf_stats.hidden_s
            pipe_meta.update({
                "prefetch_depth": prefetcher.depth,
                "chunks_staged": pf_stats.staged,
                "staged_hits": pf_stats.hits,
                "staged_misses": pf_stats.misses,
                "staged_invalidated": pf_stats.invalidated,
                "staging_wall_s": round(pf_stats.staging_wall_s, 6),
                "staging_blocked_s": round(pf_stats.blocked_s, 6),
                "hidden_staging_s": round(ph, 6),
                # fraction of input-staging wall hidden under compute
                "input_overlap_efficiency": (
                    round(ph / pf_stats.staging_wall_s, 4)
                    if pf_stats.staging_wall_s > 0 else None),
            })
            obs.counter("prefetch.hidden_staging_ms").add(int(ph * 1000))
        # end-to-end: of ALL the overlap-eligible wall (journal commits +
        # input staging), the fraction the driver never waited for — the
        # single number that says "the walk is dispatch-ahead end to end"
        total_wall = ((pipe_stats.commit_wall_s if pipe_stats else 0.0)
                      + (pf_stats.staging_wall_s if pf_stats else 0.0))
        total_hidden = ((pipe_stats.hidden_s if pipe_stats else 0.0)
                        + (pf_stats.hidden_s if pf_stats else 0.0))
        pipe_meta["end_to_end_overlap_efficiency"] = (
            round(total_hidden / total_wall, 4) if total_wall > 0 else None)
        meta["pipeline"] = pipe_meta
    # ladder/sanitize accounting aggregated across chunks (resilient mode)
    rung_totals: dict = {}
    for _, _, p in pieces:
        for r in (getattr(p, "meta", None) or {}).get("ladder", ()):
            agg = rung_totals.setdefault(
                r["rung"], {"attempted": 0, "rescued": 0})
            agg["attempted"] += r["attempted"]
            agg["rescued"] += r["rescued"]
    if rung_totals:
        meta["ladder_totals"] = rung_totals
    if tele:
        for name, v in meta["status_counts"].items():
            if v:
                obs.counter(f"fit_status.{name}").add(v)
        # summary() is None if the plane was disabled mid-run: drop the
        # block entirely rather than crash or journal a null
        extra_tele = {}
        if plan_mode is not None:
            extra_tele["align_mode"] = plan_mode
        if pf_stats is not None:
            # the input-staging overlap numbers ride into the manifest so
            # tools/advise_budget.py can suggest prefetch_depth (and the
            # align hint) for the next run of this config
            extra_tele["input_staging"] = {
                k: meta["pipeline"][k] for k in (
                    "prefetch_depth", "chunks_staged", "staged_hits",
                    "staged_misses", "staging_wall_s", "hidden_staging_s",
                    "input_overlap_efficiency")}
        telemetry = obs.summary(counters_since=counters0, chunks=tele_chunks,
                                **extra_tele)
        if telemetry is not None:
            meta["telemetry"] = telemetry
            if journal is not None:
                journal.record_telemetry(telemetry)
            obs.emit_metrics()
    return ResilientFitResult(params, nll, conv, iters, status, meta)


def _piece_status(p) -> np.ndarray:
    """Status of one chunk result; synthesized when the fit has none."""
    status = getattr(p, "status", None)
    conv = np.asarray(p.converged)
    if status is None:
        finite = np.isfinite(np.asarray(p.params)).all(axis=-1)
        return np.where(conv & finite, FitStatus.OK,
                        FitStatus.DIVERGED).astype(STATUS_DTYPE)
    return np.asarray(status).astype(STATUS_DTYPE)
