"""Chunked fit execution with HBM OOM backoff.

The north-star workload (ROADMAP: 1M series x 1k obs) cannot always fit one
monolithic batch in HBM — and the right chunk size depends on the model,
the dtype, and what else is resident on the chip.  Rather than making the
caller guess, :func:`fit_chunked` walks the panel in row chunks and treats
``RESOURCE_EXHAUSTED`` as a recoverable signal: the chunk size is halved
(bounded retries) and the degradation is recorded in the result metadata,
the batch analog of Spark re-running a too-big task after an executor OOM.

Only allocation failures trigger backoff; every other error propagates
unchanged (halving a chunk cannot fix a shape bug, and silently retrying
would bury it).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .runner import ResilientFitResult, resilient_fit
from .status import STATUS_DTYPE, FitStatus, status_counts

__all__ = ["OOMBackoffExceeded", "is_resource_exhausted", "fit_chunked"]

# substrings the XLA runtime uses for allocation failure; the simulated OOM
# of reliability.faultinject raises with the same marker so tier-1 CPU tests
# drive this path without a real HBM exhaustion
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


class OOMBackoffExceeded(RuntimeError):
    """Raised when the minimum chunk size still exhausts device memory."""


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA RESOURCE_EXHAUSTED-style allocation failures.

    ``jaxlib``'s ``XlaRuntimeError`` subclasses ``RuntimeError``, so the
    check is message-based on RuntimeError/MemoryError rather than pinned
    to a jaxlib exception type that moves between releases.
    """
    if isinstance(e, MemoryError):
        return True
    if not isinstance(e, RuntimeError):
        return False
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def fit_chunked(
    fit_fn: Callable,
    y,
    *,
    chunk_rows: Optional[int] = None,
    min_chunk_rows: int = 256,
    max_backoffs: int = 8,
    resilient: bool = True,
    policy: str = "impute",
    ladder=None,
    **fit_kwargs,
) -> ResilientFitResult:
    """Fit ``y [B, T]`` in row chunks of at most ``chunk_rows``.

    Each chunk runs through :func:`~.runner.resilient_fit` (sanitize +
    retry ladder) unless ``resilient=False``, in which case ``fit_fn`` is
    called directly and per-row status comes from the model's own status
    output.  On a ``RESOURCE_EXHAUSTED`` failure the chunk size halves
    (never below ``min_chunk_rows``) and the chunk is retried, at most
    ``max_backoffs`` times across the whole run; exhausting the budget (or
    OOMing at the floor) raises :class:`OOMBackoffExceeded`.

    ``meta`` records ``chunk_rows_initial`` / ``chunk_rows_final``, every
    backoff event, and ``degraded=True`` whenever a backoff happened — so
    a production driver can see that a run survived by shrinking, not
    just that it finished.
    """
    yb = jnp.asarray(y)
    if yb.ndim != 2:
        raise ValueError(f"fit_chunked expects [batch, time], got {yb.shape}")
    b = yb.shape[0]
    chunk = int(chunk_rows) if chunk_rows else b
    chunk = max(1, min(chunk, b))
    chunk0 = chunk

    pieces = []
    oom_events = []
    lo = 0
    while lo < b:
        hi = min(lo + chunk, b)
        # whole-panel chunk: hand the caller's array through untouched (a
        # slice would be a fresh device buffer — an extra HBM copy, and a
        # miss in the per-array-identity align-mode cache callers pre-warm)
        vals = yb if (lo == 0 and hi == b) else yb[lo:hi]
        try:
            if resilient:
                piece = resilient_fit(
                    fit_fn, vals, policy=policy, ladder=ladder,
                    **fit_kwargs,
                )
            else:
                piece = fit_fn(vals, **fit_kwargs)
        except Exception as e:  # noqa: BLE001 - filtered just below
            if not is_resource_exhausted(e):
                raise
            oom_events.append({
                "at_row": lo, "chunk_rows": chunk,
                "error": f"{type(e).__name__}: {e}"[:200],
            })
            if chunk <= min_chunk_rows or len(oom_events) > max_backoffs:
                raise OOMBackoffExceeded(
                    f"chunk of {chunk} rows still RESOURCE_EXHAUSTED after "
                    f"{len(oom_events)} backoffs (floor {min_chunk_rows})"
                ) from e
            chunk = max(min_chunk_rows, chunk // 2)
            continue
        pieces.append(piece)
        lo = hi

    params = np.concatenate([np.asarray(p.params) for p in pieces])
    nll = np.concatenate([np.asarray(p.neg_log_likelihood) for p in pieces])
    conv = np.concatenate([np.asarray(p.converged) for p in pieces])
    iters = np.concatenate([np.asarray(p.iters) for p in pieces])
    status = np.concatenate([_piece_status(p) for p in pieces])

    meta = {
        "chunk_rows_initial": chunk0,
        "chunk_rows_final": chunk,
        "chunks_run": len(pieces),
        "oom_backoffs": len(oom_events),
        "oom_events": oom_events,
        "degraded": bool(oom_events),
        "status_counts": status_counts(status),
    }
    # ladder/sanitize accounting aggregated across chunks (resilient mode)
    rung_totals: dict = {}
    for p in pieces:
        for r in (getattr(p, "meta", None) or {}).get("ladder", ()):
            agg = rung_totals.setdefault(
                r["rung"], {"attempted": 0, "rescued": 0})
            agg["attempted"] += r["attempted"]
            agg["rescued"] += r["rescued"]
    if rung_totals:
        meta["ladder_totals"] = rung_totals
    return ResilientFitResult(params, nll, conv, iters, status, meta)


def _piece_status(p) -> np.ndarray:
    """Status of one chunk result; synthesized when the fit has none."""
    status = getattr(p, "status", None)
    conv = np.asarray(p.converged)
    if status is None:
        finite = np.isfinite(np.asarray(p.params)).all(axis=-1)
        return np.where(conv & finite, FitStatus.OK,
                        FitStatus.DIVERGED).astype(STATUS_DTYPE)
    return np.asarray(status).astype(STATUS_DTYPE)
