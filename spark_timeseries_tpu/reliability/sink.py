"""Write-back output sink: stream chunk results OUT as durable shards.

``fit_chunked`` / ``forecast_chunked`` historically assembled every
chunk's host arrays and concatenated them into one result — an O(panel)
host allocation that the PR 7 source machinery eliminated on the INPUT
side only.  :class:`WritableChunkSource` closes the output half: each
committed chunk's arrays are handed to a double-buffered background
writer that lands them as ``out_{lo}_{hi}.npz`` shards next to the
journal, through the same ``durable_replace`` tmp→fsync→rename protocol
journal shards use.  A SIGKILL mid-write leaves only a hidden
``.tmp-*`` orphan, which every shard reader already excludes — output
shards get exactly the torn-file rejection input shards have.

The sink is idempotent per span: a resumed walk re-emits its
journal-loaded chunks through the sink, and re-writing a span durably
replaces the same shard with the same bytes — so a killed-and-resumed
sink directory finalizes bitwise-identical to an uninterrupted one.

``finalize(n_rows)`` drains the writer, verifies the recorded spans
tile ``[0, n_rows)`` exactly, deletes orphan shards from an earlier run
on a different chunk grid, and writes a durable ``sink_manifest.json``
naming every shard — the block ``tools/obs_report.py --check``
validates.  Read the results back at O(chunk) host footprint with
``NpzShardSource(directory, key="params")``.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as np

from .journal import _atomic_write_bytes, durable_replace

__all__ = ["SinkError", "WritableChunkSource", "SINK_MANIFEST",
           "SINK_VERSION"]

SINK_MANIFEST = "sink_manifest.json"
SINK_VERSION = 1

_STOP = object()


class SinkError(RuntimeError):
    """A write-back sink failed or finalized over an incomplete walk."""


class _Item:
    __slots__ = ("lo", "hi", "arrays", "nbytes")

    def __init__(self, lo: int, hi: int, arrays: dict, nbytes: int):
        self.lo, self.hi, self.arrays, self.nbytes = lo, hi, arrays, nbytes


class WritableChunkSource:
    """Double-buffered durable writer for one walk's output shards.

    ``write(lo, hi, arrays)`` queues one chunk's host arrays (the
    journal shard schema) for background write; at most ``depth`` chunks
    are in flight, so the sink's host footprint is O(depth × chunk) by
    construction — ``peak_in_flight_bytes`` proves it.  ``write`` blocks
    under backpressure (accounted as ``blocked_s``) and re-raises the
    worker's first error, which is also re-raised at ``finalize``.
    """

    # lock-discipline contract (tools/lint lock-map): shared between the
    # driver/committer thread calling write() and the sink worker.
    _protected_by_ = {
        "_spans": "_lock",
        "_fields": "_lock",
        "_param_width": "_lock",
        "_status_counts": "_lock",
        "_writes": "_lock",
        "_bytes_written": "_lock",
        "_write_wall_s": "_lock",
        "_in_flight_bytes": "_lock",
        "_peak_in_flight_bytes": "_lock",
        "_error": "_lock",
    }

    def __init__(self, directory, *, depth: int = 2):
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.depth = max(1, int(depth))
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._lock = threading.Lock()
        self._spans: dict = {}  # lo -> (hi, shard_name)
        self._fields: Optional[Sequence[str]] = None
        self._param_width: Optional[int] = None
        self._status_counts: dict = {}
        self._writes = 0
        self._bytes_written = 0
        self._write_wall_s = 0.0
        self._blocked_s = 0.0  # driver-only
        self._in_flight_bytes = 0
        self._peak_in_flight_bytes = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="sink-writer")
        self._worker.start()

    # -- worker side --------------------------------------------------------

    def _run(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                self._q.task_done()
                return
            try:
                with self._lock:
                    failed = self._error is not None
                if not failed:
                    self._write_one(item)
            except BaseException as e:  # noqa: BLE001 - re-raised in driver
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                with self._lock:
                    self._in_flight_bytes -= item.nbytes
                self._q.task_done()

    def _shard_name(self, lo: int, hi: int) -> str:
        return f"out_{lo:09d}_{hi:09d}.npz"

    def _write_one(self, item: _Item):
        t0 = time.perf_counter()
        shard = self._shard_name(item.lo, item.hi)
        path = os.path.join(self.directory, shard)
        durable_replace(path, lambda f: np.savez(f, **item.arrays),
                        suffix=".npz")
        status = item.arrays.get("status")
        with self._lock:
            self._spans[item.lo] = (item.hi, shard)
            if self._fields is None:
                self._fields = tuple(sorted(item.arrays))
            params = item.arrays.get("params")
            if self._param_width is None and params is not None \
                    and getattr(params, "ndim", 0) == 2:
                self._param_width = int(params.shape[1])
            if status is not None:
                vals, counts = np.unique(np.asarray(status),
                                         return_counts=True)
                for v, c in zip(vals.tolist(), counts.tolist()):
                    k = str(int(v))
                    self._status_counts[k] = \
                        self._status_counts.get(k, 0) + int(c)
            self._writes += 1
            self._bytes_written += item.nbytes
            self._write_wall_s += time.perf_counter() - t0

    # -- driver side --------------------------------------------------------

    def check(self) -> None:
        """Re-raise the worker's pending error (if any) in the caller."""
        with self._lock:
            err = self._error
        if err is not None:
            raise SinkError(
                f"write-back sink {self.directory} failed: {err}") from err

    @property
    def param_width(self) -> Optional[int]:
        with self._lock:
            return self._param_width

    def write(self, lo: int, hi: int, arrays: dict) -> None:
        """Queue one chunk's host arrays for durable background write.

        Idempotent per ``[lo, hi)``: re-emitting a span (journal resume)
        durably replaces the same shard.  Blocks while ``depth`` writes
        are in flight — the O(chunk) footprint bound."""
        self.check()
        if self._closed:
            raise SinkError("write() on a finalized sink")
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        nbytes = sum(int(v.nbytes) for v in arrays.values())
        item = _Item(int(lo), int(hi), arrays, nbytes)
        with self._lock:
            self._in_flight_bytes += nbytes
            if self._in_flight_bytes > self._peak_in_flight_bytes:
                self._peak_in_flight_bytes = self._in_flight_bytes
        t0 = time.perf_counter()
        while True:
            try:
                self._q.put(item, timeout=0.05)
                break
            except queue.Full:
                try:
                    self.check()  # a failed worker never frees the slot
                except BaseException:
                    with self._lock:
                        self._in_flight_bytes -= nbytes
                    raise
        self._blocked_s += time.perf_counter() - t0

    def barrier(self) -> None:
        """Block until every queued write is durable, then surface any
        worker error."""
        t0 = time.perf_counter()
        self._q.join()
        self._blocked_s += time.perf_counter() - t0
        self.check()

    def discard_from(self, lo: int) -> None:
        """Drop recorded spans at/after ``lo`` (walk rollback): their
        chunks are about to be recomputed on a different grid."""
        self._q.join()
        with self._lock:
            drop = [s for s in self._spans if s >= int(lo)]
            names = [self._spans.pop(s)[1] for s in drop]
        for name in names:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    def finalize(self, n_rows: int) -> dict:
        """Drain, verify the spans tile ``[0, n_rows)``, sweep orphan
        shards from earlier grids, and write ``sink_manifest.json``
        durably.  Returns the accounting dict (also the manifest's
        accounting block)."""
        if not self._closed:
            self._closed = True
            t0 = time.perf_counter()
            self._q.join()
            self._blocked_s += time.perf_counter() - t0
            self._q.put(_STOP)
            self._worker.join(timeout=30.0)
        self.check()
        with self._lock:
            spans = sorted((lo, hi, name)
                           for lo, (hi, name) in self._spans.items())
        pos = 0
        for lo, hi, _name in spans:
            if lo != pos:
                raise SinkError(
                    f"sink {self.directory} has a gap: rows [{pos}, {lo}) "
                    "were never written")
            pos = hi
        if pos != int(n_rows):
            raise SinkError(
                f"sink {self.directory} covers [0, {pos}) but the walk "
                f"spans [0, {n_rows})")
        keep = {name for _lo, _hi, name in spans}
        for fname in os.listdir(self.directory):
            if fname.startswith("out_") and fname.endswith(".npz") \
                    and fname not in keep:
                # an earlier run on a different chunk grid: its spans are
                # fully superseded by this run's verified tiling
                try:
                    os.unlink(os.path.join(self.directory, fname))
                except OSError:
                    pass
        acct = self.accounting()
        manifest = {
            "kind": "sink",
            "sink_version": SINK_VERSION,
            "n_rows": int(n_rows),
            "fields": list(self._fields or ()),
            "shards": [{"name": name, "lo": lo, "hi": hi}
                       for lo, hi, name in spans],
            "accounting": acct,
        }
        _atomic_write_bytes(
            os.path.join(self.directory, SINK_MANIFEST),
            (json.dumps(manifest, indent=1, sort_keys=True) + "\n")
            .encode())
        return acct

    def accounting(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "depth": self.depth,
                "writes": self._writes,
                "spans": len(self._spans),
                "bytes_written": int(self._bytes_written),
                "write_wall_s": round(self._write_wall_s, 6),
                "blocked_s": round(self._blocked_s, 6),
                "peak_in_flight_bytes": int(self._peak_in_flight_bytes),
                "status_counts": dict(self._status_counts),
            }
