"""TimeSeriesPanel — the distributed collection of time series (L3).

TPU-native replacement for the reference's ``TimeSeriesRDD[K]`` (SURVEY.md
Sections 1-3, upstream ``sparkts/TimeSeriesRDD.scala`` — path unverified).
Where the reference stores an ``RDD[(K, Vector)]`` with one broadcast
``DateTimeIndex`` and loops per series inside executor tasks, this class
stores the whole collection as ONE dense device array ``values[keys, time]``
(NaN marks missing), a host-side ``keys`` array, and a shared replicated
index.  The mapping of reference operations:

=====================================  =======================================
reference (Spark)                      here (JAX/TPU)
=====================================  =======================================
``mapSeries(fn)`` per-series loop      ``jax.vmap(fn)`` over the keys axis
ingest ``groupByKey`` shuffle          host scatter by vectorized index lookup
``fill``/``differences``/...           batched L2 kernels (ops.univariate)
``toInstants`` shuffle (transpose)     sharded transpose / XLA all_to_all
``seriesStats`` via StatCounter        NaN-aware vmapped reductions (+psum)
broadcast DateTimeIndex                replicated sharding of index arrays
Spark hash partitioning over keys      ``NamedSharding(mesh, P("series",))``
``saveAsCsv`` + index string header    same persisted formats (CSV / npz)
=====================================  =======================================

A series always lives whole on one chip (the keys axis is the only sharded
axis), preserving the reference's core invariant.  Structural operations that
change the key set (filters, union) are host-side ingest-path code; the hot
path (map_series / fills / model fits) stays on device end to end.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import index as dtix
from . import obs
from .index import DateTimeIndex, DateTimeLike
from .ops import univariate as uv
from .parallel import mesh as meshlib


def _as_key_array(keys: Iterable) -> np.ndarray:
    return np.asarray(list(keys), dtype=object)


def _require_pyarrow():
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - pyarrow is an extra
        raise ImportError(
            "Parquet persistence needs pyarrow (pip install "
            "spark-timeseries-tpu[parquet])"
        ) from e
    return pa, pq


_BATCH_CACHE: Dict = {}
_BATCH_CACHE_MAX = 512
_MISSING = object()  # co_names entry not in fn.__globals__ (builtin/attribute)


class _ArrayIdKey:
    """Identity-based cache key for an immutable ``jax.Array`` captured by a
    kernel (module constant, closure cell, default).  Holding the reference
    pins the id so it cannot be recycled; equality is identity — a REBOUND
    capture produces a different key, while the same array keeps hitting the
    cache (jax arrays are immutable, so identity implies equal contents).

    Memory note (ADVICE round 2): a cache ENTRY retains the captured device
    buffer regardless of this key — the cached compiled program's closure
    (and its traced constants) hold the array strongly — so a weak key here
    would add id-recycling complexity without freeing anything.  Captured-
    panel memory is bounded by ``_BATCH_CACHE_MAX`` FIFO eviction; callers
    holding very large captured panels can ``_BATCH_CACHE.clear()``."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def __hash__(self):
        return object.__hash__(self.arr)

    def __eq__(self, other):
        return isinstance(other, _ArrayIdKey) and self.arr is other.arr


def _hashable(v):
    return _ArrayIdKey(v) if isinstance(v, jax.Array) else v


def _fn_cache_key(fn: Callable):
    """A cache identity for ``fn`` that is stable across textually identical
    lambdas but distinguishes everything the function's behavior can depend
    on: module, qualname, bytecode, consts, defaults, closure values, the
    CURRENT values of referenced globals, and — for bound methods — the
    receiver plus a snapshot of its instance attributes (so mutating the
    receiver after a call cannot serve stale kernels).  Captured ``jax.Array``
    values key by identity (immutable, see ``_ArrayIdKey``); other unhashable
    captures (numpy arrays, lists) or not-yet-assigned cells raise
    (ValueError/TypeError) and the caller compiles uncached."""
    self_obj = getattr(fn, "__self__", None)
    f = getattr(fn, "__func__", fn)
    code = getattr(f, "__code__", None)
    if code is None:  # functools.partial / callables: fall back to the object
        return fn
    cells = tuple(_hashable(c.cell_contents) for c in (f.__closure__ or ()))
    kwdefs = tuple((k, _hashable(v)) for k, v in sorted((f.__kwdefaults__ or {}).items()))
    defaults = tuple(_hashable(v) for v in (f.__defaults__ or ()))
    gl = f.__globals__
    gvals = tuple(_hashable(gl.get(n, _MISSING)) for n in code.co_names)
    if self_obj is None:
        self_key = None
    else:  # snapshot attribute VALUES: obj.c = 5.0 must change the key
        attrs = getattr(self_obj, "__dict__", None)
        self_key = (
            self_obj,
            tuple((k, _hashable(v)) for k, v in sorted(attrs.items()))
            if attrs is not None
            else None,
        )
    return (
        f.__module__, f.__qualname__, code.co_code, code.co_consts,
        code.co_names, defaults, kwdefs, cells, gvals, self_key,
    )


def _cached_batched(fn: Callable, *args) -> Callable:
    """jit(vmap(fn(., *args))) memoized so repeated panel method calls reuse
    one compiled kernel.  The cache keys on the function's bytecode, closure,
    referenced-global values, and defaults rather than its object identity,
    so a fresh-but-identical lambda per call (the natural ``map_series``
    usage) still hits the cache instead of recompiling each time.  Entries
    are inserted only after the first successful call, so untraceable
    functions (e.g. pandas lambdas probing the device path) never occupy
    cache slots."""
    try:
        key = (_fn_cache_key(fn), args)
        hash(key)  # lint: nondet(hashability probe for the in-process cache)
    except (TypeError, ValueError):  # unhashable capture / empty cell: uncached
        key = None
    if key is not None:
        hit = _BATCH_CACHE.get(key)
        if hit is not None:
            obs.counter("panel.map_series.cache_hits").inc()
            return hit
        obs.counter("panel.map_series.cache_misses").inc()
    else:
        obs.counter("panel.map_series.uncached").inc()
    def _scoped_fn(v):
        with jax.named_scope("panel.map_series"):
            return fn(v, *args)

    compiled = jax.jit(jax.vmap(_scoped_fn))
    if key is None:
        return compiled

    @functools.wraps(compiled)
    def call_then_cache(*a, **k):
        out = compiled(*a, **k)  # a tracing failure caches nothing
        if len(_BATCH_CACHE) >= _BATCH_CACHE_MAX:
            _BATCH_CACHE.pop(next(iter(_BATCH_CACHE)))
        _BATCH_CACHE[key] = compiled
        return out

    return call_then_cache


@functools.lru_cache(maxsize=8)
def _fused_fill_linear() -> Callable:
    """Memoized backend-dispatching linear fill (one jitted callable)."""
    return uv.batch_fill("linear")


@functools.lru_cache(maxsize=32)
def _fused_autocorr(num_lags: int) -> Callable:
    """Memoized backend-dispatching autocorrelation (one per lag count)."""
    return uv.batch_autocorr(num_lags)


class TimeSeriesPanel:
    """A collection of series sharing one ``DateTimeIndex``.

    values: ``f32/f64[padded_keys, time]`` device array, NaN = missing.  Rows
    beyond ``n_series`` are NaN padding so the keys axis divides evenly across
    the mesh's ``series`` axis.
    """

    def __init__(
        self,
        index: DateTimeIndex,
        keys: Iterable,
        values,
        *,
        mesh: Optional[Mesh] = None,
        _pad_ok: bool = False,
    ):
        self.index = index
        self.keys = _as_key_array(keys)
        self.mesh = mesh
        vals = jnp.asarray(values)
        if vals.ndim != 2:
            raise ValueError(f"values must be [keys, time], got shape {vals.shape}")
        if not _pad_ok and vals.shape[0] != len(self.keys):
            raise ValueError(
                f"{len(self.keys)} keys but values has {vals.shape[0]} rows"
            )
        if vals.shape[1] != index.size:
            raise ValueError(
                f"index size {index.size} but values has {vals.shape[1]} columns"
            )
        if mesh is not None:
            if meshlib.TIME_AXIS in mesh.axis_names:
                t_shards = mesh.shape[meshlib.TIME_AXIS]
                if vals.shape[1] % t_shards:
                    raise ValueError(
                        f"time axis of length {vals.shape[1]} does not divide across "
                        f"{t_shards} time shards; pad or slice the index to a multiple "
                        f"of {t_shards} (NaN time-padding would corrupt kernels)"
                    )
            n_shards = mesh.shape[meshlib.SERIES_AXIS]
            padded = meshlib.pad_to_multiple(vals.shape[0], n_shards)
            if padded != vals.shape[0]:
                pad = jnp.full((padded - vals.shape[0], vals.shape[1]), jnp.nan, vals.dtype)
                vals = jnp.concatenate([vals, pad], axis=0)
            vals = meshlib.shard_series(vals, mesh)
        self.values = vals

    # -- basics -------------------------------------------------------------

    @property
    def n_series(self) -> int:
        return len(self.keys)

    @property
    def n_time(self) -> int:
        return self.index.size

    @property
    def dtype(self):
        return self.values.dtype

    def series_values(self) -> jax.Array:
        """The unpadded ``[n_series, time]`` view (device array)."""
        return self.values[: self.n_series]

    def __len__(self) -> int:
        return self.n_series

    def __getitem__(self, key) -> jax.Array:
        """Single series by key — ``panel["AAPL"]`` -> ``[time]`` array."""
        locs = np.nonzero(self.keys == key)[0]
        if locs.size == 0:
            raise KeyError(key)
        return self.values[int(locs[0])]

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"TimeSeriesPanel({self.n_series} series x {self.n_time} instants, "
            f"dtype={self.dtype}, mesh={'yes' if self.mesh else 'no'})"
        )

    def _like(self, values, index: Optional[DateTimeIndex] = None, keys=None) -> "TimeSeriesPanel":
        return TimeSeriesPanel(
            index if index is not None else self.index,
            keys if keys is not None else self.keys,
            values,
            mesh=self.mesh,
            _pad_ok=True,
        )

    # -- the hot path -------------------------------------------------------

    def map_series(
        self,
        fn: Callable[[jax.Array], jax.Array],
        new_index: Optional[DateTimeIndex] = None,
    ) -> "TimeSeriesPanel":
        """Apply a ``[time] -> [time']`` kernel to every series.

        The reference's ``mapSeries`` dispatches ``fn`` sequentially per
        series inside executor tasks (SURVEY.md Section 3.2 hot loop #2);
        here it is one vmapped XLA computation over the sharded keys axis —
        with a series-sharded panel this is embarrassingly parallel and
        XLA emits zero collectives.

        Compiled kernels are cached on the function's bytecode, closure and
        referenced-global values (not object identity), so passing a fresh
        but textually identical lambda each call reuses one compiled program;
        kernels whose closures capture unhashable state compile uncached.
        Cache hits/misses feed the telemetry registry
        (``panel.map_series.cache_*``) when ``obs`` is enabled.
        """
        with obs.span("panel.map_series", n_series=self.n_series):
            out = _cached_batched(fn)(self.values)
        idx = new_index if new_index is not None else self.index
        if out.ndim != 2 or out.shape[1] != idx.size:
            raise ValueError(
                f"map_series output shape {out.shape} does not match index size "
                f"{idx.size}; pass new_index= for length-changing transforms"
            )
        return self._like(out, index=idx)

    def to_folded(self):
        """Values in the resident TPU kernel layout (``ops.layout``):
        ``FoldedPanel`` — fold once at the panel boundary, then every
        transform dispatch on it streams at kernel rate with no per-dispatch
        layout transpose.  Pass it to ``ops.univariate.batch_autocorr`` /
        ``batch_fill_linear_chain``; ``ops.unfold_panel`` converts back."""
        from .ops.layout import fold_panel

        return fold_panel(self.series_values())

    def fill(self, method: str, value=None) -> "TimeSeriesPanel":
        # single-host linear fill takes the fused Pallas sweep when the
        # platform supports it (the dispatcher falls back to the vmapped
        # kernel otherwise); sharded panels keep the GSPMD vmap path
        if method == "linear" and self.mesh is None:
            return self._like(_fused_fill_linear()(self.values))
        return self._apply(uv.fillts, method, value)

    def differences(self, lag: int = 1) -> "TimeSeriesPanel":
        return self._apply(uv.differences_at_lag, lag)

    def quotients(self, lag: int = 1) -> "TimeSeriesPanel":
        return self._apply(uv.quotients, lag)

    def return_rates(self, lag: int = 1) -> "TimeSeriesPanel":
        return self._apply(uv.price2ret, lag)

    def _apply(self, kernel: Callable, *args) -> "TimeSeriesPanel":
        return self._like(_cached_batched(kernel, *args)(self.values))

    def autocorr(self, num_lags: int) -> jax.Array:
        """``[n_series, num_lags]`` sample autocorrelations."""
        if self.mesh is None:  # fused single-pass kernel where supported
            out = _fused_autocorr(num_lags)(self.values)
        else:
            out = _cached_batched(uv.autocorr, num_lags)(self.values)
        return out[: self.n_series]

    def pacf(self, num_lags: int) -> jax.Array:
        """``[n_series, num_lags]`` partial autocorrelations (Durbin-Levinson)."""
        out = _cached_batched(uv.pacf, num_lags)(self.values)
        return out[: self.n_series]

    def fit(self, model, *, chunk_rows: Optional[int] = None,
            resilient: bool = True, policy: str = "impute",
            checkpoint_dir: Optional[str] = None, resume: str = "auto",
            chunk_budget_s: Optional[float] = None,
            job_budget_s: Optional[float] = None,
            pipeline: bool = True, pipeline_depth: int = 2,
            prefetch_depth: int = 1, align_mode: Optional[str] = None,
            shard: bool = False, mesh=None, source=None,
            delta_from: Optional[str] = None, delta_warmstart: bool = True,
            **fit_kwargs):
        """Fit a model family over every series via the resilient chunk driver.

        ``model`` is a model-module name (``"arima"``, ``"garch"``,
        ``"ewma"``, ``"holtwinters"``, ``"autoregression"``) or any
        callable ``fit(values, **kwargs) -> FitResult``.  Execution goes
        through ``reliability.fit_chunked``: the panel is fitted in row
        chunks of at most ``chunk_rows`` (default: one chunk) with bounded
        RESOURCE_EXHAUSTED backoff, and — unless ``resilient=False`` —
        each chunk runs the sanitize -> fit -> retry -> fallback ladder
        (``reliability.resilient_fit``) so one poisoned series cannot take
        down the batch.

        ``checkpoint_dir=`` makes the job DURABLE: every finished chunk is
        committed to a write-ahead journal (``reliability.journal``) and a
        restarted call with the same panel/config skips committed chunks,
        producing results bitwise-identical to an uninterrupted run (a
        stale or torn journal is rejected loudly — see
        ``reliability.fit_chunked``).  ``chunk_budget_s`` / ``job_budget_s``
        bound the fit's wall clock: overrunning chunks come back with rows
        flagged ``FitStatus.TIMEOUT`` instead of hanging the job, and are
        retried on the next journaled resume.

        Journaled walks are PIPELINED by default: commits run on a bounded
        background committer (at most ``pipeline_depth`` in flight, in
        order) so the device computes the next chunk while the previous
        chunk's shard and manifest hit disk — bitwise-identical to the
        serial walk, which ``pipeline=False`` restores (see
        ``reliability.fit_chunked``; ``meta["pipeline"]`` reports the
        hidden commit time).  The INPUT side is pipelined too: sliced
        walks compute one static align-mode plan for the whole panel
        (``align_mode=`` pre-supplies it and skips even that probe) and
        stage chunk N+1's device slice on a background prefetcher while
        chunk N computes (``prefetch_depth``, default 1; 0 disables) —
        stage ∥ compute ∥ commit, still bitwise-identical to the serial
        walk.

        ``shard=True`` (or an explicit ``mesh=``) scales the whole walk
        across the device mesh: one journaled prefetch → compute → commit
        lane per series-axis device, bitwise-identical to the
        single-device walk on the same panel, with shard/process 0
        merging the per-shard journals into one job manifest (see
        ``reliability.fit_chunked`` sharded execution).  Sharded walks
        are ELASTIC: a failing lane is retried then quarantined (its
        chunks adopted/recomputed by survivors) and idle lanes steal from
        stragglers — pass ``lane_retries=`` / ``rebalance_threshold=``
        through ``fit_kwargs`` to tune the containment (see
        ``reliability.fit_chunked`` elastic lanes).  Note this is the
        chunk DRIVER's mesh knob, independent of the panel's own
        ``mesh``-attached SPMD fit path.

        ``delta_from=PRIOR_ROOT`` runs an **incremental (delta) refit**
        against a committed journal of an earlier fit of this panel's
        lineage (``reliability.delta``): chunks whose rows are unchanged
        are adopted from the prior journal byte-for-byte (zero compute),
        chunks whose history grew with an identical prefix refit
        warm-started from the journaled params (requires
        ``resilient=False`` and an ``init_params``-capable model — the
        arima family; ``delta_warmstart=False`` refits them cold
        instead, keeping the whole result bitwise vs a from-scratch
        fit), and only revised/new chunks refit in full.  Requires
        ``checkpoint_dir=``; see ``reliability.fit_chunked``.

        ``source=`` opts the walk into **host-resident execution** for
        panels larger than device memory (``reliability.source``): pass a
        host ``np.ndarray``, an npz shard directory path, or any
        ``ChunkSource`` holding THIS panel's values — shape must match —
        and the walk stages each chunk H2D through a reusable staging
        pool instead of requiring the panel resident in HBM, with results
        bitwise-identical to the in-HBM walk.  The panel's own (device)
        values are then never touched; construct such a panel with a
        cheap placeholder or use ``reliability.fit_chunked`` directly.

        Returns a ``reliability.ResilientFitResult`` whose rows align with
        ``self.keys``; ``.status`` carries per-series ``FitStatus`` codes
        and ``.meta`` the chunk/ladder/journal accounting.  This is the
        north-star serving entry point: the batch analog of the reference
        mapping ``fitModel`` over an RDD under Spark task retry — with the
        journal standing in for RDD lineage.
        """
        if callable(model):
            fit_fn = model
        else:
            from . import models as _models

            mod = getattr(_models, model, None)
            if mod is None or not hasattr(mod, "fit"):
                raise ValueError(f"unknown model {model!r}")
            fit_fn = mod.fit
        from .reliability import fit_chunked

        if source is not None:
            from .reliability import source as source_mod

            src = source_mod.as_source(source)
            if tuple(src.shape) != (int(self.n_series), int(self.n_time)):
                raise ValueError(
                    f"source shape {src.shape} does not match this panel "
                    f"({self.n_series} series x {self.n_time} obs); the "
                    "source must hold the panel's own values")
            values = src
        else:
            values = self.series_values()
        model_name = (model if isinstance(model, str)
                      else getattr(model, "__qualname__", repr(model)))
        with obs.span("panel.fit", model=model_name, n_series=self.n_series):
            return fit_chunked(
                fit_fn, values, chunk_rows=chunk_rows,
                resilient=resilient, policy=policy,
                checkpoint_dir=checkpoint_dir, resume=resume,
                chunk_budget_s=chunk_budget_s, job_budget_s=job_budget_s,
                pipeline=pipeline, pipeline_depth=pipeline_depth,
                prefetch_depth=prefetch_depth, align_mode=align_mode,
                shard=shard, mesh=mesh,
                delta_from=delta_from, delta_warmstart=delta_warmstart,
                **fit_kwargs,
            )

    def auto_fit(self, orders=None, *, criterion: str = "aicc",
                 include_intercept: bool = True, stage2: str = "full",
                 stage1_iters: int = 12,
                 chunk_rows: Optional[int] = None,
                 resilient: bool = False, policy: str = "impute",
                 checkpoint_dir: Optional[str] = None, resume: str = "auto",
                 chunk_budget_s: Optional[float] = None,
                 job_budget_s: Optional[float] = None,
                 pipeline: bool = True, pipeline_depth: int = 2,
                 prefetch_depth: int = 1, align_mode: Optional[str] = None,
                 shard: bool = False, mesh=None, source=None,
                 **fit_kwargs):
        """Batched ARIMA/SARIMA order search over every series
        (``models.auto.auto_fit`` — ISSUE 9 / ROADMAP item 4).

        Fits a static grid of candidate orders per series (``orders``:
        ``(p, d, q)`` triples, optionally with a seasonal
        ``(P, D, Q, s)`` fourth element; default
        ``models.auto.DEFAULT_ORDERS``), computes ``criterion`` (AICc
        default) per (row, order) on device, and arg-selects per row.
        Every candidate rides the SAME durable chunk driver as
        :meth:`fit` — per-order write-ahead journals under
        ``checkpoint_dir/grid_00000/…`` (SIGKILL anywhere mid-grid and a
        re-run resumes, replaying only uncommitted chunks, with selection
        bitwise-identical to an uninterrupted search), OOM backoff,
        budgets (``job_budget_s`` bounds the WHOLE search), pipelined
        commits/prefetch, mesh sharding (``shard=True``), and
        ``source=`` streaming for larger-than-HBM panels (same contract
        as :meth:`fit`).

        ``stage2="full"`` (default) fully fits every order — selection is
        bitwise-identical to an exhaustive per-order full-fit argmin;
        ``stage2="winners"`` sweeps every order at ``stage1_iters``
        first and spends the full budget only on each row's winning
        order (approximate selection, ~1/G of the full-fit spend).

        Returns a ``models.auto.AutoFitResult`` whose rows align with
        ``self.keys``: ``order_index`` is each series' winning grid
        position and ``meta["auto_fit"]`` the search accounting (orders
        tried, per-order stage-2 spend, selection histogram).
        """
        from .models import auto as _auto

        if source is not None:
            from .reliability import source as source_mod

            src = source_mod.as_source(source)
            if tuple(src.shape) != (int(self.n_series), int(self.n_time)):
                raise ValueError(
                    f"source shape {src.shape} does not match this panel "
                    f"({self.n_series} series x {self.n_time} obs); the "
                    "source must hold the panel's own values")
            values = src
        else:
            values = self.series_values()
        with obs.span("panel.auto_fit", n_series=self.n_series,
                      orders=len(_auto.normalize_orders(orders))):
            return _auto.auto_fit(
                values, orders, criterion=criterion,
                include_intercept=include_intercept, stage2=stage2,
                stage1_iters=stage1_iters, chunk_rows=chunk_rows,
                resilient=resilient, policy=policy,
                checkpoint_dir=checkpoint_dir, resume=resume,
                chunk_budget_s=chunk_budget_s, job_budget_s=job_budget_s,
                pipeline=pipeline, pipeline_depth=pipeline_depth,
                prefetch_depth=prefetch_depth, align_mode=align_mode,
                shard=shard, mesh=mesh, **fit_kwargs)

    def forecast(self, model, horizon, fitted, *, status=None,
                 intervals: bool = False, level: float = 0.9,
                 n_samples: int = 256, seed: Optional[int] = None,
                 chunk_rows: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None, resume: str = "auto",
                 chunk_budget_s: Optional[float] = None,
                 job_budget_s: Optional[float] = None,
                 pipeline: bool = True, pipeline_depth: int = 2,
                 prefetch_depth: int = 1, shard: bool = False, mesh=None,
                 source=None, _journal_commit_hook=None, **model_kwargs):
        """Forecast ``horizon`` steps for every series via the chunked
        forecast walk (``forecasting.forecast_chunked`` — ROADMAP item 2).

        ``model`` is a forecast-capable model name (``"arima"``,
        ``"autoregression"``, ``"ewma"``, ``"holtwinters"``,
        ``"garch"``); ``model_kwargs`` its structural config (e.g.
        ``order=(1, 1, 1)``).  ``fitted`` supplies the per-row params:
        the fit result a previous :meth:`fit` returned, a raw
        ``[n_series, k]`` array, or a PATH to a fit walk's journal
        (fit once on disk, forecast many later — committed rows load
        byte-identical to the original fit).  An :meth:`auto_fit`
        SELECTION is rejected (each row's params follow its own winning
        order's layout) — forecast it with
        ``forecasting.ensemble_forecast(auto_root=..., temperature=0)``
        instead.  Rows whose fit failed forecast NaN and keep their
        ``FitStatus``, never garbage.

        The walk rides the SAME durable chunk driver as :meth:`fit`:
        ``checkpoint_dir=`` journals forecast chunks (SIGKILL-resume
        replays only uncommitted chunks, bitwise), ``shard=True`` runs
        one elastic lane per mesh device, ``source=`` streams a
        larger-than-HBM panel, and every composition is
        bitwise-identical to the serial in-memory walk on the same chunk
        grid.  ``intervals=True`` adds Monte-Carlo ``level`` quantile
        bands whose sampling keys are counter-based per GLOBAL row
        (reproducible bitwise across runs/resumes/shards).

        Returns a ``forecasting.ForecastResult`` whose rows align with
        ``self.keys``.
        """
        from . import forecasting as _forecasting

        if source is not None:
            from .reliability import source as source_mod

            src = source_mod.as_source(source)
            if tuple(src.shape) != (int(self.n_series), int(self.n_time)):
                raise ValueError(
                    f"source shape {src.shape} does not match this panel "
                    f"({self.n_series} series x {self.n_time} obs); the "
                    "source must hold the panel's own values")
            values = src
        else:
            values = self.series_values()
        return _forecasting.forecast_chunked(
            model, fitted, values, horizon,
            model_kwargs=model_kwargs, status=status,
            intervals=intervals, level=level, n_samples=n_samples,
            seed=seed, chunk_rows=chunk_rows,
            checkpoint_dir=checkpoint_dir, resume=resume,
            chunk_budget_s=chunk_budget_s, job_budget_s=job_budget_s,
            pipeline=pipeline, pipeline_depth=pipeline_depth,
            prefetch_depth=prefetch_depth, shard=shard, mesh=mesh,
            _journal_commit_hook=_journal_commit_hook)

    def backtest(self, model, horizon, *, checkpoint_dir: Optional[str] = None,
                 **backtest_kwargs):
        """Rolling-origin backtest campaign over this panel
        (``forecasting.run_backtest``): expanding-window refits x a
        ``horizon`` sweep as ONE journaled campaign, warm-started
        window-to-window, with MAE/RMSE/MAPE/coverage in a durable
        ``backtest_manifest.json`` — SIGKILL-resumable to
        bitwise-identical metrics.  See ``forecasting.run_backtest``
        for the knobs."""
        from . import forecasting as _forecasting

        return _forecasting.run_backtest(
            self.series_values(), model, horizon,
            checkpoint_dir=checkpoint_dir, **backtest_kwargs)

    def lags(self, max_lag: int, include_original: bool = True,
             lagged_key: Callable[[object, int], object] = None) -> "TimeSeriesPanel":
        """Panel of lagged copies of every series — the upstream
        ``TimeSeries.lags(maxLag, includeOriginals, laggedKey)`` feature-matrix
        builder, panel-shaped: output rows are ``key`` (if
        ``include_original``) followed by ``lag1(key) .. lagN(key)`` for each
        input key; lagged rows lead with NaNs.
        """
        if lagged_key is None:
            lagged_key = lambda k, i: f"lag{i}({k})"
        ks = range(0 if include_original else 1, max_lag + 1)
        # [n, time, len(ks)] -> [n, len(ks), time]; module-level kernel so the
        # compiled-executable cache hits across calls
        out = _cached_batched(uv.lags, max_lag, include_original)(
            self.series_values()
        ).transpose(0, 2, 1)
        new_keys = [lagged_key(k, i) if i else k for k in self.keys for i in ks]
        return TimeSeriesPanel(
            self.index, new_keys, out.reshape(-1, self.n_time), mesh=self.mesh
        )

    # -- time-axis restructuring -------------------------------------------

    def slice(self, start: DateTimeLike, end: DateTimeLike) -> "TimeSeriesPanel":
        lo, hi = self.index.loc_range(start, end)
        return self.islice(lo, hi)

    def islice(self, start: int, end: int) -> "TimeSeriesPanel":
        return self._like(self.values[:, start:end], index=self.index.islice(start, end))

    def with_index(self, new_index: DateTimeIndex, how: str = "nan") -> "TimeSeriesPanel":
        """Reindex onto ``new_index``: positions present in both indices are
        copied; new positions are NaN (``how="nan"``) — the upstream
        ``withIndex`` contract.  Chain ``.fill(...)`` for other semantics."""
        if how != "nan":
            raise ValueError(f"unsupported how={how!r}; reindex then .fill(...)")
        locs = self.index.locs_at_datetimes(new_index.instants())  # [new_time]
        hit = locs >= 0
        gathered = self.values[:, np.maximum(locs, 0)]
        out = jnp.where(jnp.asarray(hit)[None, :], gathered, jnp.nan)
        return self._like(out, index=new_index)

    def remove_instants_with_nans(self) -> "TimeSeriesPanel":
        """Drop time positions where ANY series is NaN (host-side dynamic
        shape — upstream ``removeInstantsWithNaNs``)."""
        col_ok = np.asarray(
            jax.jit(lambda v: ~jnp.any(jnp.isnan(v[: self.n_series]), axis=0))(self.values)
        )
        keep = np.nonzero(col_ok)[0]
        new_index = dtix.IrregularDateTimeIndex(self.index.instants()[keep])
        return self._like(self.values[:, jnp.asarray(keep)], index=new_index)

    # -- key-axis restructuring (host-side ingest-path ops) -----------------

    def filter_keys(self, predicate: Callable[[object], bool]) -> "TimeSeriesPanel":
        mask = np.array([bool(predicate(k)) for k in self.keys])
        return self._select_rows(np.nonzero(mask)[0])

    def select(self, keys: Sequence) -> "TimeSeriesPanel":
        pos = {k: i for i, k in enumerate(self.keys)}
        missing = [k for k in keys if k not in pos]
        if missing:
            raise KeyError(f"keys not in panel: {missing[:5]}")
        return self._select_rows(np.array([pos[k] for k in keys], dtype=np.int64))

    def _select_rows(self, rows: np.ndarray) -> "TimeSeriesPanel":
        vals = self.series_values()[jnp.asarray(rows)] if rows.size else jnp.zeros(
            (0, self.n_time), self.dtype
        )
        return TimeSeriesPanel(self.index, self.keys[rows], vals, mesh=self.mesh)

    def filter_starting_before(self, dt: DateTimeLike) -> "TimeSeriesPanel":
        """Keep series whose first observation is at or before ``dt``."""
        cutoff = self.index.insertion_loc(dt)
        first = np.asarray(jax.jit(jax.vmap(uv.first_not_nan_loc))(self.series_values()))
        return self._select_rows(np.nonzero(first < cutoff)[0])

    def filter_ending_after(self, dt: DateTimeLike) -> "TimeSeriesPanel":
        """Keep series whose last observation is at or after ``dt``."""
        if dtix.to_nanos(dt) > dtix.to_nanos(self.index.last):
            return self._select_rows(np.array([], dtype=np.int64))
        lo = self.index.loc_at_or_after(dt)
        last = np.asarray(jax.jit(jax.vmap(uv.last_not_nan_loc))(self.series_values()))
        return self._select_rows(np.nonzero(last >= lo)[0])

    def union(self, other: "TimeSeriesPanel") -> "TimeSeriesPanel":
        if self.index != other.index:
            raise ValueError("union requires identical indices")
        keys = np.concatenate([self.keys, other.keys])
        vals = jnp.concatenate([self.series_values(), other.series_values()], axis=0)
        return TimeSeriesPanel(self.index, keys, vals, mesh=self.mesh)

    # -- aggregates and exits ----------------------------------------------

    def series_stats(self) -> Dict[str, jax.Array]:
        """NaN-aware per-series stats — upstream ``seriesStats`` (StatCounter
        per series).  Returns ``[n_series]`` arrays."""

        def stats(v):
            valid = ~jnp.isnan(v)
            n = jnp.sum(valid)
            vz = jnp.where(valid, v, 0.0)
            mean = jnp.sum(vz) / jnp.maximum(n, 1)
            var = jnp.sum(jnp.where(valid, (v - mean) ** 2, 0.0)) / jnp.maximum(n - 1, 1)
            return {
                "count": n,
                "mean": mean,
                "stdev": jnp.sqrt(var),
                "min": jnp.nanmin(v),
                "max": jnp.nanmax(v),
            }

        out = jax.jit(jax.vmap(stats))(self.values)
        return {k: v[: self.n_series] for k, v in out.items()}

    def to_instants(self) -> Tuple[np.ndarray, jax.Array]:
        """Time-major view: ``(datetimes[time], values[time, n_series])``.

        The reference implements this as a full cluster shuffle (SURVEY.md
        Section 3.4); here it is one transpose that XLA lowers to an
        ``all_to_all`` over ICI when the panel is mesh-sharded AND the time
        axis divides evenly across the mesh's series shards.  When it does
        not divide, the result stays sharded over the (now-column) series
        axis instead — functionally identical, no re-shard collective.
        """
        vals = jax.jit(lambda v: v[: self.n_series].T)(self.values)
        if self.mesh is not None:
            n_shards = self.mesh.shape[meshlib.SERIES_AXIS]
            if vals.shape[0] % n_shards == 0:
                vals = jax.device_put(vals, meshlib.instant_sharding(self.mesh))
        return self.index.datetimes(), vals

    def to_row_matrix(self) -> jax.Array:
        """``[time, n_series]`` instant-major matrix — the named analog of the
        reference's ``toRowMatrix`` (MLlib RowMatrix whose rows are instants).
        Same data as :meth:`to_instants` without the datetimes."""
        return self.to_instants()[1]

    def to_indexed_row_matrix(self) -> Tuple[np.ndarray, jax.Array]:
        """``(row_indices[time], values[time, n_series])`` — the reference's
        ``toIndexedRowMatrix``: each row is an instant tagged with its integer
        location on the index."""
        return np.arange(self.n_time), self.to_instants()[1]

    def to_instants_dataframe(self):
        import pandas as pd

        dts, vals = self.to_instants()
        return pd.DataFrame(np.asarray(vals), index=pd.DatetimeIndex(dts), columns=list(self.keys))

    def to_observations_dataframe(self, ts_col="timestamp", key_col="key", value_col="value"):
        """Long-format (timestamp, key, value) rows, NaNs dropped — the
        inverse of ``from_observations``."""
        import pandas as pd

        vals = np.asarray(self.series_values())
        kidx, tidx = np.nonzero(~np.isnan(vals))
        return pd.DataFrame(
            {
                ts_col: self.index.datetimes()[tidx],
                key_col: self.keys[kidx],
                value_col: vals[kidx, tidx],
            }
        )

    def to_pandas(self):
        """Series-major DataFrame: rows = keys, columns = datetimes."""
        import pandas as pd

        return pd.DataFrame(
            np.asarray(self.series_values()),
            index=list(self.keys),
            columns=pd.DatetimeIndex(self.index.datetimes()),
        )

    # -- persistence --------------------------------------------------------

    def save_csv(self, path: str) -> None:
        """One line per series: ``key,indexString`` header convention of the
        reference's ``saveAsCsv``: every line is ``key,v0,v1,...`` and the
        first line carries the encoded index.

        Persistence coerces keys to ``str`` — a load round-trip yields string
        keys.  Keys containing ',' are rejected (they would corrupt rows).
        """
        if any("," in str(k) for k in self.keys):
            raise ValueError("CSV persistence does not support keys containing ','")
        vals = np.asarray(self.series_values())
        with open(path, "w") as f:
            f.write(f"# index: {self.index.to_string()}\n")
            for k, row in zip(self.keys, vals):
                f.write(str(k) + "," + ",".join(repr(float(v)) for v in row) + "\n")

    @staticmethod
    def load_csv(path: str, mesh: Optional[Mesh] = None) -> "TimeSeriesPanel":
        with open(path) as f:
            header = f.readline()
            if not header.startswith("# index: "):
                raise ValueError(f"{path} missing '# index:' header")
            index = dtix.from_string(header[len("# index: ") :].strip())
            keys, rows = [], []
            for line in f:
                parts = line.rstrip("\n").split(",")
                keys.append(parts[0])
                rows.append([float(v) for v in parts[1:]])
        return TimeSeriesPanel(index, keys, np.asarray(rows), mesh=mesh)

    def save(self, path: str) -> None:
        """Binary checkpoint (npz): values + keys + index string."""
        np.savez_compressed(
            path,
            values=np.asarray(self.series_values()),
            keys=np.asarray([str(k) for k in self.keys]),
            index=self.index.to_string(),
        )

    @staticmethod
    def load(path: str, mesh: Optional[Mesh] = None) -> "TimeSeriesPanel":
        if not path.endswith(".npz") and not os.path.exists(path):
            path = path + ".npz"
        z = np.load(path, allow_pickle=False)
        return TimeSeriesPanel(
            dtix.from_string(str(z["index"])), list(z["keys"]), z["values"], mesh=mesh
        )

    def save_parquet(self, path: str, *, row_group_series: int = 16384) -> None:
        """Columnar checkpoint via Arrow/Parquet (the reference's
        ``saveAsParquetDataFrame`` / ``timeSeriesRDDFromParquet`` pair —
        SURVEY.md §2.1 TimeSeriesRDD row).

        Layout is SERIES-major — one row per series, schema
        ``key: string, values: fixed_size_list<float>[n_time]`` with the
        encoded ``DateTimeIndex`` in the file metadata — not the reference's
        instant-major DataFrame: a million-series panel would need a million
        Parquet columns instant-major, while series-major rows write
        incrementally in row groups of ``row_group_series``, so Arrow-side
        memory stays one row group beyond the single host copy of the panel.
        Keys are coerced to ``str`` (same contract as ``save_csv``).
        """
        pa, pq = _require_pyarrow()
        vals = np.asarray(self.series_values())
        t = vals.shape[1]
        schema = pa.schema(
            [("key", pa.string()), ("values", pa.list_(pa.from_numpy_dtype(vals.dtype), t))],
            metadata={
                b"spark_timeseries_tpu.index": self.index.to_string().encode(),
                b"spark_timeseries_tpu.version": b"1",
            },
        )
        with pq.ParquetWriter(path, schema) as writer:
            for lo in range(0, vals.shape[0], row_group_series):
                chunk = vals[lo : lo + row_group_series]
                arr = pa.FixedSizeListArray.from_arrays(
                    pa.array(chunk.reshape(-1)), t
                )
                keys = pa.array(
                    [str(k) for k in self.keys[lo : lo + row_group_series]],
                    pa.string(),
                )
                writer.write_table(
                    pa.Table.from_arrays([keys, arr], schema=schema)
                )

    @staticmethod
    def load_parquet(path: str, mesh: Optional[Mesh] = None) -> "TimeSeriesPanel":
        """Load a :meth:`save_parquet` checkpoint (round-trips keys as str,
        values bit-exact, and the index through its string codec)."""
        pa, pq = _require_pyarrow()
        table = pq.read_table(path)
        meta = table.schema.metadata or {}
        enc = meta.get(b"spark_timeseries_tpu.index")
        if enc is None:
            raise ValueError(
                f"{path} is not a spark_timeseries_tpu panel checkpoint "
                "(missing index metadata)"
            )
        index = dtix.from_string(enc.decode())
        vtype = table.schema.field("values").type
        t = vtype.list_size
        n = len(table)
        if n:
            col = table.column("values").combine_chunks()
            vals = np.asarray(col.flatten()).reshape(n, t)
        else:
            vals = np.empty((0, t), np.dtype(vtype.value_type.to_pandas_dtype()))
        keys = table.column("key").to_pylist()
        return TimeSeriesPanel(index, keys, vals, mesh=mesh)

    # -- resharding ---------------------------------------------------------

    def with_mesh(self, mesh: Optional[Mesh]) -> "TimeSeriesPanel":
        return TimeSeriesPanel(self.index, self.keys, self.series_values(), mesh=mesh)


# ---------------------------------------------------------------------------
# Ingest
# ---------------------------------------------------------------------------


def from_observations(
    index: DateTimeIndex,
    keys,
    timestamps,
    values,
    *,
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
    strict: bool = False,
) -> TimeSeriesPanel:
    """Build a panel from long-format observation triples.

    Replaces the reference's ``timeSeriesRDDFromObservations`` groupByKey
    shuffle (SURVEY.md Section 3.1) with a host-side vectorized scatter:
    timestamps -> positions via one ``searchsorted``-style lookup, keys ->
    rows via factorization, then one ``values[rows, locs] = v`` write.

    Observations whose timestamp is not on the index raise (``strict=True``)
    or are dropped (default).  The resulting panel's keys are SORTED
    (lexicographically for strings) — align downstream arrays with
    ``panel.keys``, not with insertion order.
    """
    keys = _as_key_array(keys)
    vals = np.asarray(values, dtype=np.float64)
    locs = index.locs_at_datetimes(timestamps)
    uniq, rows = np.unique(keys, return_inverse=True)
    ok = locs >= 0
    if strict and not ok.all():
        bad = np.nonzero(~ok)[0][:5]
        raise ValueError(f"{(~ok).sum()} observations not on the index, e.g. rows {bad}")
    panel = np.full((len(uniq), index.size), np.nan, dtype=np.float64)
    panel[rows[ok], locs[ok]] = vals[ok]
    return TimeSeriesPanel(index, uniq, jnp.asarray(panel, dtype=dtype), mesh=mesh)


def from_dataframe(
    df,
    index: Optional[DateTimeIndex] = None,
    *,
    ts_col: str = "timestamp",
    key_col: str = "key",
    value_col: str = "value",
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
) -> TimeSeriesPanel:
    """Panel from a long-format pandas DataFrame.  If ``index`` is None an
    irregular index over the distinct timestamps is built."""
    ts = df[ts_col].to_numpy()
    if index is None:
        index = dtix.IrregularDateTimeIndex(np.unique(dtix.to_nanos_array(ts)))
    return from_observations(
        index, df[key_col].to_numpy(), ts, df[value_col].to_numpy(), mesh=mesh, dtype=dtype
    )


def from_series_dict(
    series: Dict[object, np.ndarray],
    index: DateTimeIndex,
    *,
    mesh: Optional[Mesh] = None,
    dtype=jnp.float32,
) -> TimeSeriesPanel:
    keys = list(series.keys())
    vals = np.stack([np.asarray(series[k], dtype=np.float64) for k in keys])
    return TimeSeriesPanel(index, keys, jnp.asarray(vals, dtype=dtype), mesh=mesh)
