"""Drop-in compatibility layer mirroring the reference's Python API.

``spark_timeseries_tpu.compat.sparkts`` exposes the upstream
``python/sparkts`` surface (SURVEY.md §2.3): ``time_series_rdd_from_observations``,
a ``TimeSeriesRDD`` wrapper, ``DateTimeIndex`` factories, and
``Model.fit_model(...)`` classes — implemented on the TPU-native core, with
no Spark, Py4J, or JVM anywhere.
"""

from . import sparkts

__all__ = ["sparkts"]
