"""``sparkts``-shaped API on the TPU-native core.

Mirrors the reference's Python package (upstream ``python/sparkts/`` —
``timeseriesrdd.py``, ``datetimeindex.py``, ``models/`` — paths unverified,
SURVEY.md §2.3).  Where the upstream wrappers forward every call over a Py4J
socket to JVM objects and move data through three serialization hops per
element (SURVEY.md §3.5), these are thin host-side shims over the batched
device kernels: the "RDD" is a :class:`~spark_timeseries_tpu.panel.TimeSeriesPanel`,
``map_series`` is a vmapped XLA computation, and model fits run the whole
collection in one compiled program.

Intentional deltas from upstream:
- no SparkContext / SQLContext arguments anywhere;
- ``map_series`` prefers a JAX ``[time] -> [time']`` kernel (one vmapped XLA
  computation); pandas-Series lambdas — the upstream contract — are supported
  through ``mode="host"`` (or the ``mode="auto"`` fallback) at Python-loop
  speed;
- model wrappers hold device parameter arrays and work on batches too.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import index as dtix
from .. import obs
from .. import panel as panellib
from ..index import DateTimeIndex
from ..models import arima as _arima
from ..models import autoregression as _ar
from ..models import ewma as _ewma
from ..models import garch as _garch
from ..models import holtwinters as _hw
from ..models import regression_arima as _regarima
from ..panel import TimeSeriesPanel
from ..stats import tests as _stats

# ---------------------------------------------------------------------------
# datetimeindex.py surface
# ---------------------------------------------------------------------------

uniform = dtix.uniform
irregular = dtix.irregular
hybrid = dtix.hybrid

BusinessDayFrequency = dtix.BusinessDayFrequency
DayFrequency = dtix.DayFrequency
HourFrequency = dtix.HourFrequency
MinuteFrequency = dtix.MinuteFrequency
SecondFrequency = dtix.SecondFrequency
MonthFrequency = dtix.MonthFrequency
YearFrequency = dtix.YearFrequency
WeekFrequency = dtix.WeekFrequency

from_string = dtix.from_string
uniform_from_interval = dtix.uniform_from_interval


# ---------------------------------------------------------------------------
# timeseriesrdd.py surface
# ---------------------------------------------------------------------------


class TimeSeriesRDD:
    """Upstream ``sparkts.timeseriesrdd.TimeSeriesRDD``, panel-backed.

    One device array replaces the distributed ``RDD[(K, Vector)]``; the
    method names and semantics follow the upstream Python wrapper.
    """

    def __init__(self, panel: TimeSeriesPanel):
        self.panel = panel

    # -- index / keys ----------------------------------------------------

    @property
    def index(self) -> DateTimeIndex:
        return self.panel.index

    def keys(self):
        return list(self.panel.keys)

    def count(self) -> int:
        return self.panel.n_series

    # -- transforms ------------------------------------------------------

    def map_series(self, fn: Callable, dt_index: Optional[DateTimeIndex] = None,
                   mode: str = "auto") -> "TimeSeriesRDD":
        """Apply ``fn`` to every series.

        ``mode="device"``: ``fn`` is a JAX ``[time] -> [time']`` kernel, run
        as one vmapped XLA computation (the fast path).  ``mode="host"``:
        ``fn`` takes and returns a pandas Series (the upstream Python
        contract, SURVEY.md §3.5) and runs in a chunked host loop — complete
        parity, Python-loop speed.  ``mode="auto"`` tries the device path and
        falls back to host with a warning when tracing ``fn`` fails.
        """
        if mode not in ("auto", "device", "host"):
            raise ValueError(f"mode must be auto|device|host, got {mode!r}")
        if mode != "host":
            try:
                return TimeSeriesRDD(self.panel.map_series(fn, dt_index))
            except (TypeError, AttributeError, NotImplementedError):
                # tracing failures only — shape/runtime errors from a
                # traceable fn propagate rather than masquerading as
                # "not traceable" and silently rerouting to the slow path
                if mode == "device":
                    raise
                import warnings

                warnings.warn(
                    "map_series: fn is not JAX-traceable; falling back to the "
                    "host (pandas) path. Pass mode='host' to silence "
                    "or mode='device' to raise.",
                    stacklevel=2,
                )
        return self._map_series_host(fn, dt_index)

    def _map_series_host(self, fn: Callable, dt_index: Optional[DateTimeIndex]
                         ) -> "TimeSeriesRDD":
        import pandas as pd

        idx = self.panel.index
        out_index = dt_index if dt_index is not None else idx
        dts = pd.DatetimeIndex(idx.datetimes())
        vals = np.asarray(self.panel.series_values())
        rows = [
            np.asarray(fn(pd.Series(row, index=dts)), dtype=vals.dtype)
            for row in vals
        ]
        out = np.stack(rows) if rows else vals[:0]
        if out.shape[1] != out_index.size:
            raise ValueError(
                f"host map_series output length {out.shape[1]} does not match "
                f"index size {out_index.size}; pass dt_index= for "
                "length-changing transforms"
            )
        return TimeSeriesRDD(
            panellib.TimeSeriesPanel(
                out_index, list(self.panel.keys), out, mesh=self.panel.mesh
            )
        )

    def fill(self, method: str) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.fill(method))

    def differences(self, n: int = 1) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.differences(n))

    def quotients(self, n: int = 1) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.quotients(n))

    def return_rates(self) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.return_rates())

    def slice(self, start, end) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.slice(start, end))

    def with_index(self, new_index: DateTimeIndex) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.with_index(new_index))

    def remove_instants_with_nans(self) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.remove_instants_with_nans())

    def filter(self, predicate) -> "TimeSeriesRDD":
        return TimeSeriesRDD(self.panel.filter_keys(predicate))

    def find_series(self, key):
        """``[time]`` numpy values for one key (upstream returns a pandas
        Series; use :meth:`to_pandas` for that)."""
        return np.asarray(self.panel[key])

    # -- exits -----------------------------------------------------------

    def collect(self):
        """List of ``(key, np.ndarray[time])`` pairs."""
        vals = np.asarray(self.panel.series_values())
        return list(zip(self.keys(), vals))

    def to_instants(self):
        dts, vals = self.panel.to_instants()
        vals = np.asarray(vals)
        return [(dts[i], vals[i]) for i in range(len(dts))]

    def to_instants_dataframe(self):
        return self.panel.to_instants_dataframe()

    def to_row_matrix(self):
        """``[time, n_series]`` numpy matrix (upstream ``toRowMatrix``)."""
        return np.asarray(self.panel.to_row_matrix())

    def to_indexed_row_matrix(self):
        """``[(loc, row[n_series])]`` pairs (upstream ``toIndexedRowMatrix``)."""
        locs, vals = self.panel.to_indexed_row_matrix()
        vals = np.asarray(vals)
        return [(int(locs[i]), vals[i]) for i in range(len(locs))]

    def to_observations_dataframe(self, ts_col="timestamp", key_col="key",
                                  value_col="value"):
        return self.panel.to_observations_dataframe(ts_col, key_col, value_col)

    def to_pandas(self):
        return self.panel.to_pandas()

    def series_stats(self):
        return self.panel.series_stats()

    def save_as_csv(self, path: str) -> None:
        self.panel.save_csv(path)

    def save_as_parquet_data_frame(self, path: str) -> None:
        """Upstream ``saveAsParquetDataFrame`` analog (series-major Parquet —
        see ``TimeSeriesPanel.save_parquet`` for the layout rationale)."""
        self.panel.save_parquet(path)

    def __len__(self) -> int:
        return self.panel.n_series


def time_series_rdd_from_observations(dt_index: DateTimeIndex, df,
                                      ts_col: str, key_col: str,
                                      val_col: str) -> TimeSeriesRDD:
    """Upstream constructor signature, DataFrame-in, panel-backed-out."""
    return TimeSeriesRDD(
        panellib.from_dataframe(
            df, dt_index, ts_col=ts_col, key_col=key_col, value_col=val_col
        )
    )


def time_series_rdd_from_parquet(path: str) -> TimeSeriesRDD:
    """Upstream ``timeSeriesRDDFromParquet`` analog."""
    return TimeSeriesRDD(TimeSeriesPanel.load_parquet(path))


def time_series_rdd_from_pandas_dataframe(dt_index: DateTimeIndex, df
                                          ) -> TimeSeriesRDD:
    """Wide pandas frame (columns = keys, rows aligned to ``dt_index``)."""
    return TimeSeriesRDD(
        TimeSeriesPanel(dt_index, list(df.columns), jnp.asarray(df.to_numpy().T))
    )


# ---------------------------------------------------------------------------
# models/ surface — Model.fit_model(...) classmethods returning model objects
# ---------------------------------------------------------------------------


def _require_checkpoint_dir(durable_kwargs: dict) -> None:
    """The durability knobs only act through the journaled chunk driver;
    accepting them on the plain path would silently drop an SLO the caller
    believes is armed (and swallow typos)."""
    if durable_kwargs:
        raise TypeError(
            f"{sorted(durable_kwargs)} require checkpoint_dir= (they "
            "configure the journaled chunk driver; without a journal the "
            "plain fit path would silently ignore them)")


def _durable_fit(fit_fn, ts, checkpoint_dir, *, chunk_rows=None,
                 chunk_budget_s=None, job_budget_s=None, resume="auto",
                 pipeline=True, pipeline_depth=2, prefetch_depth=1,
                 align_mode=None, shard=False, mesh=None):
    """Route a compat fit through the journaled chunk driver.

    The upstream Python API ran fits inside Spark tasks, whose lineage
    made a long batch job survive executor loss; ``checkpoint_dir=`` on a
    ``fit_model`` call is the panel-era equivalent — every finished chunk
    is committed to a write-ahead journal (``reliability.journal``) and a
    restarted call with the same data/config skips committed chunks
    (results bitwise-identical to an uninterrupted run).  ``fit_fn`` is a
    keyword-bound partial of the model-module fit so the journal's config
    hash covers the hyperparameters.  Returns the ``[batch?, k]`` params
    with single-series inputs debatched, like the plain path.

    ``pipeline`` / ``pipeline_depth`` control the pipelined committer
    (``reliability.committer``): commits overlap the next chunk's compute
    by default, bitwise-identical to the serial ``pipeline=False`` walk.
    ``prefetch_depth`` (default 1; 0 disables) stages the next chunk's
    device slice while the current one computes, and ``align_mode=``
    pre-supplies the walk's static alignment plan
    (``reliability.fit_chunked`` / ``models.base.resolve_align_mode``).
    ``shard=True`` (or ``mesh=``) scales the walk across the device mesh
    — one journaled lane per series-axis device, bitwise-identical to
    the single-device walk (``reliability.fit_chunked`` sharded
    execution).

    ``ts`` may also be a ``reliability.ChunkSource`` (e.g. a host
    ``np.ndarray`` wrapped in ``HostChunkSource``) or an npz
    shard-directory path (str / ``os.PathLike``, opened via
    ``reliability.as_source``): the walk then runs HOST-RESIDENT,
    staging each chunk H2D through the source's staging pool instead of
    requiring the panel in device memory — the compat caller's
    one-argument opt-in to larger-than-HBM panels.  Plain arrays keep
    today's device-resident path; wrap in a source explicitly to opt a
    resident-sized ndarray into host staging.
    """
    import os as _os

    from .. import reliability as rel

    if isinstance(ts, (rel.ChunkSource, str, _os.PathLike)):
        single = False  # sources are 2-D panels by construction
        yb = rel.as_source(ts)
    else:
        a = jnp.asarray(ts)
        single = a.ndim == 1
        yb = jnp.atleast_2d(a)
    res = rel.fit_chunked(
        fit_fn, yb, chunk_rows=chunk_rows, resilient=False,
        checkpoint_dir=checkpoint_dir, resume=resume,
        chunk_budget_s=chunk_budget_s, job_budget_s=job_budget_s,
        pipeline=pipeline, pipeline_depth=pipeline_depth,
        prefetch_depth=prefetch_depth, align_mode=align_mode,
        shard=shard, mesh=mesh,
    )
    params = jnp.asarray(res.params)
    return params[0] if single else params


class _ModelBase:
    def __init__(self, params):
        self.params = jnp.asarray(params)

    @property
    def coefficients(self) -> np.ndarray:
        return np.asarray(self.params)

    # -- panel forecasting (ISSUE 14) -------------------------------------
    # Subclasses that map onto a forecast-capable model family override
    # ``_forecast_spec`` and inherit the durable panel wrapper: the
    # chunked forecast walk over a WHOLE panel of series sharing this
    # model's per-row params, with the driver's journaling/sharding/
    # streaming knobs riding through (``forecasting.forecast_chunked``).

    def _forecast_spec(self):
        raise NotImplementedError(
            f"{type(self).__name__} has no panel forecast kernel yet")

    def forecast_panel(self, ts, n_future: int, **walk_kwargs):
        """Chunked panel forecast: ``ts [rows, T]`` (array, source, or
        npz shard dir), one row of ``self.params`` per series (a single
        shared param vector is broadcast).  Returns a
        ``forecasting.ForecastResult``; ``checkpoint_dir=`` /
        ``shard=`` / ``intervals=`` etc. ride through to
        ``forecasting.forecast_chunked``."""
        import os as _os

        from .. import forecasting as _forecasting
        from .. import reliability as rel

        if isinstance(ts, (rel.ChunkSource, str, _os.PathLike)):
            yb = rel.as_source(ts)
            rows = int(yb.shape[0])
        else:
            yb = jnp.atleast_2d(jnp.asarray(ts))
            rows = int(yb.shape[0])
        params = np.atleast_2d(np.asarray(self.params))
        if params.shape[0] == 1 and rows > 1:
            params = np.repeat(params, rows, axis=0)
        model, model_kwargs = self._forecast_spec()
        with obs.span("compat.forecast_panel", model=model):
            return _forecasting.forecast_chunked(
                model, params, yb, n_future, model_kwargs=model_kwargs,
                **walk_kwargs)

    # -- persistence -----------------------------------------------------
    # The reference's fitted models are plain serializable case classes
    # (SURVEY.md §5.4); here the analog is an ``.npz`` holding the parameter
    # vector plus each class's hyperparameters.

    def _meta(self) -> dict:
        return {}

    @classmethod
    def _from_saved(cls, params, meta: dict) -> "_ModelBase":
        return cls(params)

    def save(self, path: str) -> None:
        np.savez(_npz_path(path), _class=type(self).__name__,
                 params=np.asarray(self.params), **self._meta())

    @classmethod
    def load(cls, path: str) -> "_ModelBase":
        model = load_model(path)
        if type(model) is not cls:
            raise ValueError(
                f"{path!r} holds a {type(model).__name__}, not a {cls.__name__}"
            )
        return model


def _npz_path(path: str) -> str:
    # np.savez silently appends ".npz"; normalize so save/load agree
    return path if str(path).endswith(".npz") else str(path) + ".npz"


def load_model(path: str) -> "_ModelBase":
    """Load any saved model, dispatching on the class recorded in the file."""
    with np.load(_npz_path(path)) as z:
        name = str(z["_class"])
        klass = globals().get(name)
        if klass is None or not (isinstance(klass, type)
                                 and issubclass(klass, _ModelBase)):
            raise ValueError(f"{path!r} holds unknown model class {name!r}")
        meta = {k: z[k] for k in z.files if k not in ("_class", "params")}
        return klass._from_saved(jnp.asarray(z["params"]), meta)


class ARIMAModel(_ModelBase):
    def __init__(self, p, d, q, params, has_intercept=True):
        super().__init__(params)
        self.p, self.d, self.q = p, d, q
        self.has_intercept = has_intercept

    @property
    def order(self):
        return (self.p, self.d, self.q)

    def _meta(self) -> dict:
        return dict(p=self.p, d=self.d, q=self.q, has_intercept=self.has_intercept)

    @classmethod
    def _from_saved(cls, params, meta):
        return cls(int(meta["p"]), int(meta["d"]), int(meta["q"]), params,
                   bool(meta["has_intercept"]))

    def forecast(self, ts, n_future: int):
        return np.asarray(
            _arima.forecast(self.params, jnp.asarray(ts), self.order, n_future,
                            self.has_intercept)
        )

    def _forecast_spec(self):
        return "arima", {"order": self.order,
                         "include_intercept": self.has_intercept}

    def sample(self, n: int, seed: int = 0):
        return np.asarray(
            _arima.sample(self.params, jax.random.key(seed), n, self.order,
                          self.has_intercept)
        )

    def log_likelihood_css(self, ts) -> float:
        yd = np.diff(np.asarray(ts), n=self.d)
        return -float(
            _arima.css_neg_loglik(self.params, jnp.asarray(yd), self.order,
                                  self.has_intercept)
        )

    def approx_aic(self, ts) -> float:
        yd = np.diff(np.asarray(ts), n=self.d)
        return float(
            _arima.approx_aic(self.params, jnp.asarray(yd), self.order,
                              self.has_intercept)
        )

    def add_time_dependent_effects(self, ts):
        return np.asarray(
            _arima.add_time_dependent_effects(self.params, jnp.asarray(ts),
                                              self.order, self.has_intercept)
        )

    def remove_time_dependent_effects(self, ts):
        return np.asarray(
            _arima.remove_time_dependent_effects(self.params, jnp.asarray(ts),
                                                 self.order, self.has_intercept)
        )

    def is_stationary(self):
        return bool(np.all(_arima.is_stationary(self.params, self.order,
                                                self.has_intercept)))

    def is_invertible(self):
        return bool(np.all(_arima.is_invertible(self.params, self.order,
                                                self.has_intercept)))


class SeasonalARIMAModel(_ModelBase):
    """A seasonal SARIMA winner from :meth:`ARIMA.auto_fit`.

    Holds the selected order, seasonal spec, and fitted parameters
    (layout ``[c?, phi, theta, PHI, THETA]`` — ``models.arima.
    _split_params_seasonal``).  Deliberately NOT an :class:`ARIMAModel`:
    that class's forecast/sample/effects methods split params with the
    non-seasonal layout and difference only ``d`` times, which would
    silently drop the seasonal structure the criterion selected the model
    for.  Seasonal forecasting is a ROADMAP follow-on; until it lands
    these methods raise instead of returning wrong numbers.
    """

    def __init__(self, order, seasonal, params, has_intercept=True):
        super().__init__(params)
        self.order = tuple(int(v) for v in order)
        self.seasonal = tuple(int(v) for v in seasonal)
        self.has_intercept = has_intercept

    def _meta(self) -> dict:
        return dict(order=np.asarray(self.order),
                    seasonal=np.asarray(self.seasonal),
                    has_intercept=self.has_intercept)

    @classmethod
    def _from_saved(cls, params, meta):
        return cls([int(v) for v in meta["order"]],
                   [int(v) for v in meta["seasonal"]], params,
                   bool(meta["has_intercept"]))

    def _not_implemented(self, what: str):
        raise NotImplementedError(
            f"{what} is not implemented for seasonal models yet "
            f"(order {self.order} x {self.seasonal}); the fitted "
            "parameters and the selection criterion are available on "
            ".params / .criterion_value")

    def forecast(self, ts, n_future: int):
        self._not_implemented("forecast")

    def sample(self, n: int, seed: int = 0):
        self._not_implemented("sample")

    def add_time_dependent_effects(self, ts):
        self._not_implemented("add_time_dependent_effects")

    def remove_time_dependent_effects(self, ts):
        self._not_implemented("remove_time_dependent_effects")

    def log_likelihood_css(self, ts) -> float:
        """Concentrated seasonal CSS log-likelihood of ``ts`` under the
        fitted parameters (both differencings applied)."""
        from ..models.arima import (_difference, _difference_seasonal,
                                    sarima_neg_loglik)

        P, D, Q, s = self.seasonal
        yd = jnp.asarray(np.asarray(ts, np.float64))
        yd = _difference(yd, self.order[1])
        yd = _difference_seasonal(yd, D, s)
        return -float(sarima_neg_loglik(
            jnp.asarray(self.params, yd.dtype), yd, self.order,
            self.seasonal, self.has_intercept))


class ARIMA:
    @staticmethod
    def fit_model(p: int, d: int, q: int, ts, include_intercept: bool = True,
                  method: str = "css-cgd", user_init_params=None,
                  checkpoint_dir: Optional[str] = None,
                  align_mode: Optional[str] = None,
                  **durable_kwargs) -> ARIMAModel:
        """``checkpoint_dir=`` journals the fit for crash/preemption resume
        (``reliability.fit_chunked``); ``chunk_rows`` / ``chunk_budget_s``
        / ``job_budget_s`` / ``resume`` / ``pipeline`` /
        ``pipeline_depth`` / ``prefetch_depth`` ride along to the chunk
        driver.  ``align_mode=`` is the static alignment hint
        (``models.base.resolve_align_mode``) — valid with or without a
        journal."""
        with obs.span("compat.fit_model", model="ARIMA"):
            if checkpoint_dir is not None:
                import functools

                params = _durable_fit(
                    functools.partial(_arima.fit, order=(p, d, q),
                                      include_intercept=include_intercept,
                                      method=method,
                                      init_params=user_init_params),
                    ts, checkpoint_dir, align_mode=align_mode,
                    **durable_kwargs)
                return ARIMAModel(p, d, q, params, include_intercept)
            _require_checkpoint_dir(durable_kwargs)
            res = _arima.fit(jnp.asarray(ts), (p, d, q), include_intercept,
                             method=method, init_params=user_init_params,
                             align_mode=align_mode)
            return ARIMAModel(p, d, q, res.params, include_intercept)

    @staticmethod
    def auto_fit(ts, orders=None, criterion: str = "aicc",
                 include_intercept: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 **auto_kwargs):
        """Batched order search (``models.auto.auto_fit``): fit a grid of
        candidate ``(p, d, q)`` (optionally seasonal
        ``(p, d, q, (P, D, Q, s))``) orders and select per series by
        ``criterion`` (AICc default; AIC/BIC).

        The upstream workflow — users looping ``ARIMA.fit_model`` over
        hand-picked orders and comparing ``approx_aic`` — becomes one
        call: the whole grid is fitted through the journaled chunk driver
        (``checkpoint_dir=`` makes the search durable, per-order journals
        under ``grid_00000/…``; every other ``auto_fit`` knob —
        ``stage2``, ``chunk_rows``, ``shard``, budgets — rides through).

        Returns a single model of the winning order for a ``[time]``
        series, or a list of per-series models (``None`` where no
        candidate produced a finite criterion) for a ``[batch, time]``
        panel: an :class:`ARIMAModel` for non-seasonal winners, a
        :class:`SeasonalARIMAModel` for seasonal ones (whose
        forecast-family methods raise until seasonal forecasting lands —
        the non-seasonal layout would silently drop the seasonal terms).
        The underlying ``AutoFitResult`` (selection histogram, criteria,
        per-order spend) rides on each model as ``model.auto_result`` /
        in position via ``result.order_index``.
        """
        from ..models import auto as _auto

        with obs.span("compat.auto_fit", model="ARIMA"):
            a = jnp.asarray(ts)
            single = a.ndim == 1
            res = _auto.auto_fit(
                jnp.atleast_2d(a), orders, criterion=criterion,
                include_intercept=include_intercept,
                checkpoint_dir=checkpoint_dir, **auto_kwargs)
            models = []
            for i, g in enumerate(np.asarray(res.order_index)):
                if g < 0:
                    models.append(None)
                    continue
                spec = res.orders[int(g)]
                p, d, q = spec.order
                k = spec.n_params(include_intercept)
                if spec.seasonal is not None:
                    m = SeasonalARIMAModel(spec.order, spec.seasonal,
                                           res.params[i, :k],
                                           include_intercept)
                else:
                    m = ARIMAModel(p, d, q, res.params[i, :k],
                                   include_intercept)
                    m.seasonal = None
                m.criterion_value = float(res.criterion[i])
                m.auto_result = res
                models.append(m)
            return models[0] if single else models


class ARModel(_ModelBase):
    def __init__(self, params, max_lag: int):
        super().__init__(params)
        self.max_lag = max_lag

    @property
    def c(self) -> float:
        return float(self.params[0])

    def _meta(self) -> dict:
        return dict(max_lag=self.max_lag)

    @classmethod
    def _from_saved(cls, params, meta):
        return cls(params, int(meta["max_lag"]))

    def forecast(self, ts, n_future: int):
        return np.asarray(
            _ar.forecast(self.params, jnp.asarray(ts), self.max_lag, n_future)
        )

    def _forecast_spec(self):
        return "autoregression", {"max_lag": self.max_lag}

    def add_time_dependent_effects(self, ts):
        return np.asarray(
            _ar.add_time_dependent_effects(self.params, jnp.asarray(ts), self.max_lag)
        )

    def remove_time_dependent_effects(self, ts):
        return np.asarray(
            _ar.remove_time_dependent_effects(self.params, jnp.asarray(ts), self.max_lag)
        )


class Autoregression:
    @staticmethod
    def fit_model(ts, max_lag: int = 1, no_intercept: bool = False) -> ARModel:
        with obs.span("compat.fit_model", model="Autoregression"):
            res = _ar.fit(jnp.asarray(ts), max_lag, no_intercept)
            return ARModel(res.params, max_lag)


class EWMAModel(_ModelBase):
    @property
    def smoothing(self) -> float:
        return float(self.params[0])

    def forecast(self, ts, n_future: int):
        return np.asarray(_ewma.forecast(self.params, jnp.asarray(ts), n_future))

    def _forecast_spec(self):
        return "ewma", {}

    def add_time_dependent_effects(self, ts):
        return np.asarray(_ewma.add_time_dependent_effects(self.params, jnp.asarray(ts)))

    def remove_time_dependent_effects(self, ts):
        return np.asarray(_ewma.remove_time_dependent_effects(self.params, jnp.asarray(ts)))


class EWMA:
    @staticmethod
    def fit_model(ts, checkpoint_dir: Optional[str] = None,
                  align_mode: Optional[str] = None,
                  **durable_kwargs) -> EWMAModel:
        with obs.span("compat.fit_model", model="EWMA"):
            if checkpoint_dir is not None:
                return EWMAModel(_durable_fit(_ewma.fit, ts, checkpoint_dir,
                                              align_mode=align_mode,
                                              **durable_kwargs))
            _require_checkpoint_dir(durable_kwargs)
            return EWMAModel(_ewma.fit(jnp.asarray(ts),
                                       align_mode=align_mode).params)


class GARCHModel(_ModelBase):
    @property
    def omega(self) -> float:
        return float(self.params[0])

    @property
    def alpha(self) -> float:
        return float(self.params[1])

    @property
    def beta(self) -> float:
        return float(self.params[2])

    def log_likelihood(self, ts) -> float:
        return float(_garch.log_likelihood(self.params, jnp.asarray(ts)))

    def forecast(self, ts, n_future: int):
        """Variance-path forecast (``models.garch.forecast``): conditional
        variances ``h_{T+1..T+n}`` — GARCH's mean forecast is zero."""
        return np.asarray(_garch.forecast(self.params, jnp.asarray(ts),
                                          n_future))

    def _forecast_spec(self):
        return "garch", {}

    def sample(self, n: int, seed: int = 0):
        return np.asarray(_garch.sample(self.params, jax.random.key(seed), n))

    def variances(self, ts):
        return np.asarray(_garch.variances(self.params, jnp.asarray(ts)))

    def add_time_dependent_effects(self, ts):
        return np.asarray(_garch.add_time_dependent_effects(self.params, jnp.asarray(ts)))

    def remove_time_dependent_effects(self, ts):
        return np.asarray(_garch.remove_time_dependent_effects(self.params, jnp.asarray(ts)))


class GARCH:
    @staticmethod
    def fit_model(ts, checkpoint_dir: Optional[str] = None,
                  align_mode: Optional[str] = None,
                  **durable_kwargs) -> GARCHModel:
        with obs.span("compat.fit_model", model="GARCH"):
            if checkpoint_dir is not None:
                return GARCHModel(_durable_fit(_garch.fit, ts, checkpoint_dir,
                                               align_mode=align_mode,
                                               **durable_kwargs))
            _require_checkpoint_dir(durable_kwargs)
            return GARCHModel(_garch.fit(jnp.asarray(ts),
                                         align_mode=align_mode).params)


class ARGARCHModel(_ModelBase):
    def sample(self, n: int, seed: int = 0):
        return np.asarray(_garch.argarch_sample(self.params, jax.random.key(seed), n))


class ARGARCH:
    @staticmethod
    def fit_model(ts, align_mode: Optional[str] = None) -> ARGARCHModel:
        with obs.span("compat.fit_model", model="ARGARCH"):
            return ARGARCHModel(_garch.fit_argarch(
                jnp.asarray(ts), align_mode=align_mode).params)


class HoltWintersModel(_ModelBase):
    def __init__(self, params, period: int, model_type: str):
        super().__init__(params)
        self.period = period
        self.model_type = model_type

    def _meta(self) -> dict:
        return dict(period=self.period, model_type=self.model_type)

    @classmethod
    def _from_saved(cls, params, meta):
        return cls(params, int(meta["period"]), str(meta["model_type"]))

    def forecast(self, ts, n_future: int):
        return np.asarray(
            _hw.forecast(self.params, jnp.asarray(ts), self.period, n_future,
                         self.model_type)
        )

    def _forecast_spec(self):
        return "holtwinters", {"period": self.period,
                               "model_type": self.model_type}

    def sse(self, ts) -> float:
        return float(_hw.sse(self.params, jnp.asarray(ts), self.period,
                             self.model_type == "multiplicative"))


class HoltWinters:
    @staticmethod
    def fit_model(ts, period: int, model_type: str = "additive",
                  method: str = "BOBYQA",
                  checkpoint_dir: Optional[str] = None,
                  align_mode: Optional[str] = None,
                  **durable_kwargs) -> HoltWintersModel:
        # upstream's only optimizer is BOBYQA; here the bounded problem is
        # solved by sigmoid-transformed L-BFGS, so both names map to it
        if method not in ("BOBYQA", "L-BFGS"):
            raise ValueError(f"unknown method {method!r} (supported: BOBYQA, L-BFGS)")
        with obs.span("compat.fit_model", model="HoltWinters"):
            if checkpoint_dir is not None:
                import functools

                params = _durable_fit(
                    functools.partial(_hw.fit, period=period,
                                      model_type=model_type),
                    ts, checkpoint_dir, align_mode=align_mode,
                    **durable_kwargs)
                return HoltWintersModel(params, period, model_type)
            _require_checkpoint_dir(durable_kwargs)
            res = _hw.fit(jnp.asarray(ts), period, model_type=model_type,
                          align_mode=align_mode)
            return HoltWintersModel(res.params, period, model_type)


class RegressionARIMAModel(_ModelBase):
    def predict(self, X):
        return np.asarray(_regarima.predict(self.params, jnp.asarray(X)))


class RegressionARIMA:
    @staticmethod
    def fit_model(y, X, method: str = "cochrane-orcutt",
                  **kwargs) -> RegressionARIMAModel:
        with obs.span("compat.fit_model", model="RegressionARIMA"):
            res = _regarima.fit(jnp.asarray(y), jnp.asarray(X), method,
                                **kwargs)
            return RegressionARIMAModel(res.params)


# ---------------------------------------------------------------------------
# statistical tests (upstream TimeSeriesStatisticalTests names)
# ---------------------------------------------------------------------------

adftest = _stats.adftest
dwtest = _stats.dwtest
bgtest = _stats.bgtest
bptest = _stats.bptest
lbtest = _stats.lbtest
kpsstest = _stats.kpsstest
