"""Date-time indexing for the TPU-native time-series framework.

This is the L1 layer of the framework: the shared ``DateTimeIndex`` that maps
positions <-> timestamps for every series in a panel, plus the calendar-aware
``Frequency`` hierarchy (duration, calendar-period, and business-day
frequencies).

Reference parity (see SURVEY.md Section 1/2 — upstream paths unverified, the
reference mount was empty):
  - ``com.cloudera.sparkts.DateTimeIndex`` — ``UniformDateTimeIndex``,
    ``IrregularDateTimeIndex``, ``HybridDateTimeIndex``; methods
    ``locAtDateTime``, ``dateTimeAtLoc``, ``slice``, ``islice``,
    ``insertionLoc``, ``size``, ``first``, ``last``; companion factories
    ``uniform``, ``irregular``, ``hybrid``, ``fromString``/``toString``.
  - ``com.cloudera.sparkts.Frequency`` — ``advance``/``difference``;
    ``DayFrequency``, ``BusinessDayFrequency``, ``HourFrequency``, etc.

TPU-first design notes
----------------------
All timestamps are int64 nanoseconds since the Unix epoch, UTC.  Lookups are
vectorized numpy on the host (index construction and ingest are host-side);
the *device-side* representation is ``instants()`` — an ``int64[size]`` array
usable inside jit (``jnp.searchsorted`` for irregular lookup, pure arithmetic
for uniform).  Business-day arithmetic is closed-form vectorized day-of-week
math, never a Python loop (SURVEY.md Section 7 "hard parts").
"""

from __future__ import annotations

import re
from abc import ABC, abstractmethod
from typing import Iterable, List, Sequence, Tuple, Union

import numpy as np

NANOS_PER_SECOND = 1_000_000_000
NANOS_PER_MINUTE = 60 * NANOS_PER_SECOND
NANOS_PER_HOUR = 60 * NANOS_PER_MINUTE
NANOS_PER_DAY = 24 * NANOS_PER_HOUR

DateTimeLike = Union[str, int, np.datetime64, "np.integer"]


def to_nanos(dt: DateTimeLike) -> int:
    """Convert a datetime-like value to int64 nanoseconds since epoch (UTC)."""
    if isinstance(dt, (int, np.integer)):
        return int(dt)
    if isinstance(dt, np.datetime64):
        return int(dt.astype("datetime64[ns]").astype(np.int64))
    if isinstance(dt, str):
        return int(np.datetime64(dt, "ns").astype(np.int64))
    # datetime.datetime and pandas.Timestamp both stringify cleanly
    return int(np.datetime64(dt, "ns").astype(np.int64))


def to_nanos_array(dts: Iterable[DateTimeLike]) -> np.ndarray:
    arr = np.asarray(dts)
    if arr.dtype.kind == "M":
        return arr.astype("datetime64[ns]").astype(np.int64)
    if arr.dtype.kind in "iu":
        return arr.astype(np.int64)
    return np.array([to_nanos(d) for d in arr.ravel()], dtype=np.int64).reshape(arr.shape)


def nanos_to_datetime64(nanos) -> np.ndarray:
    return np.asarray(nanos, dtype=np.int64).view("datetime64[ns]")


def _weekday(nanos) -> np.ndarray:
    """Day of week for nanos timestamps: 0 = Monday ... 6 = Sunday.

    The Unix epoch (1970-01-01) was a Thursday (weekday 3).
    """
    days = np.floor_divide(np.asarray(nanos, dtype=np.int64), NANOS_PER_DAY)
    return ((days + 3) % 7).astype(np.int64)


# ---------------------------------------------------------------------------
# Frequencies
# ---------------------------------------------------------------------------


class Frequency(ABC):
    """A calendar-aware step size between consecutive index positions."""

    @abstractmethod
    def advance(self, nanos, n):
        """Advance timestamp(s) by ``n`` periods (vectorized, n may be array)."""

    @abstractmethod
    def difference(self, nanos1, nanos2):
        """Number of complete periods from ``nanos1`` to ``nanos2`` (floor)."""

    @abstractmethod
    def to_string(self) -> str:
        ...

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}({self.to_string()!r})"

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self.to_string() == other.to_string()

    def __hash__(self) -> int:
        # lint: nondet(in-process dict identity only; never persisted)
        return hash((type(self).__name__, self.to_string()))


class DurationFrequency(Frequency):
    """A fixed-duration frequency expressed in nanoseconds."""

    def __init__(self, nanos: int, label: str | None = None):
        if nanos <= 0:
            raise ValueError(f"frequency duration must be positive, got {nanos}")
        self.nanos = int(nanos)
        self._label = label

    def advance(self, nanos, n):
        return np.asarray(nanos, dtype=np.int64) + np.asarray(n, dtype=np.int64) * self.nanos

    def difference(self, nanos1, nanos2):
        delta = np.asarray(nanos2, dtype=np.int64) - np.asarray(nanos1, dtype=np.int64)
        return np.floor_divide(delta, self.nanos)

    def to_string(self) -> str:
        return self._label if self._label else f"duration {self.nanos}"


class NanosecondFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods, f"nanoseconds {periods}")


class MillisecondFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * 1_000_000, f"milliseconds {periods}")


class SecondFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * NANOS_PER_SECOND, f"seconds {periods}")


class MinuteFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * NANOS_PER_MINUTE, f"minutes {periods}")


class HourFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * NANOS_PER_HOUR, f"hours {periods}")


class DayFrequency(DurationFrequency):
    """Calendar days.  UTC-only framework => a day is exactly 24h."""

    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * NANOS_PER_DAY, f"days {periods}")


class WeekFrequency(DurationFrequency):
    def __init__(self, periods: int = 1):
        self.periods = int(periods)
        super().__init__(periods * 7 * NANOS_PER_DAY, f"weeks {periods}")


class MonthFrequency(Frequency):
    """Calendar months: advance preserves day-of-month, clamped to month end."""

    def __init__(self, periods: int = 1):
        self.periods = int(periods)

    def advance(self, nanos, n):
        nanos = np.asarray(nanos, dtype=np.int64)
        n = np.asarray(n, dtype=np.int64) * self.periods
        dt = nanos_to_datetime64(nanos)
        month0 = dt.astype("datetime64[M]")
        intra = nanos - month0.astype("datetime64[ns]").astype(np.int64)
        newmonth = month0 + n.astype("timedelta64[M]")
        # clamp day-of-month to the target month's length, keep time-of-day
        day_off = np.floor_divide(intra, NANOS_PER_DAY)
        tod = intra - day_off * NANOS_PER_DAY
        month_days = np.floor_divide(
            (newmonth + np.timedelta64(1, "M")).astype("datetime64[ns]").astype(np.int64)
            - newmonth.astype("datetime64[ns]").astype(np.int64),
            NANOS_PER_DAY,
        )
        day_off = np.minimum(day_off, month_days - 1)
        return (
            newmonth.astype("datetime64[ns]").astype(np.int64)
            + day_off * NANOS_PER_DAY
            + tod
        )

    def difference(self, nanos1, nanos2):
        n1 = np.asarray(nanos1, dtype=np.int64)
        n2 = np.asarray(nanos2, dtype=np.int64)
        m1 = nanos_to_datetime64(n1).astype("datetime64[M]").astype(np.int64)
        m2 = nanos_to_datetime64(n2).astype("datetime64[M]").astype(np.int64)
        months = m2 - m1
        # floor: if the target hasn't reached the same intra-month point, back off
        reached = self.advance(n1, np.floor_divide(months, self.periods)) <= n2
        months = np.where(reached, months, months - self.periods)
        return np.floor_divide(months, self.periods)

    def to_string(self) -> str:
        return f"months {self.periods}"


class YearFrequency(MonthFrequency):
    def __init__(self, periods: int = 1):
        super().__init__(periods * 12)
        self.year_periods = int(periods)

    def to_string(self) -> str:
        return f"years {self.year_periods}"


class BusinessDayFrequency(Frequency):
    """Business days, vectorized closed-form day-of-week arithmetic.

    ``first_day_of_week`` follows the reference API (0 = Monday .. 6 =
    Sunday); the week's first five days are business days and its last two
    the weekend, so e.g. ``first_day_of_week=6`` gives a Sunday-Thursday
    business week with a Friday/Saturday weekend.
    """

    def __init__(self, days: int = 1, first_day_of_week: int = 0):
        if not 0 <= int(first_day_of_week) <= 6:
            raise ValueError("first_day_of_week must be in 0..6 (0 = Monday)")
        self.days = int(days)
        self.first_day_of_week = int(first_day_of_week)

    def _to_bday_ordinal(self, nanos) -> Tuple[np.ndarray, np.ndarray]:
        """Map timestamps to (business-day ordinal, intra-day nanos).

        Weekend timestamps map to the preceding last-business-day ordinal at
        end-of-day (intra = NANOS_PER_DAY), so the (ordinal, intra) pair —
        and hence ``difference``/``insertion_loc`` — stays monotone in time:
        a weekend instant sorts after any instant of the last business day
        and before any instant of the next one.
        """
        nanos = np.asarray(nanos, dtype=np.int64)
        days = np.floor_divide(nanos, NANOS_PER_DAY)
        intra = nanos - days * NANOS_PER_DAY
        # epoch day 0 (1970-01-01) is a Thursday (weekday 3, 0=Mon); align
        # week numbers so the first_day_of_week-th weekday starts a week
        shifted = days + 3 - self.first_day_of_week
        weeks = np.floor_divide(shifted, 7)
        wd = shifted - weeks * 7  # 0..6 relative to the week start
        is_weekend = wd > 4
        ordinal = weeks * 5 + np.minimum(wd, 4)
        intra = np.where(is_weekend, NANOS_PER_DAY, intra)
        return ordinal, intra

    def _from_bday_ordinal(self, ordinal, intra) -> np.ndarray:
        ordinal = np.asarray(ordinal, dtype=np.int64)
        weeks = np.floor_divide(ordinal, 5)
        wd = ordinal - weeks * 5
        days = weeks * 7 + wd - 3 + self.first_day_of_week
        return days * NANOS_PER_DAY + np.asarray(intra, dtype=np.int64)

    def advance(self, nanos, n):
        ordinal, intra = self._to_bday_ordinal(nanos)
        return self._from_bday_ordinal(ordinal + np.asarray(n, dtype=np.int64) * self.days, intra)

    def difference(self, nanos1, nanos2):
        o1, i1 = self._to_bday_ordinal(nanos1)
        o2, i2 = self._to_bday_ordinal(nanos2)
        whole = o2 - o1
        # true floor on the intra-day remainder (sign-independent, matching
        # DurationFrequency.difference's floor_divide semantics)
        whole = np.where(i2 < i1, whole - 1, whole)
        return np.floor_divide(whole, self.days)

    def to_string(self) -> str:
        return f"businessDays {self.days} {self.first_day_of_week}"


_FREQ_PARSERS = {
    "nanoseconds": lambda p: NanosecondFrequency(int(p[0])),
    "milliseconds": lambda p: MillisecondFrequency(int(p[0])),
    "seconds": lambda p: SecondFrequency(int(p[0])),
    "minutes": lambda p: MinuteFrequency(int(p[0])),
    "hours": lambda p: HourFrequency(int(p[0])),
    "days": lambda p: DayFrequency(int(p[0])),
    "weeks": lambda p: WeekFrequency(int(p[0])),
    "months": lambda p: MonthFrequency(int(p[0])),
    "years": lambda p: YearFrequency(int(p[0])),
    "businessDays": lambda p: BusinessDayFrequency(int(p[0]), int(p[1]) if len(p) > 1 else 0),
    "duration": lambda p: DurationFrequency(int(p[0])),
}


def frequency_from_string(s: str) -> Frequency:
    parts = s.strip().split(" ")
    name, args = parts[0], parts[1:]
    if name not in _FREQ_PARSERS:
        raise ValueError(f"unknown frequency string: {s!r}")
    return _FREQ_PARSERS[name](args)


# ---------------------------------------------------------------------------
# DateTimeIndex
# ---------------------------------------------------------------------------


class DateTimeIndex(ABC):
    """Maps positions <-> timestamps for every series sharing the index."""

    # -- core protocol ------------------------------------------------------

    @property
    @abstractmethod
    def size(self) -> int:
        ...

    @abstractmethod
    def date_time_at_loc(self, loc: int) -> np.datetime64:
        ...

    @abstractmethod
    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        """Exact location of ``dt``, or -1 if absent."""

    @abstractmethod
    def insertion_loc(self, dt: DateTimeLike) -> int:
        """Location where ``dt`` would be inserted to keep the index sorted
        (first position strictly after ``dt``)."""

    @abstractmethod
    def instants(self) -> np.ndarray:
        """``int64[size]`` nanosecond timestamps — the device-side form."""

    @abstractmethod
    def islice(self, start: int, end: int) -> "DateTimeIndex":
        """Sub-index for positions ``[start, end)``."""

    @abstractmethod
    def to_string(self) -> str:
        ...

    # -- derived ------------------------------------------------------------

    @property
    def first(self) -> np.datetime64:
        return self.date_time_at_loc(0)

    @property
    def last(self) -> np.datetime64:
        return self.date_time_at_loc(self.size - 1)

    def slice(self, start: DateTimeLike, end: DateTimeLike) -> "DateTimeIndex":
        """Sub-index covering ``[start, end]`` (inclusive, as upstream)."""
        lo = self.loc_at_or_after(start)
        hi = self.loc_at_or_before(end)
        return self.islice(lo, hi + 1)

    def loc_range(self, start: DateTimeLike, end: DateTimeLike) -> Tuple[int, int]:
        """Positions ``[lo, hi)`` covering timestamps in ``[start, end]``."""
        lo = self.loc_at_or_after(start)
        hi = self.loc_at_or_before(end)
        return lo, hi + 1

    def loc_at_or_before(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = int(np.searchsorted(self.instants(), nanos, side="right")) - 1
        if loc < 0:
            raise ValueError(f"{dt} precedes the index start {self.first}")
        return loc

    def loc_at_or_after(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = int(np.searchsorted(self.instants(), nanos, side="left"))
        if loc >= self.size:
            raise ValueError(f"{dt} follows the index end {self.last}")
        return loc

    def locs_at_datetimes(self, dts: Iterable[DateTimeLike]) -> np.ndarray:
        """Vectorized exact lookup; -1 where absent.  The ingest hot path."""
        nanos = to_nanos_array(dts)
        inst = self.instants()
        locs = np.searchsorted(inst, nanos, side="left")
        locs_clamped = np.minimum(locs, self.size - 1)
        hit = inst[locs_clamped] == nanos
        return np.where(hit, locs_clamped, -1).astype(np.int64)

    def datetimes(self) -> np.ndarray:
        return nanos_to_datetime64(self.instants())

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, DateTimeIndex)
            and self.size == other.size
            and bool(np.array_equal(self.instants(), other.instants()))
        )

    def __hash__(self) -> int:
        # lint: nondet(in-process dict identity only; never persisted)
        return hash((self.size, self.instants().tobytes()))

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}({self.to_string()!r})"


class UniformDateTimeIndex(DateTimeIndex):
    """``periods`` timestamps starting at ``start``, advancing by ``frequency``.

    For pure-duration frequencies every lookup is O(1) arithmetic (jittable);
    calendar frequencies (months, business days) use the frequency's
    closed-form vectorized advance/difference.
    """

    def __init__(
        self,
        start: DateTimeLike,
        periods: int,
        frequency: Frequency,
        _anchor: Tuple[int, int] | None = None,
    ):
        self.start_nanos = to_nanos(start)
        self.periods = int(periods)
        self.frequency = frequency
        # Calendar frequencies (months, years) clamp day-of-month relative to
        # the anchor date; a sliced sub-index must keep generating timestamps
        # from the ORIGINAL anchor or the clamping re-derives from the new
        # start and timestamps silently shift (e.g. Jan-31-anchored monthly
        # sliced at Feb-29 would yield Mar-29 instead of Mar-31).
        self._anchor_nanos, self._offset = _anchor if _anchor else (self.start_nanos, 0)
        self._instants: np.ndarray | None = None

    @property
    def size(self) -> int:
        return self.periods

    def date_time_at_loc(self, loc: int) -> np.datetime64:
        loc = int(loc)
        if loc < 0:
            loc += self.periods
        return nanos_to_datetime64(
            self.frequency.advance(self._anchor_nanos, self._offset + loc)
        )[()]

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        n = int(self.frequency.difference(self._anchor_nanos, nanos)) - self._offset
        if 0 <= n < self.periods and int(
            self.frequency.advance(self._anchor_nanos, self._offset + n)
        ) == nanos:
            return n
        return -1

    def insertion_loc(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        if nanos < self.start_nanos:
            return 0
        n = int(self.frequency.difference(self._anchor_nanos, nanos)) - self._offset
        return min(n + 1, self.periods)

    def instants(self) -> np.ndarray:
        if self._instants is None:
            self._instants = np.asarray(
                self.frequency.advance(
                    self._anchor_nanos,
                    self._offset + np.arange(self.periods, dtype=np.int64),
                ),
                dtype=np.int64,
            )
        return self._instants

    def locs_at_datetimes(self, dts: Iterable[DateTimeLike]) -> np.ndarray:
        nanos = to_nanos_array(dts)
        n = (
            np.asarray(self.frequency.difference(self._anchor_nanos, nanos), dtype=np.int64)
            - self._offset
        )
        exact = (
            np.asarray(self.frequency.advance(self._anchor_nanos, self._offset + n), dtype=np.int64)
            == nanos
        )
        ok = (n >= 0) & (n < self.periods) & exact
        return np.where(ok, n, -1).astype(np.int64)

    def islice(self, start: int, end: int) -> "UniformDateTimeIndex":
        start = int(start)
        end = int(end)
        if not (0 <= start <= end <= self.periods):
            raise IndexError(f"islice [{start}, {end}) out of range for size {self.periods}")
        return UniformDateTimeIndex(
            int(self.frequency.advance(self._anchor_nanos, self._offset + start)),
            end - start,
            self.frequency,
            _anchor=(self._anchor_nanos, self._offset + start),
        )

    def to_string(self) -> str:
        if self._offset or self._anchor_nanos != self.start_nanos:
            return (
                f"uniform,{self._anchor_nanos},{self.periods},"
                f"offset {self._offset},{self.frequency.to_string()}"
            )
        return f"uniform,{self.start_nanos},{self.periods},{self.frequency.to_string()}"


class IrregularDateTimeIndex(DateTimeIndex):
    """Arbitrary sorted instants (int64 nanos); binary-search lookups."""

    def __init__(self, instants: Iterable[DateTimeLike]):
        arr = to_nanos_array(instants)
        if arr.ndim != 1:
            raise ValueError("instants must be 1-D")
        if arr.size > 1 and not bool(np.all(arr[1:] > arr[:-1])):
            raise ValueError("instants must be strictly increasing")
        self._instants = arr

    @property
    def size(self) -> int:
        return int(self._instants.size)

    def date_time_at_loc(self, loc: int) -> np.datetime64:
        return nanos_to_datetime64(self._instants[int(loc)])[()]

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = int(np.searchsorted(self._instants, nanos, side="left"))
        if loc < self.size and self._instants[loc] == nanos:
            return loc
        return -1

    def insertion_loc(self, dt: DateTimeLike) -> int:
        return int(np.searchsorted(self._instants, to_nanos(dt), side="right"))

    def instants(self) -> np.ndarray:
        return self._instants

    def islice(self, start: int, end: int) -> "IrregularDateTimeIndex":
        return IrregularDateTimeIndex(self._instants[int(start) : int(end)])

    def to_string(self) -> str:
        return "irregular," + ",".join(str(int(v)) for v in self._instants)


class HybridDateTimeIndex(DateTimeIndex):
    """Concatenation of sub-indices (e.g. uniform segments around gaps)."""

    def __init__(self, indices: Sequence[DateTimeIndex]):
        if not indices:
            raise ValueError("hybrid index needs at least one sub-index")
        # Flatten nested hybrids: keeps instants identical and makes the
        # to_string/from_string round-trip well-defined (the string codec is
        # a flat ';'-separated list).
        flat: List[DateTimeIndex] = []
        for ix in indices:
            if isinstance(ix, HybridDateTimeIndex):
                flat.extend(ix.indices)
            else:
                flat.append(ix)
        self.indices: List[DateTimeIndex] = flat
        sizes = np.array([ix.size for ix in self.indices], dtype=np.int64)
        self._offsets = np.concatenate([[0], np.cumsum(sizes)])
        self._firsts = np.array([to_nanos(ix.first) for ix in self.indices], dtype=np.int64)
        self._lasts = np.array([to_nanos(ix.last) for ix in self.indices], dtype=np.int64)
        for i in range(len(self.indices) - 1):
            if self._lasts[i] >= self._firsts[i + 1]:
                raise ValueError("hybrid sub-indices must be disjoint and ordered")
        self._instants_cache: np.ndarray | None = None

    @property
    def size(self) -> int:
        return int(self._offsets[-1])

    def _sub_of(self, loc: int) -> Tuple[int, int]:
        i = int(np.searchsorted(self._offsets, loc, side="right")) - 1
        return i, loc - int(self._offsets[i])

    def date_time_at_loc(self, loc: int) -> np.datetime64:
        loc = int(loc)
        if loc < 0:
            loc += self.size
        i, sub = self._sub_of(loc)
        return self.indices[i].date_time_at_loc(sub)

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        i = int(np.searchsorted(self._firsts, nanos, side="right")) - 1
        if i < 0 or nanos > self._lasts[i]:
            return -1
        sub = self.indices[i].loc_at_datetime(nanos)
        return -1 if sub < 0 else int(self._offsets[i]) + sub

    def insertion_loc(self, dt: DateTimeLike) -> int:
        return int(np.searchsorted(self.instants(), to_nanos(dt), side="right"))

    def instants(self) -> np.ndarray:
        if self._instants_cache is None:
            self._instants_cache = np.concatenate([ix.instants() for ix in self.indices])
        return self._instants_cache

    def islice(self, start: int, end: int) -> DateTimeIndex:
        start, end = int(start), int(end)
        parts: List[DateTimeIndex] = []
        for i, ix in enumerate(self.indices):
            lo = max(start - int(self._offsets[i]), 0)
            hi = min(end - int(self._offsets[i]), ix.size)
            if lo < hi:
                parts.append(ix.islice(lo, hi))
        if not parts:
            return IrregularDateTimeIndex(np.array([], dtype=np.int64))
        if len(parts) == 1:
            return parts[0]
        return HybridDateTimeIndex(parts)

    def to_string(self) -> str:
        return "hybrid;" + ";".join(ix.to_string() for ix in self.indices)


# ---------------------------------------------------------------------------
# Factories (mirror the upstream companion object)
# ---------------------------------------------------------------------------


def uniform(start: DateTimeLike, periods: int, frequency: Frequency) -> UniformDateTimeIndex:
    return UniformDateTimeIndex(start, periods, frequency)


def uniform_from_interval(
    start: DateTimeLike, end: DateTimeLike, frequency: Frequency
) -> UniformDateTimeIndex:
    n = int(frequency.difference(to_nanos(start), to_nanos(end))) + 1
    return UniformDateTimeIndex(start, n, frequency)


def irregular(instants: Iterable[DateTimeLike]) -> IrregularDateTimeIndex:
    return IrregularDateTimeIndex(instants)


def hybrid(indices: Sequence[DateTimeIndex]) -> HybridDateTimeIndex:
    return HybridDateTimeIndex(indices)


def from_string(s: str) -> DateTimeIndex:
    """Decode an index from its persisted string form (checkpoint format)."""
    if s.startswith("hybrid;"):
        return HybridDateTimeIndex([from_string(p) for p in s[len("hybrid;") :].split(";")])
    kind, _, rest = s.partition(",")
    if kind == "uniform":
        m = re.match(r"(-?\d+),(\d+),(?:offset (-?\d+),)?(.+)", rest)
        if not m:
            raise ValueError(f"bad uniform index string: {s!r}")
        anchor, periods = int(m.group(1)), int(m.group(2))
        offset = int(m.group(3)) if m.group(3) else 0
        freq = frequency_from_string(m.group(4))
        start = int(freq.advance(anchor, offset)) if offset else anchor
        return UniformDateTimeIndex(start, periods, freq, _anchor=(anchor, offset))
    if kind == "irregular":
        return IrregularDateTimeIndex([int(v) for v in rest.split(",") if v])
    raise ValueError(f"unknown index string: {s!r}")


# Convenience aliases matching the reference's Scala naming.
NANOSECOND = NanosecondFrequency
MILLISECOND = MillisecondFrequency
SECOND = SecondFrequency
MINUTE = MinuteFrequency
HOUR = HourFrequency
DAY = DayFrequency
WEEK = WeekFrequency
MONTH = MonthFrequency
YEAR = YearFrequency
BUSINESS_DAY = BusinessDayFrequency
