"""Device-mesh and sharding utilities — the distributed substrate.

The reference's distribution model is Spark data-parallelism over series keys
(hash-partitioned ``RDD[(K, Vector)]``, SURVEY.md Section 2.4).  The
TPU-native equivalent implemented here: a 1-D ``jax.sharding.Mesh`` with a
``"series"`` axis; the panel's ``[keys, time]`` array is placed with
``NamedSharding(mesh, P("series", None))`` so every chip owns a contiguous
block of whole series (a series is never split across chips — the same
invariant the reference's partitioning guarantees).  Cross-series aggregates
ride ``psum`` over ICI; the ``toInstants`` transpose becomes an XLA
``all_to_all``; a replicated sharding ``P(None, None)`` replaces Spark's
TorrentBroadcast of the shared index (SURVEY.md Section 5.8).

Multi-host: under ``jax.distributed``, the same code runs unchanged — the
mesh spans all processes' devices and XLA routes ICI/DCN collectives.

Sequence-sharding (the optional ``"time"`` axis) is provided for very long
series: reductions over time decompose into per-shard partials + ``psum``,
and scans hand carries across shards via ``ppermute`` (see
``ops/seqparallel.py``).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SERIES_AXIS = "series"
TIME_AXIS = "time"


def default_mesh(
    n_devices: Optional[int] = None,
    *,
    time_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    1-D ``(series,)`` by default; pass ``time_shards > 1`` for a 2-D
    ``(series, time)`` mesh used by sequence-parallel kernels.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if time_shards > 1:
        if n % time_shards:
            raise ValueError(f"{n} devices not divisible by time_shards={time_shards}")
        arr = np.asarray(devs).reshape(n // time_shards, time_shards)
        return Mesh(arr, (SERIES_AXIS, TIME_AXIS))
    return Mesh(np.asarray(devs), (SERIES_AXIS,))


def series_sharding(mesh: Mesh) -> NamedSharding:
    """``[keys, time]`` sharded over keys, time replicated (or time-sharded
    on a 2-D mesh)."""
    if TIME_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — the broadcast-index analog."""
    return NamedSharding(mesh, P())


def instant_sharding(mesh: Mesh) -> NamedSharding:
    """``[time, keys]`` sharded over time — the result layout of the
    ``to_instants`` transpose."""
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n``."""
    return ((n + m - 1) // m) * m


def shard_series(values: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """Place a ``[keys, time]`` array with the series sharding.

    The keys axis must already be padded to a multiple of the mesh's series
    size (``TimeSeriesPanel`` pads with NaN rows at construction).
    """
    if mesh is None:
        return values
    return jax.device_put(values, series_sharding(mesh))


@functools.lru_cache(maxsize=None)
def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]), (SERIES_AXIS,))
