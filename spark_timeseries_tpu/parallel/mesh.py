"""Device-mesh and sharding utilities — the distributed substrate.

The reference's distribution model is Spark data-parallelism over series keys
(hash-partitioned ``RDD[(K, Vector)]``, SURVEY.md Section 2.4).  The
TPU-native equivalent implemented here: a 1-D ``jax.sharding.Mesh`` with a
``"series"`` axis; the panel's ``[keys, time]`` array is placed with
``NamedSharding(mesh, P("series", None))`` so every chip owns a contiguous
block of whole series (a series is never split across chips — the same
invariant the reference's partitioning guarantees).  Cross-series aggregates
ride ``psum`` over ICI; the ``toInstants`` transpose becomes an XLA
``all_to_all``; a replicated sharding ``P(None, None)`` replaces Spark's
TorrentBroadcast of the shared index (SURVEY.md Section 5.8).

Multi-host: under ``jax.distributed``, the same code runs unchanged — the
mesh spans all processes' devices and XLA routes ICI/DCN collectives.

Sequence-sharding (the optional ``"time"`` axis) is provided for very long
series: reductions over time decompose into per-shard partials + ``psum``,
and scans hand carries across shards via ``ppermute`` (see
``ops/seqparallel.py``).

**Who uses what** (reconciled with the driver, ISSUE 6): two distinct
consumers ride this module.  *SPMD fits* (``panel.fit_*`` over a
mesh-attached panel, ``ops/seqparallel.py``) place ONE global array with
:func:`series_sharding` and let XLA partition one program across the
mesh.  The *durable chunk driver* (``reliability.fit_chunked(shard=True)``
/ ``mesh=``) instead runs one prefetch→compute→commit LANE per
series-axis device: :func:`lane_values` hands each lane its
device-resident block of rows — via a single
``NamedSharding(mesh, P("series", None))`` placement when the lane spans
are the even split, per-device ``device_put`` otherwise — and the lane
spans come from ``reliability.plan.shard_spans``, which partitions the
CHUNK GRID (whole chunks per shard, the same "a series is never split
across chips" invariant, coarsened to chunks) so the sharded walk visits
exactly the single-device walk's chunk boundaries and stays
bitwise-identical to it.  Under ``jax.distributed`` build the global
panel with :func:`distribute_panel`
(``jax.make_array_from_process_local_data``); each process then runs the
lanes of its own addressable shards.

**Elastic lanes** (ISSUE 11): the per-lane placement above is the
STARTING layout, not ownership.  A single-process sharded walk may move
chunks between lanes mid-job — a quarantined lane's uncommitted chunks
and a straggler's stolen tail are re-staged to the computing lane's
device on demand (``reliability.plan.RestagedPanel`` wraps the driver's
resident panel in a ``device_put``-per-chunk view; source-backed lanes
re-stage through ``SourceLane`` exactly as at startup).  Under
``jax.distributed`` rows of another process are not addressable here, so
multi-host walks keep the static layout — re-staging across hosts is the
open ROADMAP item 5 follow-on.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SERIES_AXIS = "series"
TIME_AXIS = "time"


def default_mesh(
    n_devices: Optional[int] = None,
    *,
    time_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    1-D ``(series,)`` by default; pass ``time_shards > 1`` for a 2-D
    ``(series, time)`` mesh used by sequence-parallel kernels.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if time_shards > 1:
        if n % time_shards:
            raise ValueError(f"{n} devices not divisible by time_shards={time_shards}")
        arr = np.asarray(devs).reshape(n // time_shards, time_shards)
        return Mesh(arr, (SERIES_AXIS, TIME_AXIS))
    return Mesh(np.asarray(devs), (SERIES_AXIS,))


def series_sharding(mesh: Mesh) -> NamedSharding:
    """``[keys, time]`` sharded over keys, time replicated (or time-sharded
    on a 2-D mesh)."""
    if TIME_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — the broadcast-index analog."""
    return NamedSharding(mesh, P())


def instant_sharding(mesh: Mesh) -> NamedSharding:
    """``[time, keys]`` sharded over time — the result layout of the
    ``to_instants`` transpose."""
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n``."""
    return ((n + m - 1) // m) * m


def shard_series(values: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """Place a ``[keys, time]`` array with the series sharding.

    The keys axis must already be padded to a multiple of the mesh's series
    size (``TimeSeriesPanel`` pads with NaN rows at construction).

    The placement is the mesh plane's cross-chip data movement (the analog
    of Spark's shuffle into hash partitions), so it runs under an
    ``obs.span`` (ROADMAP: span coverage for the sharded paths) — free
    no-op when the telemetry plane is disabled.
    """
    if mesh is None:
        return values
    from .. import obs

    with obs.span("mesh.shard_series", keys=int(values.shape[0]),
                  devices=int(np.prod(list(mesh.shape.values())))):
        return jax.device_put(values, series_sharding(mesh))


def series_devices(mesh: Mesh) -> list:
    """The devices along the series axis, in shard order — the lane owners
    of a sharded chunk walk (one lane per entry).

    The sharded DRIVER is 1-D by design: each lane runs a whole fit
    program on one device (time replicated), so a 2-D ``(series, time)``
    mesh — whose time axis belongs to the SPMD sequence-parallel kernels,
    not the chunk walk — is rejected rather than silently collapsed.
    """
    if TIME_AXIS in mesh.axis_names and mesh.shape[TIME_AXIS] > 1:
        raise ValueError(
            "the sharded chunk walk needs a 1-D (series,) mesh; "
            "time-sharding belongs to the SPMD fit path (ops/seqparallel), "
            f"got axes {mesh.axis_names} with shape {dict(mesh.shape)}")
    return list(mesh.devices.flat)


def distribute_panel(local_rows, mesh: Mesh) -> jax.Array:
    """Build the GLOBAL ``[keys, time]`` panel from this process's local
    rows — the multi-host ingest step of a sharded chunk walk.

    Single-process this is just the series-sharded placement; under
    ``jax.distributed`` it is ``jax.make_array_from_process_local_data``:
    every process contributes the rows it holds, and the returned global
    array's addressable shards are exactly the lanes this process will
    run (``reliability.fit_chunked(..., mesh=mesh)``).
    """
    sharding = series_sharding(mesh)
    if jax.process_count() <= 1:
        return jax.device_put(jax.numpy.asarray(local_rows), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_rows))


def lane_values(values, mesh: Mesh, spans) -> list:
    """Place each lane's row block on its series-axis device.

    ``spans`` is the chunk-grid partition from
    ``reliability.plan.shard_spans`` (ascending, contiguous, covering the
    panel).  Returns ``[(shard_id, lo, hi, device, lane_array), ...]`` for
    the lanes THIS process runs; each ``lane_array`` holds rows
    ``[lo, hi)`` resident on ``device``.

    Placement strategy, in order:

    - ``values`` is already a multi-process global array (built with
      :func:`distribute_panel`): the lanes ARE its addressable shards —
      zero data movement, but the sharding's split must match ``spans``
      (chunk-grid-aligned), else the caller must repartition.
    - the spans are the even split of the panel over all mesh devices
      (the north-star layout): ONE ``NamedSharding`` placement of the
      whole panel, lanes read from its addressable shards.
    - otherwise: one ``device_put`` of each span's slice to its device
      (uneven tails, fewer chunks than devices).

    Either way the lane bytes are exactly ``values[lo:hi]`` — the
    placement moves data, never changes it.
    """
    devs = series_devices(mesh)
    spans = [(int(lo), int(hi)) for lo, hi in spans]
    if len(spans) > len(devs):
        raise ValueError(
            f"{len(spans)} lane spans but only {len(devs)} series devices")
    pidx = jax.process_index()
    out = []
    if isinstance(values, jax.Array) and not values.is_fully_addressable:
        by_row = {}
        for s in values.addressable_shards:
            by_row[int(s.index[0].start or 0)] = s
        claimed = set()
        for i, (lo, hi) in enumerate(spans):
            s = by_row.get(lo)
            if s is None:
                continue  # another process's lane
            if int(s.data.shape[0]) != hi - lo:
                raise ValueError(
                    f"global panel shard at row {lo} holds "
                    f"{int(s.data.shape[0])} rows but the chunk-grid lane "
                    f"wants {hi - lo}; choose chunk_rows so the chunk grid "
                    "matches the even device split (or repartition with "
                    "distribute_panel)")
            claimed.add(lo)
            out.append((i, lo, hi, list(s.data.devices())[0], s.data))
        # a local shard NO span starts at would silently compute nothing —
        # on a process where no shard start hits a span lo, the size check
        # above never fires, so the misalignment must be caught here
        unclaimed = sorted(set(by_row) - claimed)
        if unclaimed:
            raise ValueError(
                f"global panel shards starting at rows {unclaimed} are not "
                "claimed by any chunk-grid lane span; choose chunk_rows so "
                "shard boundaries land on the chunk grid (or repartition "
                "with distribute_panel)")
        return out
    n_rows = int(values.shape[0])
    sizes = {hi - lo for lo, hi in spans}
    even = (len(spans) == len(devs) and len(sizes) == 1
            and n_rows % len(devs) == 0
            and all(d.process_index == pidx for d in devs))
    with obs_span("mesh.shard_lanes", keys=n_rows, lanes=len(spans),
                  devices=len(devs)):
        if even:
            g = jax.device_put(values, series_sharding(mesh))
            shards = sorted(g.addressable_shards,
                            key=lambda s: int(s.index[0].start or 0))
            for i, ((lo, hi), s) in enumerate(zip(spans, shards)):
                out.append((i, lo, hi, list(s.data.devices())[0], s.data))
        else:
            for i, (lo, hi) in enumerate(spans):
                d = devs[i]
                if d.process_index != pidx:
                    continue
                out.append((i, lo, hi, d, jax.device_put(values[lo:hi], d)))
    return out


def obs_span(name, **attrs):
    """Lazy obs import (parallel must stay importable before obs)."""
    from .. import obs

    return obs.span(name, **attrs)


@functools.lru_cache(maxsize=None)
def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]), (SERIES_AXIS,))


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """Initialize the multi-host process group and return the global mesh.

    The reference rides Spark's driver/executor runtime for multi-machine
    work (Netty shuffle + TorrentBroadcast — SURVEY.md §5.8); the TPU-native
    equivalent is ``jax.distributed``: one Python process per host, every
    process calls this once before any other jax API, and the returned 1-D
    ``(series,)`` mesh spans ALL processes' devices — panels built with it
    shard over the full slice, with XLA routing collectives over ICI within
    a host's chips and DCN across hosts.

    On Cloud TPU (e.g. a v5e-8 pod slice) every argument is discovered from
    the environment, so the whole recipe is::

        # same script started on every host of the slice, e.g. with
        #   gcloud compute tpus tpu-vm ssh $TPU --worker=all \\
        #     --command="python train.py"
        from spark_timeseries_tpu.parallel import mesh as meshlib
        mesh = meshlib.init_distributed()          # no args on Cloud TPU
        panel = sts.from_observations(..., mesh=mesh)   # sharded ingest
        fit = arima.fit(panel.series_values(), (1, 1, 1))

    Elsewhere (CPU/GPU clusters, tests) pass the coordinator explicitly::

        mesh = meshlib.init_distributed("10.0.0.1:8476", num_processes=2,
                                        process_id=int(os.environ["RANK"]))

    Safe to call when already initialized (returns the mesh without
    re-initializing); single-process callers get the local-devices mesh,
    so code written against this entry point runs unchanged on one chip.
    """
    try:
        initialized = jax.distributed.is_initialized()
    except AttributeError:  # very old jax
        initialized = False
    explicit = coordinator_address is not None or num_processes is not None
    if not initialized and (explicit or _on_cloud_tpu_pod()):
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        try:
            jax.distributed.initialize(**kwargs)
        except (ValueError, RuntimeError):
            if explicit:  # the caller described a topology that failed: loud
                raise
            # pod-like env vars without a discoverable coordinator (single
            # host with TPU env leakage): fall back to the local mesh
            import warnings

            warnings.warn(
                "init_distributed: pod-like environment detected but "
                "jax.distributed could not auto-discover a coordinator; "
                "continuing single-process on local devices",
                stacklevel=2,
            )
    return default_mesh()


def _on_cloud_tpu_pod() -> bool:
    """True when MULTI-host TPU slice metadata is present (args
    discoverable).  Single-host TPU VMs set ``TPU_WORKER_HOSTNAMES=localhost``
    — one hostname is not a pod."""
    import os

    hostnames = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    return len(hostnames) > 1 or bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
