"""Device-mesh and sharding utilities — the distributed substrate.

The reference's distribution model is Spark data-parallelism over series keys
(hash-partitioned ``RDD[(K, Vector)]``, SURVEY.md Section 2.4).  The
TPU-native equivalent implemented here: a 1-D ``jax.sharding.Mesh`` with a
``"series"`` axis; the panel's ``[keys, time]`` array is placed with
``NamedSharding(mesh, P("series", None))`` so every chip owns a contiguous
block of whole series (a series is never split across chips — the same
invariant the reference's partitioning guarantees).  Cross-series aggregates
ride ``psum`` over ICI; the ``toInstants`` transpose becomes an XLA
``all_to_all``; a replicated sharding ``P(None, None)`` replaces Spark's
TorrentBroadcast of the shared index (SURVEY.md Section 5.8).

Multi-host: under ``jax.distributed``, the same code runs unchanged — the
mesh spans all processes' devices and XLA routes ICI/DCN collectives.

Sequence-sharding (the optional ``"time"`` axis) is provided for very long
series: reductions over time decompose into per-shard partials + ``psum``,
and scans hand carries across shards via ``ppermute`` (see
``ops/seqparallel.py``).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SERIES_AXIS = "series"
TIME_AXIS = "time"


def default_mesh(
    n_devices: Optional[int] = None,
    *,
    time_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    1-D ``(series,)`` by default; pass ``time_shards > 1`` for a 2-D
    ``(series, time)`` mesh used by sequence-parallel kernels.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if time_shards > 1:
        if n % time_shards:
            raise ValueError(f"{n} devices not divisible by time_shards={time_shards}")
        arr = np.asarray(devs).reshape(n // time_shards, time_shards)
        return Mesh(arr, (SERIES_AXIS, TIME_AXIS))
    return Mesh(np.asarray(devs), (SERIES_AXIS,))


def series_sharding(mesh: Mesh) -> NamedSharding:
    """``[keys, time]`` sharded over keys, time replicated (or time-sharded
    on a 2-D mesh)."""
    if TIME_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — the broadcast-index analog."""
    return NamedSharding(mesh, P())


def instant_sharding(mesh: Mesh) -> NamedSharding:
    """``[time, keys]`` sharded over time — the result layout of the
    ``to_instants`` transpose."""
    return NamedSharding(mesh, P(SERIES_AXIS, None))


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n``."""
    return ((n + m - 1) // m) * m


def shard_series(values: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """Place a ``[keys, time]`` array with the series sharding.

    The keys axis must already be padded to a multiple of the mesh's series
    size (``TimeSeriesPanel`` pads with NaN rows at construction).

    The placement is the mesh plane's cross-chip data movement (the analog
    of Spark's shuffle into hash partitions), so it runs under an
    ``obs.span`` (ROADMAP: span coverage for the sharded paths) — free
    no-op when the telemetry plane is disabled.
    """
    if mesh is None:
        return values
    from .. import obs

    with obs.span("mesh.shard_series", keys=int(values.shape[0]),
                  devices=int(np.prod(list(mesh.shape.values())))):
        return jax.device_put(values, series_sharding(mesh))


@functools.lru_cache(maxsize=None)
def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]), (SERIES_AXIS,))


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """Initialize the multi-host process group and return the global mesh.

    The reference rides Spark's driver/executor runtime for multi-machine
    work (Netty shuffle + TorrentBroadcast — SURVEY.md §5.8); the TPU-native
    equivalent is ``jax.distributed``: one Python process per host, every
    process calls this once before any other jax API, and the returned 1-D
    ``(series,)`` mesh spans ALL processes' devices — panels built with it
    shard over the full slice, with XLA routing collectives over ICI within
    a host's chips and DCN across hosts.

    On Cloud TPU (e.g. a v5e-8 pod slice) every argument is discovered from
    the environment, so the whole recipe is::

        # same script started on every host of the slice, e.g. with
        #   gcloud compute tpus tpu-vm ssh $TPU --worker=all \\
        #     --command="python train.py"
        from spark_timeseries_tpu.parallel import mesh as meshlib
        mesh = meshlib.init_distributed()          # no args on Cloud TPU
        panel = sts.from_observations(..., mesh=mesh)   # sharded ingest
        fit = arima.fit(panel.series_values(), (1, 1, 1))

    Elsewhere (CPU/GPU clusters, tests) pass the coordinator explicitly::

        mesh = meshlib.init_distributed("10.0.0.1:8476", num_processes=2,
                                        process_id=int(os.environ["RANK"]))

    Safe to call when already initialized (returns the mesh without
    re-initializing); single-process callers get the local-devices mesh,
    so code written against this entry point runs unchanged on one chip.
    """
    try:
        initialized = jax.distributed.is_initialized()
    except AttributeError:  # very old jax
        initialized = False
    explicit = coordinator_address is not None or num_processes is not None
    if not initialized and (explicit or _on_cloud_tpu_pod()):
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
        try:
            jax.distributed.initialize(**kwargs)
        except (ValueError, RuntimeError):
            if explicit:  # the caller described a topology that failed: loud
                raise
            # pod-like env vars without a discoverable coordinator (single
            # host with TPU env leakage): fall back to the local mesh
            import warnings

            warnings.warn(
                "init_distributed: pod-like environment detected but "
                "jax.distributed could not auto-discover a coordinator; "
                "continuing single-process on local devices",
                stacklevel=2,
            )
    return default_mesh()


def _on_cloud_tpu_pod() -> bool:
    """True when MULTI-host TPU slice metadata is present (args
    discoverable).  Single-host TPU VMs set ``TPU_WORKER_HOSTNAMES=localhost``
    — one hostname is not a pod."""
    import os

    hostnames = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    return len(hostnames) > 1 or bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
