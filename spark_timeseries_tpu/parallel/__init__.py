from . import mesh
from .mesh import (
    SERIES_AXIS,
    TIME_AXIS,
    default_mesh,
    instant_sharding,
    replicated_sharding,
    series_sharding,
)

__all__ = [
    "mesh",
    "SERIES_AXIS",
    "TIME_AXIS",
    "default_mesh",
    "series_sharding",
    "replicated_sharding",
    "instant_sharding",
]
