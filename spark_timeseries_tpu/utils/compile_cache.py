"""Opt-in persistent JAX compilation cache for restart-heavy workloads.

The journal (``reliability.journal``) makes a killed panel job resume
without recomputing committed chunks — but the restarted PROCESS still
repaid the full trace+compile of every fit program before touching the
first pending chunk, which at north-star scale is tens of seconds of pure
recompilation of programs an identical process already built.  JAX ships a
persistent compilation cache (serialized XLA executables keyed by HLO +
compile options) that turns that cost into a disk read; this module is the
library's one switch for it, so the bench, CI, and serving entry points
agree on how it is enabled:

- :func:`enable_compile_cache` — point JAX at a cache directory and relax
  the min-size/min-compile-time gates so small fit programs cache too.
  Safe to call more than once; returns the directory in effect or ``None``
  when this jax build has no cache support (the call degrades to a no-op
  rather than failing the fit — same contract as the obs plane).
- ``STSTPU_COMPILE_CACHE=<dir>`` — environment opt-in honored by
  :func:`enable_from_env` (wired into ``bench.py``; ``ci.sh`` exports
  ``JAX_COMPILATION_CACHE_DIR`` which jax honors natively).

Deliberately OPT-IN: a shared default directory would let one user's cache
poison another's benchmark numbers (first-run compile time is a published
measurement), and stale caches across jax upgrades are evicted by jax's
own key, not by us.

This module also owns the PROGRAM-reuse counters (``compile_cache.hit`` /
``compile_cache.miss`` in the obs registry, fed by ``models.base.
jit_program``): the auto-fit order search (ISSUE 9) promises one compiled
program per order shape reused across chunks, and the hit rate is how
that promise is measured (``bench.py`` ``telemetry_summary``).
"""

from __future__ import annotations

import os
import threading as _threading
from typing import Optional

__all__ = ["enable_compile_cache", "enable_from_env", "note_hit",
           "note_miss", "program_cache_stats"]

_ENV_VAR = "STSTPU_COMPILE_CACHE"
_enabled_dir: Optional[str] = None

# -- program-reuse accounting (ISSUE 9 satellite) ----------------------------
#
# The auto-fit order search compiles ONE program per (order, chunk shape)
# and reuses it across every chunk of that order's walk — the whole perf
# argument for riding the grid through the chunk driver.  These counters
# make that reuse a MEASURED number instead of a belief: `models.base.
# jit_program` (the per-static-config program cache every model fit goes
# through) reports each lookup here, the obs registry carries them as
# `compile_cache.hit` / `compile_cache.miss`, and `bench.py` surfaces the
# hit rate in its `telemetry_summary` regression-gate line.  Process-local
# mirrors ride along so the rate is readable even with the obs plane off
# (the obs counters stay authoritative for per-run deltas).

_hits = 0
_misses = 0
# concurrent lane threads (sharded walks) report through here; the obs
# counters carry their own locks, but these process-local mirrors would
# otherwise lose increments to the non-atomic load/add/store
_stats_lock = _threading.Lock()

# lock-discipline contract (tools/lint lock-map, module-level form):
# sharded lane threads report hits/misses concurrently.
_PROTECTED_BY_ = {"_hits": "_stats_lock", "_misses": "_stats_lock"}


def note_hit() -> None:
    """Record a program-cache hit (an already-built jitted program reused)."""
    global _hits
    with _stats_lock:
        _hits += 1
    from .. import obs

    obs.counter("compile_cache.hit").inc()


def note_miss() -> None:
    """Record a program-cache miss (a new program built — trace + compile
    will be paid at its first dispatch)."""
    global _misses
    with _stats_lock:
        _misses += 1
    from .. import obs

    obs.counter("compile_cache.miss").inc()


def program_cache_stats() -> dict:
    """Process-lifetime program-cache accounting: ``{hits, misses,
    hit_rate}`` (hit_rate None before the first lookup)."""
    with _stats_lock:
        hits, misses = _hits, _misses
    total = hits + misses
    return {
        "hits": hits,
        "misses": misses,
        "hit_rate": round(hits / total, 4) if total else None,
    }


def enable_compile_cache(cache_dir: str) -> Optional[str]:
    """Enable jax's persistent compilation cache under ``cache_dir``.

    Returns the directory on success, ``None`` when this jax build lacks
    the cache (never raises: a missing cache only costs recompiles).  The
    min-entry-size and min-compile-time gates are relaxed so the chunked
    fit programs — compiled once per (config, chunk-rows) — are cached
    regardless of size, which is the whole point for journaled resumes.
    """
    global _enabled_dir
    try:
        import jax

        cache_dir = os.path.abspath(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every program: the default gates skip small/fast compiles,
        # but a resumed north-star walk re-pays dozens of them at once
        for knob, v in (("jax_persistent_cache_min_entry_size_bytes", -1),
                        ("jax_persistent_cache_min_compile_time_secs", 0.0)):
            try:
                jax.config.update(knob, v)
            except Exception:  # noqa: BLE001 - knob renamed/absent: defaults ok
                pass
        # jax latches the cache decision per backend at first use: a dir
        # set AFTER the backend initialized is silently ignored (verified
        # on jax 0.4.37) — reset the latch so mid-process enabling (bench
        # main, a serving process flipping the knob) actually takes effect
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 - moved/absent: fresh-process only
            pass
        _enabled_dir = cache_dir
        return cache_dir
    except Exception:  # noqa: BLE001 - no cache support in this build
        return None


def enable_from_env() -> Optional[str]:
    """Honor ``STSTPU_COMPILE_CACHE=<dir>`` (no-op when unset)."""
    d = os.environ.get(_ENV_VAR)
    if not d:
        return None
    return enable_compile_cache(d)


def enabled_dir() -> Optional[str]:
    """The cache directory enabled through this module, if any."""
    return _enabled_dir
