"""Opt-in persistent JAX compilation cache for restart-heavy workloads.

The journal (``reliability.journal``) makes a killed panel job resume
without recomputing committed chunks — but the restarted PROCESS still
repaid the full trace+compile of every fit program before touching the
first pending chunk, which at north-star scale is tens of seconds of pure
recompilation of programs an identical process already built.  JAX ships a
persistent compilation cache (serialized XLA executables keyed by HLO +
compile options) that turns that cost into a disk read; this module is the
library's one switch for it, so the bench, CI, and serving entry points
agree on how it is enabled:

- :func:`enable_compile_cache` — point JAX at a cache directory and relax
  the min-size/min-compile-time gates so small fit programs cache too.
  Safe to call more than once; returns the directory in effect or ``None``
  when this jax build has no cache support (the call degrades to a no-op
  rather than failing the fit — same contract as the obs plane).
- ``STSTPU_COMPILE_CACHE=<dir>`` — environment opt-in honored by
  :func:`enable_from_env` (wired into ``bench.py``; ``ci.sh`` exports
  ``JAX_COMPILATION_CACHE_DIR`` which jax honors natively).

Deliberately OPT-IN: a shared default directory would let one user's cache
poison another's benchmark numbers (first-run compile time is a published
measurement), and stale caches across jax upgrades are evicted by jax's
own key, not by us.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["enable_compile_cache", "enable_from_env"]

_ENV_VAR = "STSTPU_COMPILE_CACHE"
_enabled_dir: Optional[str] = None


def enable_compile_cache(cache_dir: str) -> Optional[str]:
    """Enable jax's persistent compilation cache under ``cache_dir``.

    Returns the directory on success, ``None`` when this jax build lacks
    the cache (never raises: a missing cache only costs recompiles).  The
    min-entry-size and min-compile-time gates are relaxed so the chunked
    fit programs — compiled once per (config, chunk-rows) — are cached
    regardless of size, which is the whole point for journaled resumes.
    """
    global _enabled_dir
    try:
        import jax

        cache_dir = os.path.abspath(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every program: the default gates skip small/fast compiles,
        # but a resumed north-star walk re-pays dozens of them at once
        for knob, v in (("jax_persistent_cache_min_entry_size_bytes", -1),
                        ("jax_persistent_cache_min_compile_time_secs", 0.0)):
            try:
                jax.config.update(knob, v)
            except Exception:  # noqa: BLE001 - knob renamed/absent: defaults ok
                pass
        # jax latches the cache decision per backend at first use: a dir
        # set AFTER the backend initialized is silently ignored (verified
        # on jax 0.4.37) — reset the latch so mid-process enabling (bench
        # main, a serving process flipping the knob) actually takes effect
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 - moved/absent: fresh-process only
            pass
        _enabled_dir = cache_dir
        return cache_dir
    except Exception:  # noqa: BLE001 - no cache support in this build
        return None


def enable_from_env() -> Optional[str]:
    """Honor ``STSTPU_COMPILE_CACHE=<dir>`` (no-op when unset)."""
    d = os.environ.get(_ENV_VAR)
    if not d:
        return None
    return enable_compile_cache(d)


def enabled_dir() -> Optional[str]:
    """The cache directory enabled through this module, if any."""
    return _enabled_dir
