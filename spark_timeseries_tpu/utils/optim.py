"""Batched unconstrained optimization for model fitting.

The reference fits every model with Apache Commons Math optimizers —
``NonLinearConjugateGradientOptimizer`` (css-cgd) and ``BOBYQAOptimizer``
(css-bobyqa / Holt-Winters) — one series at a time on one JVM core
(SURVEY.md Section 2.2).  The TPU rebuild needs ONE optimizer that fits a
million independent small problems simultaneously, which means it must be:

- jit-compatible: fixed iteration budget, ``lax.while_loop`` control flow;
- vmap-compatible: every series carries its own state (history, step size,
  converged flag) with identical static shapes;
- autodiff-driven: gradients come from ``jax.grad`` of the CSS/likelihood
  scan (the reference hand-derives them).

This module implements L-BFGS (two-loop recursion, fixed-size history,
Armijo backtracking line search).  BOBYQA has no JAX analog; bounded
problems (GARCH/Holt-Winters positivity) use parameter transforms (sigmoid /
softplus) and come through the same unconstrained path — SURVEY.md Section 7
"hard parts".
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs


# Straggler-compaction sizing shared by every fit driver: below this batch
# size the compaction stage is not worth its gather, and the cap must cover
# whole [8, 128] kernel series blocks (ops.pallas_kernels._SBLK) so folded-
# column gathers stay grid-aligned.
COMPACT_MIN_BATCH = 4096


def compaction_cap(bsz: int) -> int:
    """Straggler cap for a batch of ``bsz`` rows: ~bsz/8, 1024-aligned."""
    return -(-max(1024, bsz // 8) // 1024) * 1024


def retry_cap(n: int, align: int = 8) -> int:
    """Bucket size for a host-side failed-subset gather: the next power of
    two at or above ``n`` (minimum ``align``).

    The resilient runner (``reliability.runner``) pads retry sub-batches to
    this cap for the same reason :func:`compaction_cap` aligns the
    straggler gather: the padded shape, not the exact failure count,
    determines the compiled program, so bucketing bounds the number of
    distinct shapes (and recompiles) the ladder can create.
    """
    n = max(int(n), 1)
    cap = max(align, 1)
    while cap < n:
        cap *= 2
    return cap


def gather_pad_indices(rows, cap: int):
    """Pad a host-side row-index gather to ``cap`` slots by repeating the
    first index.

    The convention every bounded-shape subset dispatch shares — the
    resilient retry ladder's failed-row buckets and the auto-fit winners
    stage-2 basin refits (``models.auto``): the padded tail recomputes a
    real row (its results are dropped on scatter), so the compiled
    program's shape is the :func:`retry_cap` bucket, never one shape per
    subset size.
    """
    import numpy as _np

    rows = _np.asarray(rows)
    if rows.size == 0:
        raise ValueError("gather_pad_indices needs at least one row")
    if int(cap) < rows.size:
        raise ValueError(f"cap {cap} smaller than the {rows.size}-row gather")
    return _np.concatenate(
        [rows, _np.full(int(cap) - rows.size, rows[0], rows.dtype)])


class LBFGSResult(NamedTuple):
    x: jax.Array  # [d] solution
    f: jax.Array  # [] final objective
    converged: jax.Array  # [] bool: grad-norm tolerance reached
    iters: jax.Array  # [] iterations actually taken
    grad_norm: jax.Array  # [] gradient norm at the returned x (best-seen iterate)


class _State(NamedTuple):
    k: jax.Array
    x: jax.Array
    f: jax.Array
    g: jax.Array
    s_hist: jax.Array  # [m, d]
    y_hist: jax.Array  # [m, d]
    rho_hist: jax.Array  # [m]
    converged: jax.Array
    failed: jax.Array  # line search broke down
    tprev: jax.Array  # last accepted linesearch step (warm-start)
    # best-seen iterate: the noise-floor-relaxed accept can adopt a step
    # that RAISES f by up to ftol*max(1,|f|) (and ftol-convergence then
    # freezes there), so the returned (x, f) is the best visited point,
    # guaranteeing f(returned) <= f(x0) (ADVICE r3).  bg is the gradient AT
    # bx, so the reported grad_norm is a valid stationarity diagnostic for
    # the returned point (ADVICE r4)
    bx: jax.Array
    bf: jax.Array
    bg: jax.Array


def _two_loop(g, s_hist, y_hist, rho_hist, k, m):
    """L-BFGS two-loop recursion with masked (not-yet-filled) history slots.

    History is a ring buffer; slot ``i`` is valid when ``rho_hist[i] > 0``.
    The loops are unrolled (``m`` is a small static history size): unrolling
    lets XLA fuse the whole recursion into a couple of kernels instead of
    ``2m`` sequential scan steps — this machinery runs once per optimizer
    iteration on every series, so launch overhead matters.
    """
    idx = (k - 1 - jnp.arange(m)) % m  # newest -> oldest

    q = g
    alphas = []
    for j in range(m):
        i = idx[j]
        valid = rho_hist[i] > 0.0
        alpha = jnp.where(valid, rho_hist[i] * jnp.dot(s_hist[i], q), 0.0)
        q = q - alpha * y_hist[i] * valid
        alphas.append(alpha)

    # initial Hessian scaling gamma = s·y / y·y of the newest valid pair
    newest = idx[0]
    sy = jnp.dot(s_hist[newest], y_hist[newest])
    yy = jnp.dot(y_hist[newest], y_hist[newest])
    gamma = jnp.where((rho_hist[newest] > 0.0) & (yy > 0.0), sy / yy, 1.0)
    r = gamma * q

    for j in reversed(range(m)):
        i = idx[j]
        valid = rho_hist[i] > 0.0
        beta = jnp.where(valid, rho_hist[i] * jnp.dot(y_hist[i], r), 0.0)
        r = r + (alphas[j] - beta) * s_hist[i] * valid
    return r  # approximates H g


def minimize_lbfgs(
    fun: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    *,
    max_iters: int = 50,
    history: int = 8,
    tol: float = 1e-6,
    ftol: float | None = None,
    max_linesearch: int = 20,
    c1: float = 1e-4,
) -> LBFGSResult:
    """Minimize ``fun`` from ``x0`` with a fixed compute budget.

    Designed for ``vmap``: all shapes static, all control flow ``lax``.
    Non-finite objective values are treated as +inf by the line search, so
    transformed-parameter models can guard invalid regions with ``jnp.where``.

    Convergence is EITHER the relative gradient-norm test (``tol``) OR an
    accepted step whose relative objective decrease falls below ``ftol``
    (scipy/Commons-Math style): at f32 the gradient of a long-series
    objective bottoms out on its accumulation noise floor while the
    objective itself has visibly stopped moving.  ``ftol=None`` picks
    1e-6 (f32) / 1e-9 (f64).
    """
    d = x0.shape[0]
    m = history
    dtype = x0.dtype
    if ftol is None:
        ftol = 1e-9 if dtype == jnp.float64 else 1e-6

    value_and_grad = jax.value_and_grad(fun)

    def safe_vg(x):
        f, g = value_and_grad(x)
        bad = ~jnp.isfinite(f) | ~jnp.all(jnp.isfinite(g))
        return jnp.where(bad, jnp.inf, f), jnp.where(bad, 0.0, g)

    f0, g0 = safe_vg(x0)
    init = _State(
        k=jnp.zeros((), jnp.int32),
        x=x0,
        f=f0,
        g=g0,
        s_hist=jnp.zeros((m, d), dtype),
        y_hist=jnp.zeros((m, d), dtype),
        rho_hist=jnp.zeros((m,), dtype),
        converged=(jnp.linalg.norm(g0) < tol) & jnp.isfinite(f0),
        failed=jnp.isinf(f0),
        tprev=jnp.ones((), dtype),
        bx=x0,
        bf=f0,
        bg=g0,
    )

    def linesearch(x, f, g, direction, t0):
        """Backtracking with quadratic interpolation: each failed trial fits
        the 1-D quadratic through (0, f), slope g·dir, and (t, f(t)) and jumps
        to its minimizer (clamped to [0.1t, 0.5t] — plain halving needs ~12
        full objective evaluations per iteration on badly scaled first steps,
        the dominant cost of a batched fit).  The Armijo test carries a noise
        floor of ftol*max(1, |f|): near convergence the predicted decrease
        falls below the objective's own evaluation noise and the strict test
        would reject EVERY step size; the relaxed accept is then resolved by
        the ftol stopping rule.  Returns (t, ok)."""
        gd = jnp.dot(g, direction)
        eps = ftol * jnp.maximum(1.0, jnp.abs(f))

        def body(carry):
            t, _, j = carry
            fnew = fun(x + t * direction)
            fnew = jnp.where(jnp.isfinite(fnew), fnew, jnp.inf)
            ok = fnew <= f + c1 * t * gd + eps
            tq = -gd * t * t / (2.0 * (fnew - f - gd * t))
            # non-finite fnew gives tq = 0 -> clamp to the aggressive edge
            tq = jnp.where(jnp.isfinite(tq), tq, 0.0)
            # the objective may evaluate in a wider dtype; the carry must not
            tq = jnp.clip(tq, 0.1 * t, 0.5 * t).astype(t.dtype)
            return jnp.where(ok, t, tq), ok, j + 1

        def cond(carry):
            t, ok, j = carry
            return (~ok) & (j < max_linesearch)

        t, ok, _ = lax.while_loop(
            cond, body, (t0, jnp.zeros((), bool), 0)
        )
        return t, ok

    def step(state: _State) -> _State:
        direction = -_two_loop(state.g, state.s_hist, state.y_hist, state.rho_hist, state.k, m)
        # fall back to steepest descent if direction is not a descent direction
        descent = jnp.dot(state.g, direction) < 0.0
        direction = jnp.where(descent, direction, -state.g)

        # with no curvature history the direction is raw steepest descent,
        # whose scale is arbitrary: bound the first trial step length by 1.
        # With history, warm-start from the last accepted step — a problem
        # that keeps needing tiny steps should not re-pay the whole
        # backtrack from t=1 every iteration
        has_hist = jnp.any(state.rho_hist > 0.0)
        t0 = jnp.where(
            has_hist & descent,
            jnp.minimum(1.0, 4.0 * state.tprev),
            1.0 / jnp.maximum(1.0, jnp.linalg.norm(direction)),
        ).astype(dtype)
        t, ok = linesearch(state.x, state.f, state.g, direction, t0)
        x_new = state.x + t * direction
        f_new2, g_new = safe_vg(x_new)

        s = x_new - state.x
        y = g_new - state.g
        sy = jnp.dot(s, y)
        slot = state.k % m
        good_pair = (sy > 1e-10) & ok
        s_hist = state.s_hist.at[slot].set(jnp.where(good_pair, s, state.s_hist[slot]))
        y_hist = state.y_hist.at[slot].set(jnp.where(good_pair, y, state.y_hist[slot]))
        rho_hist = state.rho_hist.at[slot].set(
            jnp.where(good_pair, 1.0 / jnp.maximum(sy, 1e-30), state.rho_hist[slot])
        )

        # same noise floor as the Armijo test: a step that moved f by less
        # than the evaluation noise is "accepted" and then resolved by ftol
        accept = ok & (f_new2 <= state.f + ftol * jnp.maximum(1.0, jnp.abs(state.f)))
        x_out = jnp.where(accept, x_new, state.x)
        f_out = jnp.where(accept, f_new2, state.f)
        g_out = jnp.where(accept, g_new, state.g)
        conv = jnp.linalg.norm(g_out) < tol * jnp.maximum(1.0, jnp.linalg.norm(x_out))
        conv = conv | (
            accept & (state.f - f_new2 <= ftol * jnp.maximum(1.0, jnp.abs(f_new2)))
        )
        better = f_out < state.bf
        return _State(
            k=state.k + 1,
            x=x_out,
            f=f_out,
            g=g_out,
            s_hist=jnp.where(accept, s_hist, state.s_hist),
            y_hist=jnp.where(accept, y_hist, state.y_hist),
            rho_hist=jnp.where(accept, rho_hist, state.rho_hist),
            converged=conv,
            failed=state.failed | (~ok & ~conv),
            tprev=jnp.where(accept, t, state.tprev),
            bx=jnp.where(better, x_out, state.bx),
            bf=jnp.where(better, f_out, state.bf),
            bg=jnp.where(better, g_out, state.bg),
        )

    def cond(state: _State):
        return (state.k < max_iters) & ~state.converged & ~state.failed

    final = lax.while_loop(cond, step, init)
    # (x, f, grad_norm) all refer to the best-seen iterate
    return LBFGSResult(
        x=final.bx,
        f=final.bf,
        converged=final.converged & jnp.isfinite(final.bf),
        iters=final.k,
        grad_norm=jnp.linalg.norm(final.bg),
    )


_rownorm = lambda v: jnp.linalg.norm(v, axis=-1)
_rowdot = lambda a, b: jnp.sum(a * b, axis=-1)
_two_loop_b = jax.vmap(_two_loop, in_axes=(0, 0, 0, 0, None, None))


def _make_vg_b(fb):
    """Batched value-and-grad with the non-finite guard rows carry."""

    def vg(x):
        f, pullback = jax.vjp(fb, x)
        (g,) = pullback(jnp.ones_like(f))
        bad = ~jnp.isfinite(f) | ~jnp.all(jnp.isfinite(g), axis=-1)
        return jnp.where(bad, jnp.inf, f), jnp.where(bad[:, None], 0.0, g)

    return vg


def _init_state_b(vg, x0, m, tol):
    bsz, d = x0.shape
    dtype = x0.dtype
    f0, g0 = vg(x0)
    return _State(
        k=jnp.zeros((), jnp.int32),
        x=x0,
        f=f0,
        g=g0,
        s_hist=jnp.zeros((bsz, m, d), dtype),
        y_hist=jnp.zeros((bsz, m, d), dtype),
        rho_hist=jnp.zeros((bsz, m), dtype),
        converged=(_rownorm(g0) < tol) & jnp.isfinite(f0),
        failed=jnp.isinf(f0),
        tprev=jnp.ones((bsz,), dtype),
        bx=x0,
        bf=f0,
        bg=g0,
    )


def _make_linesearch_b(fb, *, ftol, max_linesearch, c1):
    def linesearch(x, f, g, direction, done, t0):
        # done rows are pre-satisfied: their (frozen) state can never
        # pass the strict Armijo test, and one such row would otherwise
        # drag the whole batch through max_linesearch extra objective
        # evaluations.  Failed trials jump to the minimizer of the
        # quadratic through (0, f), slope g·dir, and (t, f(t)) (clamped
        # to [0.1t, 0.5t]): every trial is a FULL-batch objective pass
        # gated by the worst row, and plain halving needs ~12 of them
        # per iteration on badly scaled steps
        gd = _rowdot(g, direction)
        # noise floor: near convergence the predicted decrease falls
        # below the objective's f32 evaluation noise and the strict
        # Armijo test rejects EVERY step size, dragging the whole batch
        # through deep backtracks; the relaxed accept is resolved by the
        # ftol rule
        eps = ftol * jnp.maximum(1.0, jnp.abs(f))

        def body(carry):
            t, ok, j = carry
            fnew = fb(x + t[:, None] * direction)
            fnew = jnp.where(jnp.isfinite(fnew), fnew, jnp.inf)
            ok_new = ok | (fnew <= f + c1 * t * gd + eps)
            tq = -gd * t * t / (2.0 * (fnew - f - gd * t))
            tq = jnp.where(jnp.isfinite(tq), tq, 0.0)
            # the objective may evaluate in a wider dtype; the carry
            # must not
            tq = jnp.clip(tq, 0.1 * t, 0.5 * t).astype(t.dtype)
            return jnp.where(ok_new, t, tq), ok_new, j + 1

        def cond(carry):
            _, ok, j = carry
            return jnp.any(~ok) & (j < max_linesearch)

        t, ok, n_ls = lax.while_loop(cond, body, (t0, done, 0))
        return t, ok, n_ls

    return linesearch


def _make_step_b(fb, *, m, dtype, tol, ftol, max_linesearch, c1):
    """One lockstep L-BFGS iteration over a batched objective ``fb`` —
    shared by the inline two-stage driver (:func:`minimize_lbfgs_batched`)
    and the lazily compiled stage-1/stage-2 split."""
    vg_fb = _make_vg_b(fb)
    linesearch = _make_linesearch_b(fb, ftol=ftol,
                                    max_linesearch=max_linesearch, c1=c1)

    def step(carry):
        state, iters, ls_hist = carry
        done = state.converged | state.failed
        with jax.named_scope("optim.lbfgs_batched.two_loop"):
            direction = -_two_loop_b(
                state.g, state.s_hist, state.y_hist, state.rho_hist,
                state.k, m
            )
        descent = _rowdot(state.g, direction) < 0.0
        direction = jnp.where(descent[:, None], direction, -state.g)

        # rows with no curvature history step along raw steepest
        # descent, whose scale is arbitrary: bound their first trial
        # step length by 1.  With history, warm-start from the row's
        # last accepted step — every extra trial is a FULL-batch
        # objective pass, so a straggler row that keeps needing tiny
        # steps must not re-pay the whole backtrack from t=1 every
        # iteration
        has_hist = jnp.any(state.rho_hist > 0.0, axis=-1)
        t0 = jnp.where(
            has_hist & descent,
            jnp.minimum(1.0, 4.0 * state.tprev),
            1.0 / jnp.maximum(1.0, _rownorm(direction)),
        ).astype(dtype)
        with jax.named_scope("optim.lbfgs_batched.linesearch"):
            t, ok, n_ls = linesearch(
                state.x, state.f, state.g, direction, done, t0)
        x_new = state.x + t[:, None] * direction
        with jax.named_scope("optim.lbfgs_batched.value_and_grad"):
            f_new, g_new = vg_fb(x_new)

        s = x_new - state.x
        y = g_new - state.g
        sy = _rowdot(s, y)
        slot = state.k % m
        accept = (
            ok
            & (f_new <= state.f + ftol * jnp.maximum(1.0, jnp.abs(state.f)))
            & ~done
        )
        # gate history on accept (not just the linesearch ok), matching
        # the per-series minimize_lbfgs: a step rejected at the
        # re-evaluation must not poison the curvature history
        good_pair = (sy > 1e-10) & accept
        upd = lambda hist, v: hist.at[:, slot].set(
            jnp.where(good_pair[:, None], v, hist[:, slot])
        )
        s_hist = upd(state.s_hist, s)
        y_hist = upd(state.y_hist, y)
        rho_hist = state.rho_hist.at[:, slot].set(
            jnp.where(good_pair, 1.0 / jnp.maximum(sy, 1e-30),
                      state.rho_hist[:, slot])
        )
        x_out = jnp.where(accept[:, None], x_new, state.x)
        f_out = jnp.where(accept, f_new, state.f)
        g_out = jnp.where(accept[:, None], g_new, state.g)
        conv = state.converged | (
            _rownorm(g_out) < tol * jnp.maximum(1.0, _rownorm(x_out))
        )
        conv = conv | (
            accept
            & (state.f - f_new <= ftol * jnp.maximum(1.0, jnp.abs(f_new)))
        )
        better = f_out < state.bf
        new_state = _State(
            k=state.k + 1,
            x=x_out,
            f=f_out,
            g=g_out,
            s_hist=s_hist,
            y_hist=y_hist,
            rho_hist=rho_hist,
            converged=conv,
            failed=state.failed | (~ok & ~conv & ~done),
            tprev=jnp.where(accept, t, state.tprev),
            bx=jnp.where(better[:, None], x_out, state.bx),
            bf=jnp.where(better, f_out, state.bf),
            bg=jnp.where(better[:, None], g_out, state.bg),
        )
        iters = jnp.where(done, iters, state.k + 1)
        if ls_hist is not None:
            ls_hist = ls_hist.at[state.k].set(n_ls)
        return new_state, iters, ls_hist

    return step


def minimize_lbfgs_batched(
    fun_batched: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    *,
    max_iters: int = 50,
    history: int = 8,
    tol: float = 1e-6,
    ftol: float | None = None,
    max_linesearch: int = 20,
    c1: float = 1e-4,
    count_evals: bool = False,
    straggler_fun: "Callable[[jax.Array], Callable] | None" = None,
    straggler_cap: int | None = None,
) -> "LBFGSResult | tuple[LBFGSResult, dict]":
    """Jointly minimize ``B`` independent problems with ONE batched objective.

    ``fun_batched(x[B, d]) -> f[B]`` evaluates every problem at once — the
    entry point for fused whole-batch objectives (e.g. the Pallas CSS kernel,
    ``ops.pallas_kernels``) that cannot be traced per-series under ``vmap``.
    Semantics match ``vmap(minimize_lbfgs)``: each row carries its own
    history, step size, and convergence flag; rows are block-diagonal so the
    gradient of ``sum(f)`` is exactly the per-row gradient.  All rows step in
    lockstep (as they do under ``vmap`` of a ``while_loop``); finished rows
    freeze their state.

    **Straggler compaction** (VERDICT r4 item 2): every lockstep pass costs
    a full-batch objective evaluation even when most rows have converged —
    the tail of the fit pays O(B) per iteration for O(B/8) live rows.  When
    ``straggler_fun`` is given, the lockstep loop exits as soon as at most
    ``straggler_cap`` rows remain unconverged; those rows (and their whole
    optimizer state) are gathered into a ``[cap, d]`` problem whose
    objective is ``straggler_fun(row_indices)``, the loop continues on the
    small batch for the remaining iteration budget, and the results scatter
    back.  In exact arithmetic per-row trajectories are identical to the
    uncompacted run (the step-size carry, accept tests, and convergence
    tests are all per-row, and batched objectives compute rows
    independently) — but the compacted program IS a different compiled
    program, so f32 fusion differences exist, and rows sitting on flat or
    non-convex stretches can amplify them into different (equally valid)
    optima.  Callers should hold compaction to the same distribution-level
    parity bar as any backend change (see the bench parity gates), not to
    bitwise equality.  ``straggler_cap`` defaults to ``max(128, B // 8)``.

    ``count_evals=True`` (diagnostics, e.g. ``tools/profile_headline.py``)
    additionally returns ``(result, info)`` with ``info["ls_evals"]``
    (``[max_iters] int32`` — linesearch objective evaluations per outer
    iteration), ``info["compact_at"]`` (iteration at which compaction
    engaged, == iterations run when it never did), and ``info["cap"]`` —
    the profiler instruments the REAL optimizer instead of a fork of it.
    """
    bsz, d = x0.shape
    m = history
    dtype = x0.dtype
    if ftol is None:
        ftol = 1e-9 if dtype == jnp.float64 else 1e-6
    cap = straggler_cap if straggler_cap is not None else max(128, bsz // 8)
    compact = straggler_fun is not None and cap < bsz

    knobs = dict(m=m, dtype=dtype, tol=tol, ftol=ftol,
                 max_linesearch=max_linesearch, c1=c1)
    vg = _make_vg_b(fun_batched)
    init = _init_state_b(vg, x0, m, tol)
    iters0 = jnp.zeros((bsz,), jnp.int32)

    def undone_count(state):
        return jnp.sum(~(state.converged | state.failed))

    ls0 = jnp.zeros((max_iters,), jnp.int32) if count_evals else None
    step_full = _make_step_b(fun_batched, **knobs)

    def cond_full(carry):
        state, _, _ = carry
        live = jnp.any(~(state.converged | state.failed))
        if compact:
            # keep lockstepping only while the stragglers outnumber the cap
            live = live & (undone_count(state) > cap)
        return (state.k < max_iters) & live

    stage1, iters, ls_hist = lax.while_loop(
        cond_full, step_full, (init, iters0, ls0))
    final = stage1
    compact_at = stage1.k

    if compact:
        # gather the (at most cap) unconverged rows and their whole state;
        # out-of-range fill indices read row bsz-1 and are dropped on the
        # scatter, so duplicates never corrupt live rows.
        #
        # TRUNCATION CONTRACT (ADVICE r5): when stage 1 exits at max_iters
        # with MORE than cap rows undone, this size=cap gather silently
        # drops the excess — benign only because stage 2 shares the same
        # exhausted iteration budget (cond_sub tests state.k <
        # stage2_max_iters == max_iters), so the sub-loop runs zero steps
        # and the dropped rows' state is unchanged by the scatter.  Any
        # change that gives stage 2 its OWN budget must first make this
        # gather lossless — the assert below is the tripwire.
        stage2_max_iters = max_iters
        assert stage2_max_iters == max_iters, (
            "stage-2 straggler budget must equal max_iters while the "
            "size=cap gather can truncate at max_iters (ADVICE r5: make "
            "the gather lossless before giving stage 2 its own budget)")
        # this Python block runs once per TRACE of the enclosing fit
        # program (lru-cached jit per static config), so the counter counts
        # stage-2 COMPILE trips, not steady-state dispatches
        obs.counter("optim.stage2_compact_traces").inc()
        undone1 = ~(stage1.converged | stage1.failed)
        idx = jnp.nonzero(undone1, size=cap, fill_value=bsz)[0]
        idxc = jnp.minimum(idx, bsz - 1)
        take = lambda a: a[idxc]
        sub = _State(
            k=stage1.k,
            x=take(stage1.x), f=take(stage1.f), g=take(stage1.g),
            s_hist=take(stage1.s_hist), y_hist=take(stage1.y_hist),
            rho_hist=take(stage1.rho_hist),
            converged=take(stage1.converged), failed=take(stage1.failed),
            tprev=take(stage1.tprev),
            bx=take(stage1.bx), bf=take(stage1.bf), bg=take(stage1.bg),
        )
        step_sub = _make_step_b(straggler_fun(idxc), **knobs)

        def cond_sub(carry):
            state, _, _ = carry
            return (state.k < stage2_max_iters) & jnp.any(
                ~(state.converged | state.failed))

        sub_f, sub_iters, ls_hist = lax.while_loop(
            cond_sub, step_sub, (sub, take(iters), ls_hist))
        put = lambda full, s: full.at[idx].set(s, mode="drop")
        final = stage1._replace(
            k=sub_f.k,
            converged=put(stage1.converged, sub_f.converged),
            failed=put(stage1.failed, sub_f.failed),
            bx=put(stage1.bx, sub_f.bx),
            bf=put(stage1.bf, sub_f.bf),
            bg=put(stage1.bg, sub_f.bg),
        )
        iters = put(iters, sub_iters)

    # (x, f, grad_norm) all refer to the best-seen iterate per row
    result = LBFGSResult(
        x=final.bx,
        f=final.bf,
        converged=final.converged & jnp.isfinite(final.bf),
        iters=iters,
        grad_norm=_rownorm(final.bg),
    )
    if not count_evals:
        return result
    return result, {"ls_evals": ls_hist, "compact_at": compact_at,
                    "cap": cap if compact else 0}


# -- lazily compiled straggler compaction (stage-1 / stage-2 split) ----------
#
# The inline driver above traces and compiles the compacted stage-2 program
# into every compact fit — even when stage 1 converges all rows and the
# sub-loop would run zero iterations, roughly doubling fit compile time for
# batches that never need it (ADVICE r5).  The split below lets a model fit
# run stage 1 as its own compiled program that ALSO returns the compacted
# straggler state; the host then checks the (tiny) undone count and only
# dispatches — and therefore only ever traces/compiles — the stage-2 program
# when stragglers actually remain.  The decision is a pure function of the
# fit's inputs (same data -> same undone count -> same programs), so
# journaled resumes stay bitwise-reproducible per config.


class StragglerCarry(NamedTuple):
    """Stage-1 exit state a lazily compiled stage 2 resumes from.

    ``state`` is the full optimizer state of the (at most ``cap``)
    unconverged rows, gathered exactly as the inline driver gathers them;
    ``idx`` are the scatter indices (fill value ``bsz`` -> dropped on
    scatter), ``idxc`` the clamped gather indices model code uses to
    repack the objective's data for the compacted problem.  ``undone``
    and ``k`` are the host-checkable dispatch gate: stage 2 is worth
    dispatching iff ``undone > 0`` and ``k < max_iters`` (the shared
    budget — see the truncation-contract tripwire in
    :func:`minimize_lbfgs_batched`)."""

    state: _State  # compacted [cap, ...] optimizer state
    idx: jax.Array  # [cap] scatter indices (fill = bsz: dropped)
    idxc: jax.Array  # [cap] clamped gather indices
    iters: jax.Array  # [bsz] per-row iteration counts at stage-1 exit
    undone: jax.Array  # [] int32 unconverged-row count at stage-1 exit
    k: jax.Array  # [] int32 stage-1 exit iteration


def lbfgs_batched_stage1(
    fun_batched: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    *,
    straggler_cap: int,
    max_iters: int = 50,
    history: int = 8,
    tol: float = 1e-6,
    ftol: float | None = None,
    max_linesearch: int = 20,
    c1: float = 1e-4,
) -> "tuple[LBFGSResult, StragglerCarry]":
    """Stage 1 of the compacted batched L-BFGS, as a standalone traceable.

    Runs the lockstep loop with the same early exit as the inline driver
    (stop once at most ``straggler_cap`` rows remain unconverged), then
    gathers the straggler state into the ``[cap, ...]`` layout and returns
    ``(result_as_if_done, carry)``.  When no rows remain unconverged the
    result IS the final answer (the inline stage-2 loop would have run
    zero iterations and scattered the state back unchanged); otherwise the
    caller dispatches :func:`lbfgs_batched_stage2` — compiled only then —
    with a compacted objective built from ``carry.idxc``.

    ``straggler_cap`` must be < the batch size (callers gate on
    :func:`compaction_cap`); semantics otherwise match
    :func:`minimize_lbfgs_batched` (no ``count_evals``: pass accounting
    stays on the inline driver, which the profiler instruments).
    """
    bsz, _ = x0.shape
    m = history
    dtype = x0.dtype
    if ftol is None:
        ftol = 1e-9 if dtype == jnp.float64 else 1e-6
    cap = int(straggler_cap)
    if cap >= bsz:
        raise ValueError(
            f"straggler_cap {cap} must be < batch {bsz} (an uncompacted fit "
            "has no stage 2 to defer — use minimize_lbfgs_batched)")
    knobs = dict(m=m, dtype=dtype, tol=tol, ftol=ftol,
                 max_linesearch=max_linesearch, c1=c1)
    vg = _make_vg_b(fun_batched)
    init = _init_state_b(vg, x0, m, tol)
    iters0 = jnp.zeros((bsz,), jnp.int32)
    step_full = _make_step_b(fun_batched, **knobs)

    def cond_full(carry):
        state, _, _ = carry
        undone = jnp.sum(~(state.converged | state.failed))
        # keep lockstepping only while the stragglers outnumber the cap
        return (state.k < max_iters) & (undone > cap)

    stage1, iters, _ = lax.while_loop(cond_full, step_full,
                                      (init, iters0, None))
    undone1 = ~(stage1.converged | stage1.failed)
    # same gather as the inline driver: out-of-range fill indices read row
    # bsz-1 and are dropped on the scatter.  The TRUNCATION CONTRACT
    # (ADVICE r5) carries over unchanged: at stage-1 exit with k == max_iters
    # and more than cap rows undone this gather drops the excess — benign
    # only because stage 2 shares the exhausted budget, which here is
    # enforced twice: the tripwire assert in lbfgs_batched_stage2 AND the
    # host gate (carry.k < max_iters) that skips the dispatch entirely.
    idx = jnp.nonzero(undone1, size=cap, fill_value=bsz)[0]
    idxc = jnp.minimum(idx, bsz - 1)
    take = lambda a: a[idxc]
    sub = _State(
        k=stage1.k,
        x=take(stage1.x), f=take(stage1.f), g=take(stage1.g),
        s_hist=take(stage1.s_hist), y_hist=take(stage1.y_hist),
        rho_hist=take(stage1.rho_hist),
        converged=take(stage1.converged), failed=take(stage1.failed),
        tprev=take(stage1.tprev),
        bx=take(stage1.bx), bf=take(stage1.bf), bg=take(stage1.bg),
    )
    result = LBFGSResult(
        x=stage1.bx,
        f=stage1.bf,
        converged=stage1.converged & jnp.isfinite(stage1.bf),
        iters=iters,
        grad_norm=_rownorm(stage1.bg),
    )
    carry = StragglerCarry(state=sub, idx=idx, idxc=idxc, iters=iters,
                           undone=jnp.sum(undone1).astype(jnp.int32),
                           k=stage1.k)
    return result, carry


def lbfgs_batched_stage2(
    fun_sub_batched: Callable[[jax.Array], jax.Array],
    full: LBFGSResult,
    carry: StragglerCarry,
    *,
    max_iters: int = 50,
    history: int = 8,
    tol: float = 1e-6,
    ftol: float | None = None,
    max_linesearch: int = 20,
    c1: float = 1e-4,
) -> LBFGSResult:
    """Stage 2 of the lazy split: finish the compacted stragglers.

    ``fun_sub_batched`` is the compacted objective over the ``[cap, d]``
    problem (the model builds it from ``carry.idxc`` — e.g. a row gather
    of the panel, or the folded-column repack for the ARIMA kernel);
    ``full`` is stage 1's as-if-done result, into which the finished
    straggler rows are scattered.  Budget is SHARED with stage 1
    (``carry.k`` continues counting toward the same ``max_iters``) —
    see the truncation-contract tripwire below.
    """
    m = history
    dtype = carry.state.x.dtype
    if ftol is None:
        ftol = 1e-9 if dtype == jnp.float64 else 1e-6
    # TRUNCATION CONTRACT (ADVICE r5): the stage-1 size=cap gather silently
    # drops the excess when stage 1 exits at max_iters with more than cap
    # rows undone — benign only because stage 2 shares the same exhausted
    # iteration budget.  Any change that gives stage 2 its OWN budget must
    # first make the gather lossless — this assert is the tripwire.
    stage2_max_iters = max_iters
    assert stage2_max_iters == max_iters, (
        "stage-2 straggler budget must equal max_iters while the size=cap "
        "gather can truncate at max_iters (ADVICE r5: make the gather "
        "lossless before giving stage 2 its own budget)")
    # this Python block runs once per TRACE of the stage-2 program — which,
    # unlike the inline driver, only ever happens when stragglers actually
    # remained — so the counter now counts NEEDED stage-2 compiles
    obs.counter("optim.stage2_compact_traces").inc()
    knobs = dict(m=m, dtype=dtype, tol=tol, ftol=ftol,
                 max_linesearch=max_linesearch, c1=c1)
    step_sub = _make_step_b(fun_sub_batched, **knobs)

    def cond_sub(c):
        state, _, _ = c
        return (state.k < stage2_max_iters) & jnp.any(
            ~(state.converged | state.failed))

    sub_f, sub_iters, _ = lax.while_loop(
        cond_sub, step_sub, (carry.state, carry.iters[carry.idxc], None))
    put = lambda a, s: a.at[carry.idx].set(s, mode="drop")
    # scatter semantics match the inline driver's state scatter followed by
    # its finalize: per scattered row, converged & isfinite(bf) and the
    # grad norm are computed from the SUB state, untouched rows keep stage
    # 1's values verbatim
    return LBFGSResult(
        x=put(full.x, sub_f.bx),
        f=put(full.f, sub_f.bf),
        converged=put(full.converged,
                      sub_f.converged & jnp.isfinite(sub_f.bf)),
        iters=put(full.iters, sub_iters),
        grad_norm=put(full.grad_norm, _rownorm(sub_f.bg)),
    )


def batched_minimize(
    fun: Callable[[jax.Array, jax.Array], jax.Array],
    x0: jax.Array,
    data: jax.Array,
    **kwargs,
) -> LBFGSResult:
    """vmap ``minimize_lbfgs`` over problems: ``fun(params[d], data_row)``.

    ``x0``: ``[batch, d]`` initial points; ``data``: ``[batch, ...]`` per-
    problem data (e.g. each series' observations).  This is the rebuild's
    replacement for the reference's per-series optimizer loop: one XLA
    computation fits every series at once.
    """
    solver = partial(minimize_lbfgs, **kwargs)
    return jax.vmap(lambda x, row: solver(lambda p: fun(p, row), x))(x0, data)


# -- bounded-parameter transforms (BOBYQA replacement) ----------------------


def sigmoid_to_interval(u, lo, hi):
    """Map R -> (lo, hi)."""
    return lo + (hi - lo) * jax.nn.sigmoid(u)


def interval_to_sigmoid(x, lo, hi):
    """Inverse of :func:`sigmoid_to_interval` (x strictly inside)."""
    p = (x - lo) / (hi - lo)
    p = jnp.clip(p, 1e-7, 1 - 1e-7)
    return jnp.log(p) - jnp.log1p(-p)


def softplus_inverse(y):
    return jnp.log(jnp.expm1(jnp.maximum(y, 1e-10)))
