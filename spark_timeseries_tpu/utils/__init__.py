from . import compile_cache, optim
from .optim import batched_minimize, minimize_lbfgs

__all__ = ["compile_cache", "optim", "minimize_lbfgs", "batched_minimize"]
