from . import optim
from .optim import batched_minimize, minimize_lbfgs

__all__ = ["optim", "minimize_lbfgs", "batched_minimize"]
