"""Shared small-regression building blocks.

The reference leans on Commons-Math ``OLSMultipleLinearRegression`` across
models and tests (SURVEY.md Section 2.2); every batched fit here funnels
through one ridge-stabilized normal-equations solve that maps well onto the
MXU (tiny ``[k, k]`` Gram matrices, huge batch).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _chol_solve_unrolled(A, y):
    """Batched SPD solve with statically unrolled Cholesky (k = A.shape[-1]).

    ``jnp.linalg.solve`` lowers to a batched LU whose per-matrix control
    flow is serial on TPU — ~190 ms of device time for 100k 4x4 systems,
    which made the Hannan-Rissanen init the single largest cost of the
    headline ARIMA fit.  For the tiny SPD systems every OLS here produces
    (ridge-stabilized normal equations), an unrolled Cholesky is ~k^3/3
    fused ELEMENTWISE ops over the batch — pure VPU streaming, no per-row
    control flow.

    PRECONDITION: ``A`` must be symmetric positive-definite at working
    precision (this decomposition is UNPIVOTED — there is no row exchange
    to recover from a non-positive pivot).  Rows that violate it are
    reported in the returned ``bad`` mask; their solutions are computed
    with pivots clamped to a floor SCALED TO THE MATRIX
    (``eps * trace/k``, ADVICE r5 — an absolute 1e-30 floor turned
    slightly-indefinite f32 input into ~1e+15 divisions and exploding
    solutions), so they stay bounded relative to the input but are NOT
    trustworthy — callers should replace them (see :func:`ridge_solve`).

    Returns ``(x, bad)``: the solutions and a ``[...]`` bool mask of rows
    whose factorization hit a non-positive (or non-finite) pivot.
    """
    k = A.shape[-1]
    eps = jnp.asarray(jnp.finfo(A.dtype).eps, A.dtype)
    scale = jnp.trace(A, axis1=-2, axis2=-1) / k
    floor = eps * jnp.maximum(scale, jnp.asarray(jnp.finfo(A.dtype).tiny, A.dtype))
    bad = jnp.zeros(A.shape[:-2], bool)
    L = [[None] * k for _ in range(k)]
    for i in range(k):
        for j in range(i + 1):
            s = A[..., i, j]
            for p in range(j):
                s = s - L[i][p] * L[j][p]
            if i == j:
                bad = bad | ~(s > 0.0)  # non-positive OR NaN pivot
                L[i][j] = jnp.sqrt(jnp.maximum(s, floor))
            else:
                L[i][j] = s / L[j][j]
    z = [None] * k
    for i in range(k):
        s = y[..., i]
        for p in range(i):
            s = s - L[i][p] * z[p]
        z[i] = s / L[i][i]
    x = [None] * k
    for i in reversed(range(k)):
        s = z[i]
        for p in range(i + 1, k):
            s = s - L[p][i] * x[p]
        x[i] = s / L[i][i]
    return jnp.stack(x, axis=-1), bad


def ridge_solve(XtX, Xty, ridge: float = 1e-8):
    """Solve normal equations with THE scaled-ridge stabilization rule.

    Single source of ``scale = max(trace/k, 1)``; every OLS construction in
    the tree (design-matrix, shifted-column, and pallas-moment paths) must
    funnel through here so the backends stay numerically identical.
    Supports leading batch dims: ``XtX [..., k, k]``, ``Xty [..., k]``.

    Small systems (k <= 8 — every model-fit OLS in the tree) solve via the
    batched unrolled Cholesky; larger ones fall back to ``linalg.solve``.

    The Cholesky path assumes SPD input; rows whose factorization hits a
    non-positive pivot (f32 accumulation can leave a near-rank-deficient
    Gram matrix slightly indefinite even after the ridge) are re-solved
    with the pivoted ``jnp.linalg.solve`` LU instead of returning an
    exploding clamped-pivot solution (ADVICE r5).  The fallback runs under
    a ``lax.cond``: batches with no bad row — the overwhelmingly common
    case — never pay the LU.  (Under ``vmap`` the cond lowers to a select
    and both paths execute; only the cheap vmapped-per-series OLS callers
    take that hit, never the hot batched fit paths.)
    """
    k = XtX.shape[-1]
    scale = jnp.maximum(jnp.trace(XtX, axis1=-2, axis2=-1) / k, 1.0)
    eye = jnp.eye(k, dtype=XtX.dtype)
    A = XtX + (ridge * scale)[..., None, None] * eye
    if k > 8:
        return jnp.linalg.solve(A, Xty[..., None])[..., 0]
    x, bad = _chol_solve_unrolled(A, Xty)
    if bad.ndim == 0:  # unbatched solve: one row, one decision
        return lax.cond(
            bad,
            lambda: jnp.linalg.solve(A, Xty[..., None])[..., 0],
            lambda: x,
        )
    return lax.cond(
        jnp.any(bad),
        lambda: jnp.where(
            bad[..., None], jnp.linalg.solve(A, Xty[..., None])[..., 0], x
        ),
        lambda: x,
    )


def ols(X, y, ridge: float = 1e-8):
    """OLS coefficients via ridge-stabilized normal equations.

    ``X^T X`` is tiny ([k, k] for k regressors), so a Cholesky-friendly
    solve is far cheaper than SVD-based lstsq and batches perfectly under
    vmap; the scaled ridge keeps rank-deficient designs finite.
    """
    return ridge_solve(X.T @ X, X.T @ y, ridge)
