"""Shared small-regression building blocks.

The reference leans on Commons-Math ``OLSMultipleLinearRegression`` across
models and tests (SURVEY.md Section 2.2); every batched fit here funnels
through one ridge-stabilized normal-equations solve that maps well onto the
MXU (tiny ``[k, k]`` Gram matrices, huge batch).
"""

from __future__ import annotations

import jax.numpy as jnp


def _chol_solve_unrolled(A, y):
    """Batched SPD solve with statically unrolled Cholesky (k = A.shape[-1]).

    ``jnp.linalg.solve`` lowers to a batched LU whose per-matrix control
    flow is serial on TPU — ~190 ms of device time for 100k 4x4 systems,
    which made the Hannan-Rissanen init the single largest cost of the
    headline ARIMA fit.  For the tiny SPD systems every OLS here produces
    (ridge-stabilized normal equations), an unrolled Cholesky is ~k^3/3
    fused ELEMENTWISE ops over the batch — pure VPU streaming, no per-row
    control flow.  ``sqrt`` is clamped so degenerate rows stay finite (they
    produce the same garbage-in-garbage-out rows LU did)."""
    k = A.shape[-1]
    L = [[None] * k for _ in range(k)]
    for i in range(k):
        for j in range(i + 1):
            s = A[..., i, j]
            for p in range(j):
                s = s - L[i][p] * L[j][p]
            if i == j:
                L[i][j] = jnp.sqrt(jnp.maximum(s, 1e-30))
            else:
                L[i][j] = s / L[j][j]
    z = [None] * k
    for i in range(k):
        s = y[..., i]
        for p in range(i):
            s = s - L[i][p] * z[p]
        z[i] = s / L[i][i]
    x = [None] * k
    for i in reversed(range(k)):
        s = z[i]
        for p in range(i + 1, k):
            s = s - L[p][i] * x[p]
        x[i] = s / L[i][i]
    return jnp.stack(x, axis=-1)


def ridge_solve(XtX, Xty, ridge: float = 1e-8):
    """Solve normal equations with THE scaled-ridge stabilization rule.

    Single source of ``scale = max(trace/k, 1)``; every OLS construction in
    the tree (design-matrix, shifted-column, and pallas-moment paths) must
    funnel through here so the backends stay numerically identical.
    Supports leading batch dims: ``XtX [..., k, k]``, ``Xty [..., k]``.

    Small systems (k <= 8 — every model-fit OLS in the tree) solve via the
    batched unrolled Cholesky; larger ones fall back to ``linalg.solve``.
    """
    k = XtX.shape[-1]
    scale = jnp.maximum(jnp.trace(XtX, axis1=-2, axis2=-1) / k, 1.0)
    eye = jnp.eye(k, dtype=XtX.dtype)
    A = XtX + (ridge * scale)[..., None, None] * eye
    if k <= 8:
        return _chol_solve_unrolled(A, Xty)
    return jnp.linalg.solve(A, Xty[..., None])[..., 0]


def ols(X, y, ridge: float = 1e-8):
    """OLS coefficients via ridge-stabilized normal equations.

    ``X^T X`` is tiny ([k, k] for k regressors), so a Cholesky-friendly
    solve is far cheaper than SVD-based lstsq and batches perfectly under
    vmap; the scaled ridge keeps rank-deficient designs finite.
    """
    return ridge_solve(X.T @ X, X.T @ y, ridge)
