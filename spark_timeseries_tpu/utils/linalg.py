"""Shared small-regression building blocks.

The reference leans on Commons-Math ``OLSMultipleLinearRegression`` across
models and tests (SURVEY.md Section 2.2); every batched fit here funnels
through one ridge-stabilized normal-equations solve that maps well onto the
MXU (tiny ``[k, k]`` Gram matrices, huge batch).
"""

from __future__ import annotations

import jax.numpy as jnp


def ridge_solve(XtX, Xty, ridge: float = 1e-8):
    """Solve normal equations with THE scaled-ridge stabilization rule.

    Single source of ``scale = max(trace/k, 1)``; every OLS construction in
    the tree (design-matrix, shifted-column, and pallas-moment paths) must
    funnel through here so the backends stay numerically identical.
    Supports leading batch dims: ``XtX [..., k, k]``, ``Xty [..., k]``.
    """
    k = XtX.shape[-1]
    scale = jnp.maximum(jnp.trace(XtX, axis1=-2, axis2=-1) / k, 1.0)
    eye = jnp.eye(k, dtype=XtX.dtype)
    return jnp.linalg.solve(
        XtX + (ridge * scale)[..., None, None] * eye, Xty[..., None]
    )[..., 0]


def ols(X, y, ridge: float = 1e-8):
    """OLS coefficients via ridge-stabilized normal equations.

    ``X^T X`` is tiny ([k, k] for k regressors), so a Cholesky-friendly
    solve is far cheaper than SVD-based lstsq and batches perfectly under
    vmap; the scaled ridge keeps rank-deficient designs finite.
    """
    return ridge_solve(X.T @ X, X.T @ y, ridge)
