"""Shared small-regression building blocks.

The reference leans on Commons-Math ``OLSMultipleLinearRegression`` across
models and tests (SURVEY.md Section 2.2); every batched fit here funnels
through one ridge-stabilized normal-equations solve that maps well onto the
MXU (tiny ``[k, k]`` Gram matrices, huge batch).
"""

from __future__ import annotations

import jax.numpy as jnp


def ols(X, y, ridge: float = 1e-8):
    """OLS coefficients via ridge-stabilized normal equations.

    ``X^T X`` is tiny ([k, k] for k regressors), so a Cholesky-friendly
    solve is far cheaper than SVD-based lstsq and batches perfectly under
    vmap; the scaled ridge keeps rank-deficient designs finite.
    """
    XtX = X.T @ X
    k = XtX.shape[0]
    scale = jnp.maximum(jnp.trace(XtX) / k, 1.0)
    return jnp.linalg.solve(XtX + ridge * scale * jnp.eye(k, dtype=X.dtype), X.T @ y)
