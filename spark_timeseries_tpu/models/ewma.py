"""EWMA — exponentially weighted moving average smoothing (L4).

Rebuild of the reference's ``sparkts/models/EWMA.scala`` (SURVEY.md
Section 2.2, upstream path unverified): smoothing recursion
``s_t = alpha * x_t + (1 - alpha) * s_{t-1}`` with ``alpha`` fitted by
minimizing the one-step-ahead SSE.  The reference runs a Commons-Math
gradient optimizer per series; here the SSE is a ``lax.scan`` and a sigmoid
transform keeps ``alpha`` in (0, 1) through the shared vmapped L-BFGS.

Parameter layout: ``[alpha]``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import optim
from .base import (FitResult, align_right, debatch,
                   derive_status, ensure_batched, maybe_align,
                   jit_program, resolve_align_mode, resolve_backend)


def smooth(alpha, x, n_valid=None):
    """The EWMA recursion (``addTimeDependentEffects``): s_0 = x_0.

    ``n_valid`` marks a right-aligned valid span (``base.align_right``): the
    state seeds at the first valid value and the zero prefix emits 0.
    """
    if n_valid is None:
        def step(s, xt):
            s = alpha * xt + (1.0 - alpha) * s
            return s, s

        _, out = lax.scan(step, x[0], x)
        return out

    start = x.shape[0] - n_valid

    def step(s, inp):
        xt, t = inp
        s = jnp.where(
            t < start, 0.0, jnp.where(t == start, xt, alpha * xt + (1.0 - alpha) * s)
        )
        return s, s

    _, out = lax.scan(step, jnp.zeros((), x.dtype), (x, jnp.arange(x.shape[0])))
    return out


def unsmooth(alpha, s):
    """Invert :func:`smooth`: x_t = (s_t - (1-alpha) s_{t-1}) / alpha
    (``removeTimeDependentEffects``).  The inverse does not exist at
    alpha = 0 (smoothing discards the input entirely); near-zero alpha
    returns NaN rather than silently overflowing to inf."""
    prev = jnp.concatenate([s[:1], s[:-1]])
    x = jnp.where(
        jnp.abs(alpha) > 1e-12, (s - (1.0 - alpha) * prev) / alpha, jnp.nan
    )
    return x.at[0].set(s[0])


def sse(alpha, x, n_valid=None):
    """One-step-ahead squared error: sum_t (x_t - s_{t-1})^2 for valid t >= 1."""
    s = smooth(alpha, x, n_valid)
    err = x[1:] - s[:-1]
    if n_valid is not None:
        start = x.shape[0] - n_valid
        err = jnp.where(jnp.arange(1, x.shape[0]) > start, err, 0.0)
    return jnp.sum(err * err)


def fit(y, *, max_iters: int = 40, tol: Optional[float] = None,
        backend: str = "auto", align_mode: Optional[str] = None) -> FitResult:
    """Fit ``alpha`` per series by SSE minimization -> params ``[batch?, 1]``.

    Leading/trailing NaNs are tolerated (right-aligned masking); series with
    fewer than 3 valid points come back NaN with ``converged=False``.
    ``backend``: ``"scan"`` (portable), ``"pallas"`` (fused TPU kernel), or
    ``"auto"`` (pallas when ``ops.pallas_kernels.supported`` says so).

    ``align_mode`` is the static alignment hint (``base.resolve_align_mode``)
    the chunk driver threads through sliced walks to skip the per-chunk NaN
    probe; a hint too strong for the data flags the violating rows
    (DIVERGED / EXCLUDED) instead of silently misfitting them.
    """
    yb, single = ensure_batched(y)
    if tol is None:
        tol = 1e-8 if yb.dtype == jnp.float64 else 1e-4
    backend = resolve_backend(backend, yb.dtype, yb.shape[1])
    return debatch(
        _fit_program(max_iters, float(tol), backend,
                     resolve_align_mode(yb, align_mode))(yb),
        single,
    )


@jit_program
def _fit_program(max_iters, tol, backend, align_mode="general"):
    def run(yb):
        ya, nv = maybe_align(yb, align_mode)

        u0 = jnp.zeros((yb.shape[0], 1), yb.dtype)
        # optimize the MEAN squared error (see models.arima: same argmin,
        # O(1) gradients); the reported objective is the unscaled SSE
        n_eff = jnp.maximum(nv - 1, 1).astype(yb.dtype)
        if backend in ("pallas", "pallas-interpret"):
            from ..ops import pallas_kernels as pk

            interp = backend == "pallas-interpret"

            def fb(u):
                alpha = optim.sigmoid_to_interval(u[:, 0], 0.0, 1.0)
                return pk.ewma_sse(alpha, ya, nv, interpret=interp) / n_eff

            res = optim.minimize_lbfgs_batched(fb, u0, max_iters=max_iters, tol=tol)
        else:
            def objective(u, data):
                x, n, ne = data
                return sse(optim.sigmoid_to_interval(u[0], 0.0, 1.0), x, n) / ne

            res = optim.batched_minimize(
                objective, u0, (ya, nv, n_eff), max_iters=max_iters, tol=tol
            )
        alpha = optim.sigmoid_to_interval(res.x, 0.0, 1.0)
        ok = nv >= 3
        params = jnp.where(ok[:, None], alpha, jnp.nan)
        return FitResult(
            params,
            jnp.where(ok, res.f * n_eff, jnp.nan),
            res.converged & ok,
            res.iters,
            derive_status(ok, res.converged, params),
        )

    return run


def forecast(params, y, n_future: int):
    """EWMA forecasts are flat at the last smoothed level."""
    yb, single = ensure_batched(y)
    pb = jnp.atleast_2d(params)
    out = _forecast_program(n_future)(pb, yb)
    return out[0] if single else out


@jit_program
def _forecast_program(n_future):
    def run(pb, yb):
        def one(a, x):
            xa, nv = align_right(x)
            last = smooth(a[0], xa, nv)[-1]
            # empty span or failed-fit params must not yield a plausible 0.0
            return jnp.where((nv > 0) & jnp.isfinite(a[0]), last, jnp.nan)

        last = jax.vmap(one)(pb, yb)
        return jnp.broadcast_to(last[:, None], (yb.shape[0], n_future))

    return run


_smooth_batched = jax.jit(jax.vmap(lambda a, v: smooth(a[0], v)))
_unsmooth_batched = jax.jit(jax.vmap(lambda a, v: unsmooth(a[0], v)))


def add_time_dependent_effects(params, x):
    xb, single = ensure_batched(x)
    pb = jnp.atleast_2d(params)
    out = _smooth_batched(pb, xb)
    return out[0] if single else out


def remove_time_dependent_effects(params, s):
    sb, single = ensure_batched(s)
    pb = jnp.atleast_2d(params)
    out = _unsmooth_batched(pb, sb)
    return out[0] if single else out
