"""Common model interface (L4).

Mirrors the reference's ``TimeSeriesModel`` trait (SURVEY.md Section 2.2:
``addTimeDependentEffects`` / ``removeTimeDependentEffects``) as a pair of
pure functions on parameter pytrees, plus the fit-result container shared by
every model family.

Conventions:
- Every model module exposes ``fit(y, ...) -> FitResult`` accepting ``[time]``
  or ``[batch, time]`` (auto-vmapped), with all structure (orders, seasonality)
  static so one compiled computation fits the whole batch.
- ``FitResult.params`` is ``[batch?, k]``; per-series diagnostics (converged,
  iterations, final objective) ride along — the structured-diagnostics
  replacement for Spark logs (SURVEY.md Section 5.5).
"""

from __future__ import annotations

import functools
import threading
import weakref
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .. import obs


def jit_program(builder):
    """Build + cache ONE compiled program per static configuration.

    ``builder(*static) -> traceable fn``; ``jit_program(builder)(*static)``
    returns the jitted fn, cached on the static args.  Model entry points are
    plain library calls (no long-lived jit closure at the call site), so
    without this every ``fit``/``forecast`` call would re-trace and
    re-compile — the analog of the reference reusing one JVM JIT-compiled
    code path across calls.

    Every lookup reports hit/miss to ``utils.compile_cache`` (obs counters
    ``compile_cache.hit`` / ``compile_cache.miss``): per-order program
    reuse is the auto-fit search's perf core (ISSUE 9), and the hit rate
    makes that reuse measurable instead of assumed.
    """
    cached = functools.lru_cache(maxsize=512)(
        lambda *static: jax.jit(builder(*static))
    )
    # lookup + hit/miss classification are one atomic step: sharded lane
    # threads call fit concurrently, and an unsynchronized cache_info()
    # delta would misattribute another thread's hit to this thread's
    # miss, making the published reuse rate nondeterministic.  The lock
    # only guards building the (cheap, uncompiled) jitted wrapper — XLA
    # compilation happens at first dispatch, outside it.
    lock = threading.Lock()

    def norm(a):  # tolerate list-valued order/shape args (lists don't hash)
        return tuple(a) if isinstance(a, list) else a

    def get(*static):
        from ..utils import compile_cache as _cc

        with lock:
            before = cached.cache_info().hits
            out = cached(*map(norm, static))
            hit = cached.cache_info().hits > before
        (_cc.note_hit if hit else _cc.note_miss)()
        return out

    return functools.wraps(builder)(get)


def resolve_backend(backend: str, dtype, n_time: int,
                    structural_ok: bool = True) -> str:
    """Validate a fit ``backend`` and resolve ``"auto"``.

    ``auto`` picks the fused Pallas objective when the platform/dtype/length
    allow (``ops.pallas_kernels.supported``) AND the model's structural
    parameters fit the kernel's chunked layout (``structural_ok`` — e.g.
    ``pk.css_structural_ok(p, q)``), else the portable ``lax.scan`` path.
    An explicitly requested ``"pallas"`` with violating structure raises at
    the kernel entry point instead.  Shared by every model family so the
    backend vocabulary cannot drift between them.
    """
    if backend not in ("auto", "scan", "pallas", "pallas-interpret"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend != "auto":
        return backend
    from ..ops import pallas_kernels as pk

    return "pallas" if structural_ok and pk.supported(dtype, n_time) else "scan"


class FitResult(NamedTuple):
    """Batched fit output: parameters + convergence diagnostics.

    ``status`` carries per-row ``reliability.FitStatus`` codes (int8): a
    plain fit reports ``OK`` (converged, finite params), ``DIVERGED``
    (optimizer failed or produced non-finite output), or ``EXCLUDED``
    (the model rejected the row structurally — too short / all NaN).  The
    resilient runner (``reliability.resilient_fit``) refines these with
    the ``SANITIZED`` / ``RETRIED`` / ``FALLBACK`` transitions.
    """

    params: jax.Array  # [batch?, k]
    neg_log_likelihood: jax.Array  # [batch?] final objective (model-defined)
    converged: jax.Array  # [batch?] bool
    iters: jax.Array  # [batch?] optimizer iterations used
    status: jax.Array = None  # [batch?] int8 FitStatus codes


def derive_status(ok, converged, params) -> jax.Array:
    """Per-row FitStatus for a plain (non-resilient) fit program.

    ``ok`` is the model's structural gate (enough valid observations to
    identify the parameters): gated-out rows are ``EXCLUDED``; rows that
    converged to finite params are ``OK``; everything else ``DIVERGED``.
    Computed inside the jitted fit program — int8 codes cost nothing next
    to the params they ride with.
    """
    from ..reliability.status import FitStatus

    good = ok & converged & jnp.all(jnp.isfinite(params), axis=-1)
    return jnp.where(
        ~ok,
        jnp.int8(FitStatus.EXCLUDED),
        jnp.where(good, jnp.int8(FitStatus.OK), jnp.int8(FitStatus.DIVERGED)),
    )


def ensure_batched(y) -> tuple[jax.Array, bool]:
    """Promote ``[time]`` to ``[1, time]``; report whether input was single."""
    y = jnp.asarray(y)
    if y.ndim == 1:
        return y[None, :], True
    if y.ndim == 2:
        return y, False
    raise ValueError(f"series must be [time] or [batch, time], got {y.shape}")


def debatch(x, single: bool):
    return jax.tree.map(lambda a: a[0], x) if single else x


def require_pallas_for_count_evals(count_evals: bool, backend: str) -> None:
    """Shared ``count_evals`` contract: pass accounting instruments the
    batched L-BFGS (``utils.optim``), which only the pallas fit paths use —
    the scan paths go through ``batched_minimize`` (vmapped per-series
    loops) where a per-iteration eval count has no batched meaning."""
    if count_evals and backend not in ("pallas", "pallas-interpret"):
        raise ValueError("count_evals requires the pallas backend "
                         f"(resolved backend: {backend!r})")


def debatch_fit(out, single: bool, count_evals: bool):
    """Unpack a fit program's ``result | (result, info)`` return shape."""
    if count_evals:
        res, info = out
        return debatch(res, single), info
    return debatch(out, single)


ALIGN_MODES = ("dense", "no-trailing", "general")


def resolve_align_mode(yb, align_mode: Optional[str] = None) -> str:
    """Resolve a fit's static alignment mode: caller hint or host probe.

    ``align_mode=None`` (the default) probes the panel on the host
    (:func:`align_mode_on_host` — one fused reduction + one host sync,
    cached per array identity).  A non-None hint skips the probe and the
    sync entirely: the chunk driver (``reliability.fit_chunked``) computes
    the panel's mode ONCE per walk and threads it into every chunk fit as
    a static argument, so a sliced walk pays zero per-chunk probe syncs.

    **Hint contract** (wrong hint = flagged rows, never silently wrong
    numbers): an unknown mode name raises ``ValueError``; a WEAKER mode
    than the data needs (``"general"`` on a dense panel) is always
    numerically correct, only slower; a STRONGER mode than the data
    supports surfaces per row — under ``"dense"`` any NaN poisons that
    row's objective (``converged=False``, status ``DIVERGED``), and under
    ``"no-trailing"`` a row whose last position is NaN is excluded
    (``n_valid=0``, NaN params, status ``EXCLUDED``) by the guard in
    :func:`maybe_align` rather than fitted against a zero-filled tail
    with an inflated valid span.
    """
    if align_mode is None:
        return align_mode_on_host(yb)
    if align_mode not in ALIGN_MODES:
        raise ValueError(
            f"unknown align_mode {align_mode!r} (one of {ALIGN_MODES})")
    return align_mode


def align_mode_on_host(yb) -> str:
    """Static alignment mode for a fit program: how much work the per-row
    right-alignment actually needs on THIS panel.

    - ``"dense"``: no NaNs anywhere — alignment is the identity.
    - ``"no-trailing"``: every series is valid at the last position, so the
      valid span already ENDS at T-1 (leading-NaN ragged series, the common
      different-start-dates panel): alignment is just prefix zeroing —
      no roll.
    - ``"general"``: trailing NaNs exist somewhere — the full per-row roll.

    Decided OUTSIDE the jitted program because the roll is the expensive
    part: vmapped ``jnp.roll`` lowers to a batched gather that costs more at
    panel scale (~0.4 s at 100k x 1k) than the entire L-BFGS loop.  The
    check is one fused reduction + one host sync — paid ONCE per array:
    jax arrays are immutable, so the mode is cached per array identity
    (a weakref guards against id reuse after GC), and repeated un-jitted
    ``fit``/``forecast`` calls on the same panel skip the device round-trip
    (VERDICT r3 item 9).  Traced inputs (``fit`` called under jit) can't be
    inspected and take the general path.
    """
    if isinstance(yb, jax.core.Tracer):
        return "general"
    key = id(yb)
    hit = _align_mode_cache.get(key)
    if hit is not None and hit[0]() is yb:
        return hit[1]
    # each probe is a device round-trip (host sync); counted so drivers can
    # verify a sliced chunk walk really paid ONE probe, not one per chunk
    obs.counter("align.host_probes").inc()
    try:
        nan_any, nan_last = _nan_probe(yb)
    except RuntimeError:
        # some backends cannot run even this tiny probe on the panel (e.g.
        # jax 0.4 CPU refuses multiprocess computations on process-spanning
        # sharded arrays): degrade to the always-correct general path
        # rather than failing the fit.  The degraded mode still enters the
        # cache below — repeat fits on the same panel must not re-pay a
        # probe that is known to fail on this array
        mode = "general"
    else:
        if not bool(nan_any):
            mode = "dense"
        else:
            mode = "no-trailing" if not bool(nan_last) else "general"
    try:
        ref = weakref.ref(yb)
    except TypeError:  # not weak-referenceable (e.g. plain numpy scalarlike)
        return mode
    if len(_align_mode_cache) >= 256:
        # drop entries whose array has been collected first; only if the
        # cache is genuinely full of LIVE arrays fall back to FIFO eviction
        # of the oldest insertions (dicts preserve insertion order) — a
        # process cycling many panels must not lose every cached mode at
        # once (ADVICE r4)
        dead = [k for k, (r, _) in _align_mode_cache.items() if r() is None]
        for k in dead:
            del _align_mode_cache[k]
        while len(_align_mode_cache) >= 256:
            del _align_mode_cache[next(iter(_align_mode_cache))]
    _align_mode_cache[key] = (ref, mode)
    return mode


_align_mode_cache: dict = {}  # id(array) -> (weakref, mode)


@jax.jit  # module-level: one compile per shape, not per call
def _nan_probe(v):
    return jnp.any(jnp.isnan(v)), jnp.any(jnp.isnan(v[:, -1]))


def maybe_align(yb, mode: str):
    """``(aligned, n_valid)`` under a static :func:`align_mode_on_host` mode."""
    if mode == "dense":
        return yb, jnp.full((yb.shape[0],), yb.shape[1], jnp.int32)
    if mode == "no-trailing":
        valid = ~jnp.isnan(yb)
        # interior NaNs are zero-filled exactly as align_right does
        first = jnp.argmax(valid, axis=1)
        nv = yb.shape[1] - first
        t = jnp.arange(yb.shape[1])[None, :]
        ya = jnp.where(t >= first[:, None], jnp.nan_to_num(yb), 0.0)
        # hint guard (resolve_align_mode contract): a row whose LAST
        # position is NaN violates "no-trailing" — exclude it (n_valid=0,
        # NaN values) instead of silently fitting a zero-filled tail with
        # an inflated valid span.  The host probe never derives this mode
        # when such rows exist, so on probe-derived panels ``bad`` is
        # all-False and the select is numerically a no-op; one column read
        # is the entire cost of making a wrong caller hint loud.
        bad = jnp.isnan(yb[:, -1])
        ya = jnp.where(bad[:, None], jnp.nan, ya)
        nv = jnp.where(bad, 0, nv)
        return ya, nv.astype(jnp.int32)
    ya, nv = jax.vmap(align_right)(yb)
    return ya, nv.astype(jnp.int32)


def align_right(y: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Shift a series' valid span to END at the last position -> ``(y', n_valid)``.

    Model fits accept series with leading/trailing NaNs (unobserved head or
    tail — the ragged-panel case of SURVEY.md §7): the valid run
    ``[first_non_nan, last_non_nan]`` is rolled so it ends at ``T-1``, padding
    positions become 0.0, and ``n_valid`` (its length, traced scalar) lets
    objectives mask the padded prefix while every shape stays static.  With
    the data right-aligned, "last value" / "last errors" logic in forecasting
    needs no dynamic indexing.

    Interior NaNs inside the valid run are replaced by 0.0 — fill them first
    (``panel.fill``) for meaningful fits.  All-NaN input yields ``n_valid=0``
    (callers flag such series as failed).
    """
    y = jnp.asarray(y)
    T = y.shape[0]
    valid = ~jnp.isnan(y)
    any_valid = jnp.any(valid)
    first = jnp.argmax(valid)
    last = T - 1 - jnp.argmax(valid[::-1])
    nv = jnp.where(any_valid, last - first + 1, 0)
    rolled = jnp.roll(y, (T - 1) - last)
    t = jnp.arange(T)
    rolled = jnp.where(t >= T - nv, rolled, 0.0)
    return jnp.nan_to_num(rolled), nv
