"""Common model interface (L4).

Mirrors the reference's ``TimeSeriesModel`` trait (SURVEY.md Section 2.2:
``addTimeDependentEffects`` / ``removeTimeDependentEffects``) as a pair of
pure functions on parameter pytrees, plus the fit-result container shared by
every model family.

Conventions:
- Every model module exposes ``fit(y, ...) -> FitResult`` accepting ``[time]``
  or ``[batch, time]`` (auto-vmapped), with all structure (orders, seasonality)
  static so one compiled computation fits the whole batch.
- ``FitResult.params`` is ``[batch?, k]``; per-series diagnostics (converged,
  iterations, final objective) ride along — the structured-diagnostics
  replacement for Spark logs (SURVEY.md Section 5.5).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class FitResult(NamedTuple):
    """Batched fit output: parameters + convergence diagnostics."""

    params: jax.Array  # [batch?, k]
    neg_log_likelihood: jax.Array  # [batch?] final objective (model-defined)
    converged: jax.Array  # [batch?] bool
    iters: jax.Array  # [batch?] optimizer iterations used


def ensure_batched(y) -> tuple[jax.Array, bool]:
    """Promote ``[time]`` to ``[1, time]``; report whether input was single."""
    y = jnp.asarray(y)
    if y.ndim == 1:
        return y[None, :], True
    if y.ndim == 2:
        return y, False
    raise ValueError(f"series must be [time] or [batch, time], got {y.shape}")


def debatch(x, single: bool):
    return jax.tree.map(lambda a: a[0], x) if single else x
