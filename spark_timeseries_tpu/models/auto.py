"""Auto model selection at panel scale (ISSUE 9 / ROADMAP item 4).

Real users rarely know their ``(p, d, q)`` — upstream spark-ts exposes
model selection as a first-class workflow, and seasonal order choice is
the paper's largest still-unreproduced scenario surface.  :func:`auto_fit`
fits a STATIC grid of candidate ARIMA (optionally seasonal SARIMA) orders
per series, computes an information criterion per (row, order) ON DEVICE,
and arg-selects per row — the batched rebuild of "loop statsmodels'
``auto_arima`` over a million series".

**Execution model.**  Each candidate order is one ordinary journaled chunk
walk (``reliability.fit_chunked`` with a ``grid=(g, G)`` coordinate on its
:class:`~..reliability.plan.ExecutionPlan`): the search therefore inherits
EVERYTHING the driver already earns — write-ahead journaling with
SIGKILL-resume that replays only uncommitted chunks (a kill mid-grid
resumes with completed orders loaded from their manifests and the
in-flight order continuing mid-walk), OOM chunk backoff, wall-clock
budgets, pipelined commits/prefetch, mesh sharding (``shard=True``), and
``ChunkSource`` streaming for larger-than-HBM panels.  Within each order's
walk the lazy stage-1/stage-2 straggler split in ``utils.optim`` does the
per-order amortization: stage 1 (the cheap lockstep sweep) runs for every
order, and the compacted stage-2 straggler program is traced/compiled/
dispatched ONLY when an order's rows actually need it.  One compiled
program per (order, chunk shape) is reused across every chunk of that
order's walk — measured by the ``compile_cache.hit``/``miss`` counters
(``utils.compile_cache``).

**Selection.**  Criteria (AICc default; AIC/BIC) are computed from each
order's concentrated CSS likelihood and the row's valid-span length in ONE
jitted program over the stacked ``[G, B]`` results — per-row argmin, tie
broken toward the earlier grid entry, no host round-trip per candidate.
Rows where no candidate produced a finite criterion come back with
``order_index = -1`` and NaN params.  The default (``stage2="full"``)
selection is bitwise-identical to an exhaustive per-order full-fit argmin
on the same panel with the same chunk layout.

**Stage-2 economy** (``stage2="winners"``): run every order at a small
stage-1 iteration budget first, rank basins per row by the stage-1
criterion, then spend the FULL budget only on each row's winning order
(gathered into ``optim.retry_cap``-aligned sub-batches, one journaled
refit walk per winning order).  Selection then follows the stage-1
ranking — documented as approximate (a basin that looks worse at the
stage-1 budget can win under full convergence) in exchange for spending
full-fit iterations on ~1/G of the (row, order) grid.

Durability artifacts: per-order journals live under
``checkpoint_dir/grid_00000/…`` (each manifest carrying an
``extra.auto_fit`` block) and the search writes a root
``auto_manifest.json`` recording orders tried, per-order stage-2 spend,
and the selection histogram — rendered/validated by
``tools/obs_report.py`` and turned into next-run knobs by
``tools/advise_budget.py``.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..utils import compile_cache as _compile_cache
from ..utils import optim
from . import arima
from .base import FitResult, jit_program

__all__ = [
    "AutoFitResult",
    "DEFAULT_ORDERS",
    "OrderSpec",
    "STEPWISE_SEED_ORDERS",
    "auto_fit",
    "criterion_matrix",
    "fusion_groups",
    "normalize_orders",
    "select_orders",
]

CRITERIA = ("aicc", "aic", "bic")

# pragmatic default grid: the low-order workhorses statsmodels' stepwise
# search visits first — differencing once covers most trending panels, and
# anything richer is cheap to pass explicitly
DEFAULT_ORDERS = (
    (1, 0, 0), (0, 0, 1), (1, 0, 1),
    (0, 1, 1), (1, 1, 0), (1, 1, 1),
)

# default seed neighborhood for the stepwise search (ISSUE 19): the four
# cheapest workhorses spanning both differencing tiers — two fused pass-0
# walks — with everything richer reached by expansion only when a row's
# winner asks for it
STEPWISE_SEED_ORDERS = (
    (1, 0, 0), (0, 0, 1), (1, 1, 0), (0, 1, 1),
)


class OrderSpec(NamedTuple):
    """One candidate on the search grid: an ARIMA order plus an optional
    multiplicative seasonal ``(P, D, Q, s)`` extension."""

    order: Tuple[int, int, int]
    seasonal: Optional[Tuple[int, int, int, int]] = None

    @property
    def label(self) -> str:
        if self.seasonal is None:
            return str(tuple(self.order))
        return f"{tuple(self.order)}x{tuple(self.seasonal)}"

    def n_params(self, include_intercept: bool) -> int:
        if self.seasonal is None:
            return arima._n_params(self.order, include_intercept)
        return arima._n_params_seasonal(self.order, self.seasonal,
                                        include_intercept)

    def lag_span(self) -> Tuple[int, int, int]:
        """``(p_full, q_full, d_full)`` of the (expanded) recursion."""
        return arima.seasonal_lag_span(self.order, self.seasonal)


def normalize_orders(orders) -> Tuple[OrderSpec, ...]:
    """Coerce a grid spec into a validated tuple of :class:`OrderSpec`.

    Accepts ``(p, d, q)`` triples, ``(p, d, q, (P, D, Q, s))`` pairs,
    ``OrderSpec`` instances, or ``None`` (the default grid).  Duplicates
    are rejected — a duplicate candidate can never win a strict argmin
    and only burns a full walk.
    """
    if orders is None:
        orders = DEFAULT_ORDERS
    specs = []
    for entry in orders:
        if isinstance(entry, OrderSpec):
            order, seasonal = entry.order, entry.seasonal
        else:
            entry = tuple(entry)
            if len(entry) == 4 and isinstance(entry[3], (tuple, list)):
                order, seasonal = entry[:3], tuple(entry[3])
            elif len(entry) == 3:
                order, seasonal = entry, None
            else:
                raise ValueError(
                    f"order spec must be (p, d, q) or (p, d, q, (P, D, Q, "
                    f"s)), got {entry!r}")
        p, d, q = (int(v) for v in order)
        if min(p, d, q) < 0:
            raise ValueError(f"orders must be >= 0, got {(p, d, q)}")
        seasonal = arima._validate_seasonal(seasonal)
        specs.append(OrderSpec((p, d, q), seasonal))
    if not specs:
        raise ValueError("orders grid is empty")
    seen = set()
    for s in specs:
        key = (s.order, s.seasonal)
        if key in seen:
            raise ValueError(f"duplicate order on the grid: {s.label}")
        seen.add(key)
    return tuple(specs)


class AutoFitResult(NamedTuple):
    """Per-row winner of the order search plus the selection record.

    ``params`` is ``[B, k_max]`` with each row's tail beyond its winning
    order's parameter count NaN-padded; ``order_index`` is the winning
    grid position (``-1``: no candidate produced a finite criterion);
    ``criterion`` is the winning criterion value per row, always
    consistent with the returned ``neg_log_likelihood`` (under
    ``stage2="winners"`` it is recomputed from the full-budget refit, so
    it is NOT comparable with stage-1 sweep values).  ``orders`` is
    the normalized grid and ``meta["auto_fit"]`` the search accounting
    (per-order spend, selection histogram, stage-2 mode).
    """

    params: np.ndarray  # [B, k_max]
    neg_log_likelihood: np.ndarray  # [B]
    converged: np.ndarray  # [B] bool
    iters: np.ndarray  # [B]
    status: np.ndarray  # [B] int8 FitStatus
    order_index: np.ndarray  # [B] int32, -1 = none eligible
    criterion: np.ndarray  # [B] winning criterion value
    orders: Tuple[OrderSpec, ...]
    meta: dict


# ---------------------------------------------------------------------------
# criterion + selection (one jitted program over the stacked grid)
# ---------------------------------------------------------------------------


def _criterion_one(nll, nv, k: int, p_full: int, d_full: int,
                   criterion: str):
    """Per-row criterion of one order from its concentrated CSS nll and
    the row's valid-span length ``nv`` (pre-differencing).  ``n_eff``
    matches the likelihood's own concentration denominator
    (``nv - d_full - p_full``); degenerate denominators and non-finite
    likelihoods map to +inf so the row cannot select this order."""
    n_eff = nv - float(d_full) - float(p_full)
    kf = float(k)
    if criterion == "bic":
        c = 2.0 * nll + kf * jnp.log(jnp.maximum(n_eff, 1.0))
        c = jnp.where(n_eff > 0, c, jnp.inf)
    else:
        c = 2.0 * nll + 2.0 * kf
        if criterion == "aicc":
            denom = n_eff - kf - 1.0
            c = c + jnp.where(
                denom > 0, 2.0 * kf * (kf + 1.0) / jnp.maximum(denom, 1.0),
                jnp.inf)
    return jnp.where(jnp.isfinite(c), c, jnp.inf)


@jit_program
def _select_program(meta: Tuple[Tuple[int, int, int], ...], criterion: str):
    """Stacked-grid criterion + per-row argmin, one compiled program.

    ``meta`` is the static per-order ``(k, p_full, d_full)`` tuple; inputs
    are the ``[G, B, k_max]`` params stack, ``[G, B]`` nll/converged/
    iters/status stacks, and the ``[B]`` valid-span lengths.  Ties break
    toward the EARLIER grid entry (``jnp.argmin`` first-min), so grid
    order is part of the selection contract.
    """

    def run(params, nll, conv, iters, status, nv0):
        nv = nv0.astype(nll.dtype)
        crit = jnp.stack([
            _criterion_one(nll[g], nv, k, p_full, d_full, criterion)
            for g, (k, p_full, d_full) in enumerate(meta)
        ])  # [G, B]
        best = jnp.argmin(crit, axis=0).astype(jnp.int32)
        bestc = jnp.min(crit, axis=0)
        has = jnp.isfinite(bestc)
        rows = jnp.arange(nll.shape[1])
        idx = jnp.where(has, best, 0)
        params_sel = jnp.where(has[:, None], params[idx, rows], jnp.nan)
        nll_sel = jnp.where(has, nll[idx, rows], jnp.nan)
        conv_sel = conv[idx, rows] & has
        iters_sel = jnp.where(has, iters[idx, rows], 0)
        # a row with no eligible candidate keeps the WORST thing that
        # happened to it anywhere on the grid (codes are severity-ordered)
        status_sel = jnp.where(has, status[idx, rows],
                               jnp.max(status, axis=0))
        order_idx = jnp.where(has, best, jnp.int32(-1))
        counts = jnp.stack(
            [jnp.sum(order_idx == g) for g in range(len(meta))]
            + [jnp.sum(~has)]).astype(jnp.int32)
        crit_sel = jnp.where(has, bestc, jnp.nan)
        return (params_sel, nll_sel, conv_sel, iters_sel, status_sel,
                order_idx, crit_sel, crit, counts)

    return run


def criterion_matrix(specs, nll_stack, nv0, *, criterion: str = "aicc",
                     include_intercept: bool = True):
    """``[G, B]`` criterion values for a stacked grid of fit results —
    the standalone spelling of the selection program's first half, shared
    with the exhaustive-argmin reference in tests."""
    specs = normalize_orders(specs)
    nll_stack = jnp.asarray(nll_stack)
    nv = jnp.asarray(nv0).astype(nll_stack.dtype)
    rows = []
    for spec in specs:
        p_full, _, d_full = spec.lag_span()
        rows.append(_criterion_one(
            nll_stack[len(rows)], nv, spec.n_params(include_intercept),
            p_full, d_full, criterion))
    return jnp.stack(rows)


def select_orders(specs, results, nv0, *, criterion: str = "aicc",
                  include_intercept: bool = True):
    """Run the on-device selection over per-order fit results.

    ``results`` is a sequence (one per order, grid order) of objects with
    ``params`` / ``neg_log_likelihood`` / ``converged`` / ``iters`` /
    ``status`` arrays (``FitResult`` and ``ResilientFitResult`` both
    qualify); ``nv0`` is the ``[B]`` per-row valid-span length
    (:func:`panel_n_valid`).  Returns the host-side selection dict the
    :func:`auto_fit` result is assembled from — and IS the exhaustive
    argmin when the results are exhaustive full fits, which is exactly
    how the bitwise acceptance test uses it.
    """
    specs = normalize_orders(specs)
    if len(results) != len(specs):
        raise ValueError(f"{len(specs)} orders but {len(results)} results")
    if criterion not in CRITERIA:
        raise ValueError(f"unknown criterion {criterion!r} "
                         f"(one of {CRITERIA})")
    kmax = max(s.n_params(include_intercept) for s in specs)
    b = np.asarray(results[0].neg_log_likelihood).shape[0]
    dtype = np.asarray(results[0].neg_log_likelihood).dtype
    params = np.full((len(specs), b, kmax), np.nan, dtype)
    nll = np.empty((len(specs), b), dtype)
    conv = np.empty((len(specs), b), bool)
    iters = np.empty((len(specs), b), np.int32)
    status = np.empty((len(specs), b), np.int8)
    for g, (spec, res) in enumerate(zip(specs, results)):
        k = spec.n_params(include_intercept)
        rp = np.asarray(res.params)
        # an all-TIMEOUT walk synthesizes width-1 NaN params (the driver
        # never learned the real k); those rows' NaN nll keeps them
        # unselectable, so the narrow copy is purely defensive
        w = min(k, rp.shape[1])
        params[g, :, :w] = rp[:, :w]
        nll[g] = np.asarray(res.neg_log_likelihood)
        conv[g] = np.asarray(res.converged)
        iters[g] = np.asarray(res.iters, np.int32)
        status[g] = np.asarray(res.status, np.int8)
    meta = []
    for s in specs:
        p_full, _, d_full = s.lag_span()
        meta.append((s.n_params(include_intercept), p_full, d_full))
    meta = tuple(meta)
    out = _select_program(meta, criterion)(
        jnp.asarray(params), jnp.asarray(nll), jnp.asarray(conv),
        jnp.asarray(iters), jnp.asarray(status),
        jnp.asarray(np.asarray(nv0, np.int32)))
    (params_sel, nll_sel, conv_sel, iters_sel, status_sel, order_idx,
     crit_sel, crit, counts) = (np.asarray(a) for a in out)
    return {
        "params": params_sel,
        "neg_log_likelihood": nll_sel,
        "converged": conv_sel,
        "iters": iters_sel,
        "status": status_sel.astype(np.int8),
        "order_index": order_idx,
        "criterion": crit_sel,
        "criteria_matrix": crit,
        "counts": counts,
    }


def panel_n_valid(y) -> np.ndarray:
    """``[B] int32`` valid-span length per row: ``last_non_nan -
    first_non_nan + 1`` (0 for all-NaN rows) — the one row property every
    criterion on the grid shares, identical to the span
    ``base.align_right`` fits against.  Accepts a device/host array or a
    ``reliability.source.ChunkSource`` (streamed on the host, so an
    oversubscribed panel never touches the device for this)."""
    from ..reliability import source as source_mod

    if isinstance(y, source_mod.ChunkSource):
        b, t = y.shape
        out = np.empty((b,), np.int32)
        step = max(1, int(y.default_chunk_rows or 4096))
        buf = np.empty((step, t), y.dtype)
        for lo in range(0, b, step):
            hi = min(lo + step, b)
            y.read_rows(lo, hi, buf[: hi - lo])
            out[lo:hi] = _nv_host(buf[: hi - lo])
        return out
    if isinstance(y, jax.Array) and not isinstance(y, jax.core.Tracer):
        return np.asarray(_nv_program()(y), np.int32)
    return _nv_host(np.asarray(y))


def _nv_host(y: np.ndarray) -> np.ndarray:
    valid = ~np.isnan(y)
    any_valid = valid.any(axis=1)
    first = valid.argmax(axis=1)
    last = y.shape[1] - 1 - valid[:, ::-1].argmax(axis=1)
    return np.where(any_valid, last - first + 1, 0).astype(np.int32)


@jit_program
def _nv_program():
    def run(yb):
        valid = ~jnp.isnan(yb)
        any_valid = jnp.any(valid, axis=1)
        first = jnp.argmax(valid, axis=1)
        last = yb.shape[1] - 1 - jnp.argmax(valid[:, ::-1], axis=1)
        return jnp.where(any_valid, last - first + 1, 0).astype(jnp.int32)

    return run


# ---------------------------------------------------------------------------
# fused order execution (ISSUE 10): the grid as a batch axis, not a loop
# ---------------------------------------------------------------------------


def fusion_groups(orders, fuse="auto"):
    """Partition a grid into same-``d`` fusion groups of width <= ``fuse``.

    Each group fits as ONE ``fit_chunked`` walk through the fused grid
    program (``models.arima.fit_grid``) — every chunk is staged,
    prefetched, and journaled once for the whole group instead of once
    per order.  ``fuse="auto"`` fuses each ``d``'s orders into one group;
    an int caps group width (``fuse=1``: one singleton per order — the
    bitwise per-order search).  Groups are ordered by their first grid
    index, and a search walks them in that order, so the cost model is
    ``walks = sum over d of ceil(G_d / K)``.
    """
    specs = normalize_orders(orders)
    if fuse != "auto":
        fuse = int(fuse)
        if fuse < 1:
            raise ValueError(f"fuse must be >= 1 or 'auto', got {fuse}")
    if fuse == 1:
        return tuple((g,) for g in range(len(specs)))
    cap = None if fuse == "auto" else fuse
    by_d: dict = {}
    for g, s in enumerate(specs):
        by_d.setdefault(s.order[1], []).append(g)
    groups = []
    for gs in by_d.values():
        step = cap or len(gs)
        for lo in range(0, len(gs), step):
            groups.append(tuple(gs[lo: lo + step]))
    groups.sort(key=lambda m: m[0])
    return tuple(groups)


def _grid_diff_cache_hits(specs, groups) -> int:
    """Differencings the shared-prep cache saves across the whole search:
    per fused group, every order beyond its first (d, D, s) signature
    reads the cached differenced panel instead of re-differencing."""
    return sum(
        len(m) - arima.grid_diff_cache_keys(
            tuple((specs[g].order, specs[g].seasonal) for g in m))
        for m in groups if len(m) > 1)


def _demux_fused(res, gspecs, include_intercept: bool):
    """Unpack a fused walk's packed-wide result into per-order results.

    ``res.params`` is the ``[B, K*(k_max + GRID_PACK_COLS)]`` pack
    ``fit_grid`` built (per order: params, nll, eligible, converged,
    iters, status — all-finite; the NaN conventions are restored here
    from the eligibility/status columns) — possibly resumed
    byte-identically from the journal; the row-level ``res.status``
    flags TIMEOUT rows the driver synthesized without dispatch (their
    pack bytes are NaN).  Returns one :class:`~.base.FitResult` of host
    arrays per order, in group order — exactly what
    :func:`select_orders` consumes.
    """
    from ..reliability.status import FitStatus

    k_max = max(s.n_params(include_intercept) for s in gspecs)
    wb = k_max + arima.GRID_PACK_COLS
    wide = np.asarray(res.params)
    b = wide.shape[0]
    row_status = np.asarray(res.status)
    timeout = row_status == int(FitStatus.TIMEOUT)
    # resilient transitions are ROW-wide facts: the sanitizer repaired the
    # row's data and the retry ladder refit the whole packed row, so a
    # SANITIZED/RETRIED/FALLBACK mark lifts every order's pack status
    # (severity max — a repair never downgrades a DIVERGED)
    repair = np.where(
        (row_status >= int(FitStatus.SANITIZED))
        & (row_status <= int(FitStatus.FALLBACK)),
        row_status, 0).astype(np.int8)
    if wide.shape[1] != len(gspecs) * wb:
        # an all-TIMEOUT walk never finished a chunk: the driver learned
        # no pack width and synthesized width-1 NaN params
        return [FitResult(
            np.full((b, k_max), np.nan, wide.dtype),
            np.full(b, np.nan, wide.dtype),
            np.zeros(b, bool), np.zeros(b, np.int32),
            np.full(b, int(FitStatus.TIMEOUT), np.int8),
        ) for _ in gspecs]
    out = []
    for j, spec in enumerate(gspecs):
        blk = wide[:, j * wb: (j + 1) * wb]
        params = np.array(blk[:, :k_max])
        nll = np.array(blk[:, k_max])
        eligf = blk[:, k_max + 1]
        convf = blk[:, k_max + 2]
        itf = blk[:, k_max + 3]
        stf = blk[:, k_max + 4]
        elig = np.isfinite(eligf) & (eligf != 0)
        conv = np.isfinite(convf) & (convf != 0)
        iters = np.where(np.isfinite(itf), itf, 0).astype(np.int32)
        status = np.where(np.isfinite(stf), stf,
                          float(FitStatus.DIVERGED)).astype(np.int8)
        status = np.maximum(status, repair)
        # restore the per-order NaN conventions the pack flattened (the
        # pack is all-finite for the resilient runner's row mask): an
        # ineligible order carries NaN nll (criterion: unselectable), an
        # excluded row NaN params, and every order NaN beyond its own k
        nll[~elig] = np.nan
        params[status == int(FitStatus.EXCLUDED)] = np.nan
        params[:, spec.n_params(include_intercept):] = np.nan
        if timeout.any():
            params[timeout] = np.nan
            nll[timeout] = np.nan
            conv = conv & ~timeout
            iters[timeout] = 0
            status[timeout] = int(FitStatus.TIMEOUT)
        out.append(FitResult(params, nll, conv, iters, status))
    return out


# ---------------------------------------------------------------------------
# the search driver
# ---------------------------------------------------------------------------


def _order_fit_fn(spec: OrderSpec, include_intercept: bool, fit_kwargs: dict):
    """The per-order fit partial handed to ``fit_chunked`` — keyword-bound
    so the journal's config hash covers the order AND every hyperknob."""
    kw = dict(fit_kwargs)
    if spec.seasonal is not None:
        kw["seasonal"] = spec.seasonal
    return functools.partial(arima.fit, order=spec.order,
                             include_intercept=include_intercept, **kw)


def _grid_dir(checkpoint_dir: Optional[str], g: int,
              stage: str = "") -> Optional[str]:
    if checkpoint_dir is None:
        return None
    return os.path.join(checkpoint_dir, f"grid_{g:05d}{stage}")


def _remaining_budget(job_budget_s: Optional[float],
                      t0: float) -> Optional[float]:
    """The job budget LEFT for the next order's walk: the whole search
    shares one wall-clock allowance, so orders dispatched after it is
    spent mark their chunks TIMEOUT without dispatch (the driver's
    normal budget semantics) instead of running unbounded."""
    if job_budget_s is None:
        return None
    return max(1e-6, job_budget_s - (time.perf_counter() - t0))


def auto_fit(
    y,
    orders=None,
    *,
    criterion: str = "aicc",
    include_intercept: bool = True,
    stage2: str = "full",
    stage1_iters: int = 12,
    fuse="auto",
    stepwise: bool = False,
    stepwise_max_passes: int = 8,
    stepwise_max_order: int = 3,
    return_criteria: bool = False,
    chunk_rows: Optional[int] = None,
    resilient: bool = False,
    policy: str = "impute",
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    align_mode: Optional[str] = None,
    shard: bool = False,
    mesh=None,
    _journal_commit_hook=None,
    **fit_kwargs,
) -> AutoFitResult:
    """Batched order search over ``y [B, T]`` (array or ``ChunkSource``).

    Fits every candidate on ``orders`` (default :data:`DEFAULT_ORDERS`;
    entries ``(p, d, q)`` or ``(p, d, q, (P, D, Q, s))`` for seasonal
    SARIMA candidates) as one journaled chunk walk per order, computes
    ``criterion`` (``"aicc"`` default, ``"aic"``/``"bic"``) per (row,
    order) on device, and arg-selects per row.  All ``fit_chunked`` knobs
    ride through per order (``checkpoint_dir`` fans out into per-order
    ``grid_00000/…`` journals; ``job_budget_s`` bounds the WHOLE search);
    remaining ``fit_kwargs`` (``max_iters``, ``backend``, ``method``,
    ``tol``, ...) go to every order's ``models.arima.fit``.

    **Fused execution** (``fuse``, ISSUE 10): the candidate grid is a
    batch dimension, not a loop — orders sharing the plain differencing
    order ``d`` are fused into groups of at most ``fuse`` candidates
    (``"auto"``, the default: each ``d``'s orders fuse into one group),
    and each group fits as ONE journaled walk through the padded-
    polynomial grid program (``models.arima.fit_grid``), so every chunk
    is staged/prefetched/journaled once for K orders instead of K times
    and orders sharing a ``(d, D, s)`` differencing signature difference
    the panel once (``meta["auto_fit"]["diff_cache_hits"]``).  Fused
    walks run the scan backend; selection over a fused group agrees with
    the per-order search (tested) but is not bitwise (padded coefficient
    slots, shared lockstep loop).  Resilient fused searches retry per
    ROW, not per (row, order): the ladder fires only for rows with NO
    usable candidate (a single stubborn order neither sends the row
    through the ladder nor wipes the orders that did fit) — per-candidate
    rescue is ``fuse=1``'s contract.  ``fuse=1`` restores the per-order
    walks BITWISE — including the exhaustive-argmin selection identity
    and the PR 8 journal layout.

    ``stage2="full"`` (default): every order is fully fit — with
    ``fuse=1`` the selection is bitwise-identical to an exhaustive
    per-order full-fit argmin on the same panel/chunk layout, and the
    stage-1/stage-2 economy lives inside each fit (the lazy straggler
    split only compiles/dispatches an order's stage-2 program when rows
    actually need it).
    ``stage2="winners"``: sweep every order at ``stage1_iters`` first,
    rank per row, then spend the full budget only on each row's winning
    order — approximate selection, full-quality winning params, with the
    stage-2 spend recorded per order in ``meta["auto_fit"]``.  Fused
    searches run the repaired economy: rows grouped by winning order,
    one warm-started batched refit dispatch per basin slice
    (``retry_cap``-aligned, initialized from the journaled stage-1
    params), instead of PR 8's per-order full sub-walks; the refits are
    deterministic functions of the journaled stage-1 results, so a
    resumed search recomputes them identically (they are not separately
    journaled).  ``fuse=1`` keeps PR 8's journaled refit walks bitwise.

    **Stepwise search** (``stepwise=True``, ISSUE 19): instead of
    fitting a static grid exhaustively, run the Hyndman–Khandakar
    expansion — fit a small seed neighborhood (``orders``, default
    :data:`STEPWISE_SEED_ORDERS`) as fused full-budget walks, expand
    ``p``/``q`` by ±1 (``d`` fixed, capped at ``stepwise_max_order``)
    around the per-row winners, and repeat until a pass's new orders win
    zero rows or ``stepwise_max_passes`` is reached.  Each pass is an
    ordinary journaled campaign under ``checkpoint_dir/stepwise_%02d/``:
    SIGKILL anywhere and a re-run resumes — completed passes load from
    their journals bitwise, the expansion (a deterministic function of
    the journaled results) replays identically, and the torn pass
    continues mid-walk.  Selection runs over ALL orders tried, with grid
    indices in global trial order, so agreement with the exhaustive
    search on the union grid is exact whenever the expansion visited
    every row's exhaustive winner (tested on well-separated panels).
    Requires ``stage2="full"`` and non-seasonal candidates; the
    exhaustive path (``stepwise=False``) is untouched as the reference
    implementation.

    Durable: SIGKILL anywhere — mid-chunk, mid-group, between groups —
    and a re-run with the same panel/grid/config resumes from the
    per-group journals, replaying only uncommitted chunks, with selection
    (recomputed from the full grid) bitwise-identical to an uninterrupted
    search.  A root ``auto_manifest.json`` records orders tried, fusion
    groups, per-order spend, and the selection histogram for the tools.
    """
    if orders is None and stepwise:
        orders = STEPWISE_SEED_ORDERS
    specs = normalize_orders(orders)
    if criterion not in CRITERIA:
        raise ValueError(f"unknown criterion {criterion!r} "
                         f"(one of {CRITERIA})")
    if stage2 not in ("full", "winners"):
        raise ValueError(f"stage2 must be 'full' or 'winners', got "
                         f"{stage2!r}")
    if stage2 == "winners" and int(stage1_iters) < 1:
        raise ValueError("stage1_iters must be >= 1")
    if stepwise:
        if stage2 != "full":
            raise ValueError(
                "stepwise search requires stage2='full' — the restricted "
                "grid IS its economy; the winners split composes with "
                "exhaustive grids only")
        if int(stepwise_max_passes) < 1:
            raise ValueError("stepwise_max_passes must be >= 1")
        if int(stepwise_max_order) < 0:
            raise ValueError("stepwise_max_order must be >= 0")
        if any(s.seasonal is not None for s in specs):
            raise ValueError(
                "stepwise expansion is defined on plain (p, d, q) orders; "
                "pass seasonal candidates on an explicit exhaustive grid")
        bad = [s.label for s in specs
               if max(s.order[0], s.order[2]) > int(stepwise_max_order)]
        if bad:
            raise ValueError(
                f"seed orders {bad} exceed stepwise_max_order="
                f"{int(stepwise_max_order)}")
    groups = fusion_groups(specs, fuse)
    if any(len(m) > 1 for m in groups) or (stepwise and fuse != 1):
        bad = sorted(set(fit_kwargs) - {"max_iters", "tol", "backend",
                                        "method"})
        if bad:
            raise ValueError(
                f"fit kwargs {bad} are not supported by the fused grid "
                "program; pass fuse=1 for the per-order search")
        if fit_kwargs.get("backend", "auto") not in ("auto", "scan"):
            raise ValueError(
                "fused groups run on the portable scan backend; pass "
                "fuse=1 to search per order with backend="
                f"{fit_kwargs['backend']!r}")
    diff_cache_hits = _grid_diff_cache_hits(specs, groups)
    from ..reliability import fit_chunked
    from ..reliability import source as source_mod

    if isinstance(y, source_mod.ChunkSource):
        values = y
        b = int(y.shape[0])
    else:
        values = jnp.asarray(y)
        if values.ndim != 2:
            raise ValueError(
                f"auto_fit expects [batch, time], got {values.shape}")
        b = int(values.shape[0])
    nv0 = panel_n_valid(values)
    g_total = len(specs)
    t0 = time.perf_counter()
    cc0 = _compile_cache.program_cache_stats()
    tele = obs.enabled()

    walk_knobs = dict(
        chunk_rows=chunk_rows, resilient=resilient, policy=policy,
        resume=resume, chunk_budget_s=chunk_budget_s,
        pipeline=pipeline, pipeline_depth=pipeline_depth,
        prefetch_depth=prefetch_depth, align_mode=align_mode,
        shard=shard, mesh=mesh, _journal_commit_hook=_journal_commit_hook,
    )

    def _walk(spec, g, ckpt, *, stage_tag, max_iters_override=None,
              vals=None):
        """One order's walk — the full panel by default, or a gathered
        sub-panel (``vals``, the winners refit).  EVERY walk inherits the
        caller's knobs (resilient/policy/align_mode/budgets/pipeline/
        shard) so a stage-2 refit fits its rows under the same contract
        the stage-1 sweep did; the align hint stays valid on any row
        subset (it is a row-wise property of the panel)."""
        kw = dict(fit_kwargs)
        if max_iters_override is not None:
            kw["max_iters"] = max_iters_override
        fit_fn = _order_fit_fn(spec, include_intercept, kw)
        extra = {"auto_fit": {
            "grid_index": g, "grid_total": g_total,
            "order": list(spec.order),
            "seasonal": (list(spec.seasonal) if spec.seasonal is not None
                         else None),
            "criterion": criterion, "stage": stage_tag,
        }}
        with obs.span("auto_fit.order", grid=g, order=spec.label,
                      stage=stage_tag):
            t_g = time.perf_counter()
            res = fit_chunked(
                fit_fn, values if vals is None else vals,
                checkpoint_dir=ckpt, grid=(g, g_total),
                job_budget_s=_remaining_budget(job_budget_s, t0),
                journal_extra=extra, **walk_knobs)
            wall = time.perf_counter() - t_g
        return res, wall

    def _walk_fused(members, ckpt, *, stage_tag, max_iters_override=None):
        """One fusion GROUP's walk: K same-d orders through ONE journaled
        fit_chunked campaign (models.arima.fit_grid) — chunks carry the
        whole group, staged/committed once for all K orders, under the
        same knobs/budgets as a per-order walk."""
        kw = dict(fit_kwargs)
        if max_iters_override is not None:
            kw["max_iters"] = max_iters_override
        gspecs = tuple((specs[g].order, specs[g].seasonal) for g in members)
        fit_fn = functools.partial(
            arima.fit_grid, specs=gspecs,
            include_intercept=include_intercept, **kw)
        extra = {"auto_fit": {
            "grid_index": members[0], "grid_total": g_total,
            "fused_orders": list(members),
            "orders": [list(specs[g].order) for g in members],
            "seasonals": [(list(specs[g].seasonal)
                           if specs[g].seasonal is not None else None)
                          for g in members],
            "criterion": criterion, "stage": stage_tag,
            "fuse": len(members),
        }}
        label = "+".join(specs[g].label for g in members)
        with obs.span("auto_fit.order", grid=members[0], order=label,
                      stage=stage_tag, fused=len(members)):
            t_g = time.perf_counter()
            res = fit_chunked(
                fit_fn, values,
                checkpoint_dir=ckpt,
                grid=(members[0], g_total, tuple(members)),
                job_budget_s=_remaining_budget(job_budget_s, t0),
                journal_extra=extra, **walk_knobs)
            wall = time.perf_counter() - t_g
        return res, wall

    def _order_entry(g, wall, res, *, stage2_traces=None, fused_with=None):
        spec = specs[g]
        entry = {
            "grid_index": g,
            "order": list(spec.order),
            "seasonal": (list(spec.seasonal)
                         if spec.seasonal is not None else None),
            "label": spec.label,
            "k": spec.n_params(include_intercept),
            "wall_s": round(wall, 4),
            "chunks_run": res.meta.get("chunks_run"),
            "rows_fit": b,
            "stage2_traces": stage2_traces,
            "timeouts": res.meta.get("timeouts", 0),
        }
        if fused_with is not None:
            entry["fused_group"] = fused_with[0]
            entry["fused_width"] = len(fused_with)
        return entry

    order_meta = []
    stepwise_meta = None
    sw_groups = ()
    if stepwise:
        seed_labels = [s.label for s in specs]
        (sel, specs, order_meta, passes_meta, stage1_wall, sw_groups,
         sw_diff_hits, sw_converged) = _stepwise_search(
            specs, values, nv0, b, criterion, include_intercept, fuse,
            checkpoint_dir, stepwise_max_passes, stepwise_max_order,
            fit_kwargs, walk_knobs,
            budget_left=(None if job_budget_s is None else
                         lambda: job_budget_s
                         - (time.perf_counter() - t0)))
        g_total = len(specs)
        stage2_wall = 0.0
        diff_cache_hits = sw_diff_hits
        stepwise_meta = {
            "passes": passes_meta,
            "max_passes": int(stepwise_max_passes),
            "max_order": int(stepwise_max_order),
            "seed": seed_labels,
            "converged": sw_converged,
            "orders_tried": g_total,
        }
    elif stage2 == "full":
        results = [None] * g_total
        for members in groups:
            if len(members) == 1:
                g = members[0]
                s2_0 = ((obs.snapshot() or {}).get("counters", {})
                        if tele else {})
                res, wall = _walk(specs[g], g, _grid_dir(checkpoint_dir, g),
                                  stage_tag="full")
                s2_1 = ((obs.snapshot() or {}).get("counters", {})
                        if tele else {})
                results[g] = res
                order_meta.append(_order_entry(
                    g, wall, res,
                    stage2_traces=(
                        s2_1.get("optim.stage2_compact_traces", 0)
                        - s2_0.get("optim.stage2_compact_traces", 0))
                    if tele else None))
            else:
                res, wall = _walk_fused(
                    members, _grid_dir(checkpoint_dir, members[0]),
                    stage_tag="full")
                per = _demux_fused(res, [specs[g] for g in members],
                                   include_intercept)
                for j, g in enumerate(members):
                    results[g] = per[j]
                    order_meta.append(_order_entry(
                        g, wall / len(members), res, fused_with=members))
        order_meta.sort(key=lambda m: m["grid_index"])
        sel = select_orders(specs, results, nv0, criterion=criterion,
                            include_intercept=include_intercept)
        stage1_wall = sum(m["wall_s"] for m in order_meta)
        stage2_wall = 0.0
    elif fuse == 1:
        # PR 8's economy, kept bitwise for the fuse=1 escape hatch
        sel, order_meta, stage1_wall, stage2_wall = _winners_search(
            specs, values, nv0, b, criterion, include_intercept,
            stage1_iters, checkpoint_dir, _walk)
    else:
        sel, order_meta, stage1_wall, stage2_wall = _winners_search_fused(
            specs, groups, values, nv0, b, criterion, include_intercept,
            stage1_iters, checkpoint_dir, _walk, _walk_fused, _order_entry,
            fit_kwargs=fit_kwargs, resilient=resilient, policy=policy,
            chunk_rows=chunk_rows, align_mode=align_mode,
            budget_left=(None if job_budget_s is None else
                         lambda: job_budget_s
                         - (time.perf_counter() - t0)))

    counts = sel["counts"]
    for m in order_meta:
        m["selected_rows"] = int(counts[m["grid_index"]])
    selection_counts = {specs[g].label: int(counts[g])
                        for g in range(g_total)}
    selection_counts["none"] = int(counts[g_total])
    cc1 = _compile_cache.program_cache_stats()
    cc_hits = cc1["hits"] - cc0["hits"]
    cc_misses = cc1["misses"] - cc0["misses"]
    total_wall = time.perf_counter() - t0
    stage_suffix = "" if stage2 == "full" else "_s1"
    if stepwise:
        fusion_meta = [
            {"dir": f"stepwise_{p:02d}/grid_{m[0]:05d}", "orders": list(m),
             "stepwise_pass": p}
            for p, m in sw_groups]
    else:
        fusion_meta = [
            {"dir": f"grid_{m[0]:05d}{stage_suffix}", "orders": list(m)}
            for m in groups]
    auto_meta = {
        "criterion": criterion,
        "stage2": stage2,
        "stage1_iters": stage1_iters if stage2 == "winners" else None,
        "fuse": fuse if fuse == "auto" else int(fuse),
        "stepwise": stepwise_meta,
        "fusion_groups": fusion_meta,
        "diff_cache_hits": diff_cache_hits,
        "n_rows": b,
        "orders": order_meta,
        "selection_counts": selection_counts,
        "wall_s": round(total_wall, 4),
        "stage1_wall_s": round(stage1_wall, 4),
        "stage2_wall_s": round(stage2_wall, 4),
        "stage2_spend_share": (
            round(stage2_wall / max(stage1_wall + stage2_wall, 1e-9), 4)),
        "compile_cache": {
            "hits": cc_hits, "misses": cc_misses,
            "hit_rate": (round(cc_hits / (cc_hits + cc_misses), 4)
                         if (cc_hits + cc_misses) else None)},
    }
    meta = {"auto_fit": auto_meta}
    if return_criteria:
        meta["criteria_matrix"] = sel["criteria_matrix"]
    if checkpoint_dir is not None:
        # the dirs THIS search used, derived from its own plan (never a
        # disk glob: a previous search in the same directory — e.g. a
        # full run before a winners run — must not be advertised as part
        # of this one, or the tools would read the wrong journals).  A
        # fused search walks one dir per fusion GROUP, named by the
        # group's first grid index; fused winners refits are warm-started
        # recomputations of the journaled stage-1 sweeps, so only fuse=1
        # leaves grid_*_winners journals behind.  A stepwise search walks
        # one dir per (pass, group) under stepwise_%02d/ namespaces.
        if stepwise:
            grid_dirs = [fm["dir"] for fm in fusion_meta]
        else:
            grid_dirs = [f"grid_{m[0]:05d}{stage_suffix}" for m in groups]
            if stage2 == "winners" and fuse == 1:
                grid_dirs += [f"grid_{m['grid_index']:05d}_winners"
                              for m in order_meta
                              if m.get("stage2_rows")]
        _write_auto_manifest(checkpoint_dir, auto_meta, sorted(grid_dirs))
        meta["auto_manifest"] = os.path.join(checkpoint_dir,
                                             "auto_manifest.json")
    obs.counter("auto_fit.searches").inc()
    obs.event("auto_fit.selected", orders=g_total, rows=b,
              none=selection_counts["none"])
    return AutoFitResult(
        sel["params"], sel["neg_log_likelihood"], sel["converged"],
        sel["iters"], sel["status"], sel["order_index"], sel["criterion"],
        specs, meta)


def _winners_search(specs, values, nv0, b, criterion, include_intercept,
                    stage1_iters, checkpoint_dir, _walk):
    """The ``stage2="winners"`` economy: rank on cheap stage-1 sweeps,
    spend the full budget only on each row's winning order."""
    g_total = len(specs)
    order_meta = []
    stage1_results = []
    stage1_wall = 0.0
    for g, spec in enumerate(specs):
        res, wall = _walk(spec, g, _grid_dir(checkpoint_dir, g, "_s1"),
                          stage_tag="stage1",
                          max_iters_override=stage1_iters)
        stage1_results.append(res)
        stage1_wall += wall
        order_meta.append({
            "grid_index": g,
            "order": list(spec.order),
            "seasonal": (list(spec.seasonal)
                         if spec.seasonal is not None else None),
            "label": spec.label,
            "k": spec.n_params(include_intercept),
            "wall_s": round(wall, 4),
            "chunks_run": res.meta.get("chunks_run"),
            "rows_fit": b,
            "stage2_traces": None,
            "timeouts": res.meta.get("timeouts", 0),
        })
    sel = select_orders(specs, stage1_results, nv0, criterion=criterion,
                        include_intercept=include_intercept)
    # the winner refits scatter into the selection arrays: make them
    # writable host copies (device-backed np views are read-only)
    for key in ("params", "neg_log_likelihood", "converged", "iters",
                "status", "criterion"):
        sel[key] = np.array(sel[key])
    order_idx = sel["order_index"]
    stage2_wall = 0.0
    # refit each winning order's rows at the FULL budget: gathered into a
    # retry_cap-aligned sub-batch (bounded compiled shapes — the resilient
    # ladder's contract) and scattered back over the stage-1 selection.
    # The refit walk runs under the SAME knobs as the sweeps (resilient
    # ladder, align hint, budgets, pipeline) via _walk, journaled under
    # grid_{g}_winners — the sub-panel is a deterministic function of the
    # journaled stage-1 results, so a resumed search gathers the same
    # rows and the journal fingerprint matches.
    for g, spec in enumerate(specs):
        rows = np.nonzero(order_idx == g)[0]
        if rows.size == 0:
            order_meta[g]["stage2_rows"] = 0
            continue
        cap = optim.retry_cap(rows.size)
        pad_idx = optim.gather_pad_indices(rows, cap)
        sub = _gather_rows(values, pad_idx)
        res, wall = _walk(spec, g, _grid_dir(checkpoint_dir, g, "_winners"),
                          stage_tag="winners", vals=sub)
        stage2_wall += wall
        keep = np.arange(rows.size)
        k = spec.n_params(include_intercept)
        sel["params"][rows, :k] = np.asarray(res.params)[keep]
        sel["params"][rows, k:] = np.nan
        sel["neg_log_likelihood"][rows] = np.asarray(
            res.neg_log_likelihood)[keep]
        sel["converged"][rows] = np.asarray(res.converged)[keep]
        sel["iters"][rows] = np.asarray(res.iters)[keep]
        sel["status"][rows] = np.asarray(res.status)[keep]
        # the reported criterion must match the RETURNED nll, not the
        # truncated stage-1 sweep's — recompute it from the refit (NaN
        # where the refit itself diverged: the row keeps its selection
        # but carries no comparable criterion value)
        p_full, _, d_full = spec.lag_span()
        crit = np.asarray(_criterion_one(
            jnp.asarray(sel["neg_log_likelihood"][rows]),
            jnp.asarray(np.asarray(nv0)[rows].astype(
                sel["neg_log_likelihood"].dtype)),
            k, p_full, d_full, criterion))
        sel["criterion"][rows] = np.where(np.isfinite(crit), crit, np.nan)
        order_meta[g]["stage2_rows"] = int(rows.size)
        order_meta[g]["stage2_wall_s"] = round(wall, 4)
    return sel, order_meta, stage1_wall, stage2_wall


def _winners_search_fused(specs, groups, values, nv0, b, criterion,
                          include_intercept, stage1_iters, checkpoint_dir,
                          _walk, _walk_fused, _order_entry, *, fit_kwargs,
                          resilient, policy, chunk_rows, align_mode,
                          budget_left=None):
    """The repaired ``stage2="winners"`` economy (ISSUE 10): fused stage-1
    sweeps, then ONE warm-started batched refit per basin slice.

    PR 8's economy re-ran a full ``fit_chunked`` campaign per winning
    order, each against fresh sub-batch shapes — at bench scale the
    recompiles made the "economy" 18x SLOWER than the exhaustive search
    (``winners_speedup: 0.0538``).  Here stage 1 rides the fused group
    walks at ``stage1_iters`` (journaled under ``grid_*_s1``), and stage
    2 groups rows by their winning order and dispatches each basin as
    compacted ``retry_cap``-aligned batched refits initialized from the
    stage-1 params — a handful of cheap warm-started dispatches instead
    of G driver campaigns.  The refits are deterministic functions of
    the journaled stage-1 results (same gather, same init, same
    program), so a SIGKILLed search resumes the sweeps from their
    journals and recomputes identical refits.
    """
    g_total = len(specs)
    results = [None] * g_total
    order_meta = []
    stage1_wall = 0.0
    for members in groups:
        if len(members) == 1:
            g = members[0]
            res, wall = _walk(specs[g], g,
                              _grid_dir(checkpoint_dir, g, "_s1"),
                              stage_tag="stage1",
                              max_iters_override=stage1_iters)
            results[g] = res
            order_meta.append(_order_entry(g, wall, res))
        else:
            res, wall = _walk_fused(
                members, _grid_dir(checkpoint_dir, members[0], "_s1"),
                stage_tag="stage1", max_iters_override=stage1_iters)
            per = _demux_fused(res, [specs[g] for g in members],
                               include_intercept)
            for j, g in enumerate(members):
                results[g] = per[j]
                order_meta.append(_order_entry(
                    g, wall / len(members), res, fused_with=members))
        stage1_wall += wall
    order_meta.sort(key=lambda m: m["grid_index"])
    sel = select_orders(specs, results, nv0, criterion=criterion,
                        include_intercept=include_intercept)
    for key in ("params", "neg_log_likelihood", "converged", "iters",
                "status", "criterion"):
        sel[key] = np.array(sel[key])
    order_idx = sel["order_index"]
    # the refits fit row subsets of the panel; its alignment mode is a
    # row-wise property, so the panel-level answer is exact for every
    # basin (and the per-array probe cache means an in-HBM panel pays no
    # extra host sync — the sweeps already probed this array)
    from ..reliability import source as source_mod
    from ..reliability.status import FitStatus
    from . import base as model_base

    refit_align = align_mode
    if refit_align is None:
        refit_align = (values.align_mode()
                       if isinstance(values, source_mod.ChunkSource)
                       else model_base.align_mode_on_host(values))
    stage2_wall = 0.0
    for g, spec in enumerate(specs):
        rows = np.nonzero(order_idx == g)[0]
        if rows.size == 0:
            order_meta[g]["stage2_rows"] = 0
            continue
        if budget_left is not None and budget_left() <= 0:
            # the whole-search budget bound covers stage 2 too (the
            # driver's semantics: once spent, remaining work is marked
            # TIMEOUT without dispatch — a resumed search retries it)
            sel["params"][rows] = np.nan
            sel["neg_log_likelihood"][rows] = np.nan
            sel["converged"][rows] = False
            sel["iters"][rows] = 0
            sel["status"][rows] = int(FitStatus.TIMEOUT)
            sel["criterion"][rows] = np.nan
            order_meta[g]["stage2_rows"] = int(rows.size)
            order_meta[g]["stage2_timeouts"] = int(rows.size)
            obs.event("auto_fit.winners_timeout", grid=g,
                      rows=int(rows.size))
            continue
        t_g = time.perf_counter()
        with obs.span("auto_fit.winners_basin", grid=g, order=spec.label,
                      rows=int(rows.size)):
            arrs = _refit_basin(
                spec, rows, results[g], values,
                include_intercept=include_intercept, fit_kwargs=fit_kwargs,
                resilient=resilient, policy=policy, chunk_rows=chunk_rows,
                align_mode=refit_align)
        wall = time.perf_counter() - t_g
        stage2_wall += wall
        k = spec.n_params(include_intercept)
        sel["params"][rows, :k] = arrs["params"][:, :k]
        sel["params"][rows, k:] = np.nan
        sel["neg_log_likelihood"][rows] = arrs["nll"]
        sel["converged"][rows] = arrs["converged"]
        sel["iters"][rows] = arrs["iters"]
        sel["status"][rows] = arrs["status"]
        # the reported criterion must match the RETURNED nll, not the
        # truncated stage-1 sweep's — recompute it from the refit (NaN
        # where the refit itself diverged)
        p_full, _, d_full = spec.lag_span()
        crit = np.asarray(_criterion_one(
            jnp.asarray(sel["neg_log_likelihood"][rows]),
            jnp.asarray(np.asarray(nv0)[rows].astype(
                sel["neg_log_likelihood"].dtype)),
            k, p_full, d_full, criterion))
        sel["criterion"][rows] = np.where(np.isfinite(crit), crit, np.nan)
        order_meta[g]["stage2_rows"] = int(rows.size)
        order_meta[g]["stage2_wall_s"] = round(wall, 4)
    return sel, order_meta, stage1_wall, stage2_wall


def _refit_basin(spec, rows, stage1_res, values, *, include_intercept,
                 fit_kwargs, resilient, policy, chunk_rows, align_mode):
    """One basin's full-budget stage-2: batched warm-started refits.

    ``rows`` (the rows whose stage-1 winner is ``spec``) are walked in
    slices of at most the search's ``chunk_rows``, each gathered into a
    ``retry_cap``-aligned sub-batch (``optim.gather_pad_indices`` — the
    pad tail recomputes a real row and is dropped on scatter, so every
    slice of a basin reuses ONE compiled program per (order, cap) shape)
    and dispatched as a single ``models.arima.fit`` initialized from the
    stage-1 sweep's params for these exact (row, order) cells.  Resilient
    searches run the sanitize+ladder contract instead of the warm start
    (the ladder refits failed subsets with the same fit_fn, which a fixed
    init array cannot follow)."""
    from ..reliability import runner as runner_mod

    k = spec.n_params(include_intercept)
    kw = dict(fit_kwargs)
    if align_mode is not None:
        kw["align_mode"] = align_mode
    step = int(min(rows.size, chunk_rows or rows.size))
    cap = optim.retry_cap(step)
    s1_params = np.asarray(stage1_res.params)[:, :k]
    outs = {f: [] for f in ("params", "nll", "converged", "iters", "status")}
    for lo in range(0, rows.size, step):
        sl = rows[lo: lo + step]
        pad_idx = optim.gather_pad_indices(sl, cap)
        sub = _materialize_rows(values, pad_idx)
        if resilient:
            fit_fn = _order_fit_fn(spec, include_intercept, dict(fit_kwargs))
            r = runner_mod.resilient_fit(
                fit_fn, sub, policy=policy,
                **({"align_mode": align_mode}
                   if align_mode is not None else {}))
        else:
            fit_fn = _order_fit_fn(spec, include_intercept, kw)
            init = s1_params[pad_idx]
            # winners have finite stage-1 params by construction (an
            # ineligible order cannot win); the guard keeps a violated
            # assumption from poisoning the whole sub-batch
            init = np.where(np.isfinite(init), init, 0.0)
            r = fit_fn(sub, init_params=jnp.asarray(init))
        keep = np.arange(sl.size)
        outs["params"].append(np.asarray(r.params)[keep])
        outs["nll"].append(np.asarray(r.neg_log_likelihood)[keep])
        outs["converged"].append(np.asarray(r.converged)[keep])
        outs["iters"].append(np.asarray(r.iters, np.int32)[keep])
        outs["status"].append(np.asarray(r.status, np.int8)[keep])
    return {f: np.concatenate(v) for f, v in outs.items()}


def _materialize_rows(values, idx: np.ndarray):
    """Device sub-panel ``values[idx]`` for a basin refit: on-device
    gather for resident arrays; batched contiguous host reads
    (:func:`_read_rows_host`) for ``ChunkSource`` panels — a basin slice
    is a bounded ``retry_cap`` sub-batch, so materializing it on device
    is the cheap direction even for oversubscribed panels."""
    from ..reliability import source as source_mod

    if isinstance(values, source_mod.ChunkSource):
        return jnp.asarray(_read_rows_host(values, np.asarray(idx)))
    return jnp.asarray(values)[jnp.asarray(np.asarray(idx))]


def _read_rows_host(values, idx: np.ndarray) -> np.ndarray:
    """Host gather of ``values[idx]`` from a ``ChunkSource``: contiguous
    ascending index runs become one batched ``read_rows`` each (the pad
    tail repeats ``idx[0]``, its own run), filling ONE buffer — shared by
    the streaming gather (:func:`_gather_rows`) and the device
    materializer (:func:`_materialize_rows`)."""
    t = int(values.shape[1])
    out = np.empty((idx.size, t), values.dtype)
    pos = 0
    run_start = 0
    for i in range(1, idx.size + 1):
        if i == idx.size or idx[i] != idx[i - 1] + 1:
            lo, hi = int(idx[run_start]), int(idx[i - 1]) + 1
            values.read_rows(lo, hi, out[pos: pos + (hi - lo)])
            pos += hi - lo
            run_start = i
    return out


def _gather_rows(values, idx: np.ndarray):
    """Row gather tolerant of device arrays and ``ChunkSource`` panels.

    A source-backed panel stays OFF the device: contiguous index runs
    are read host-side in batches (one ``read_rows`` per run, not per
    row) and the gathered sub-panel comes back as a
    ``HostChunkSource`` — the winners refit then STREAMS it through the
    staging pool like any other host-resident walk instead of
    materializing a possibly HBM-sized sub-panel.  Device panels keep
    the on-device gather (they are resident by definition).
    """
    from ..reliability import source as source_mod

    if isinstance(values, source_mod.ChunkSource):
        return source_mod.HostChunkSource(_read_rows_host(values, idx))
    return jnp.asarray(values)[jnp.asarray(idx)]


def _stepwise_neighbors(order, max_order: int):
    """Hyndman–Khandakar expansion moves around one winning order: vary
    ``p`` and ``q`` by ±1 (including the joint ±1 diagonal) with ``d``
    FIXED — differencing is a property of the series, not a search move —
    and both coefficients capped at ``max_order``.  Deterministic
    ascending output order."""
    p, d, q = order
    out = []
    for dp, dq in ((-1, -1), (-1, 0), (0, -1), (0, 1), (1, 0), (1, 1)):
        p2, q2 = p + dp, q + dq
        if 0 <= p2 <= max_order and 0 <= q2 <= max_order:
            out.append((p2, d, q2))
    return out


def _stepwise_search(seed_specs, values, nv0, b, criterion,
                     include_intercept, fuse, checkpoint_dir, max_passes,
                     max_order, fit_kwargs, walk_knobs, *, budget_left=None):
    """The stepwise Hyndman–Khandakar driver (ISSUE 19).

    Fits the seed neighborhood as pass 0 (fused same-``d`` groups, full
    budget), arg-selects over everything tried so far, expands ``p``/``q``
    around the distinct per-row winners, and repeats until a pass's new
    orders win zero rows, the expansion is exhausted, or ``max_passes``
    is reached.  Every pass is an ordinary journaled campaign under
    ``checkpoint_dir/stepwise_%02d/grid_%05d`` (grid dirs named by GLOBAL
    trial index): SIGKILL anywhere and a re-run replays the same pass
    sequence — completed walks load from their journals bitwise, so the
    recomputed selections and expansions are identical, and the torn walk
    resumes mid-chunk.  The selection tie-break prefers earlier-TRIED
    orders, exactly as the exhaustive search prefers earlier grid
    entries.
    """
    from ..reliability import fit_chunked

    max_passes = int(max_passes)
    max_order = int(max_order)
    specs: list = []
    results: list = []
    order_meta: list = []
    passes_meta: list = []
    sw_groups: list = []  # (pass_idx, global member tuple) in walk order
    diff_hits = 0
    frontier = list(seed_specs)
    sel = None
    wall_total = 0.0
    converged = False

    def _entry(g, wall, res, pass_idx, fused_with=None):
        spec = specs[g]
        entry = {
            "grid_index": g,
            "order": list(spec.order),
            "seasonal": None,
            "label": spec.label,
            "k": spec.n_params(include_intercept),
            "wall_s": round(wall, 4),
            "chunks_run": res.meta.get("chunks_run"),
            "rows_fit": b,
            "stage2_traces": None,
            "timeouts": res.meta.get("timeouts", 0),
            "stepwise_pass": pass_idx,
        }
        if fused_with is not None:
            entry["fused_group"] = fused_with[0]
            entry["fused_width"] = len(fused_with)
        return entry

    for pass_idx in range(max_passes):
        if not frontier:
            converged = True
            break
        pass_dir = (None if checkpoint_dir is None else
                    os.path.join(checkpoint_dir,
                                 f"stepwise_{pass_idx:02d}"))
        base_g = len(specs)
        specs.extend(frontier)
        g_total = len(specs)
        local_groups = fusion_groups(tuple(frontier), fuse)
        diff_hits += _grid_diff_cache_hits(tuple(frontier), local_groups)
        pass_results = [None] * len(frontier)
        pass_wall = 0.0
        for local in local_groups:
            members = tuple(base_g + j for j in local)
            sw_groups.append((pass_idx, members))
            budget = (None if budget_left is None
                      else max(1e-6, budget_left()))
            if len(members) == 1:
                g = members[0]
                spec = specs[g]
                fit_fn = _order_fit_fn(spec, include_intercept,
                                       dict(fit_kwargs))
                extra = {"auto_fit": {
                    "grid_index": g, "grid_total": g_total,
                    "order": list(spec.order), "seasonal": None,
                    "criterion": criterion, "stage": "stepwise",
                    "stepwise_pass": pass_idx,
                }}
                with obs.span("auto_fit.order", grid=g, order=spec.label,
                              stage="stepwise", sw_pass=pass_idx):
                    t_g = time.perf_counter()
                    res = fit_chunked(
                        fit_fn, values,
                        checkpoint_dir=_grid_dir(pass_dir, g),
                        grid=(g, g_total), job_budget_s=budget,
                        journal_extra=extra, **walk_knobs)
                    wall = time.perf_counter() - t_g
                pass_results[local[0]] = res
                order_meta.append(_entry(g, wall, res, pass_idx))
            else:
                gspecs = tuple((specs[g].order, specs[g].seasonal)
                               for g in members)
                fit_fn = functools.partial(
                    arima.fit_grid, specs=gspecs,
                    include_intercept=include_intercept,
                    **dict(fit_kwargs))
                extra = {"auto_fit": {
                    "grid_index": members[0], "grid_total": g_total,
                    "fused_orders": list(members),
                    "orders": [list(specs[g].order) for g in members],
                    "seasonals": [None for _ in members],
                    "criterion": criterion, "stage": "stepwise",
                    "fuse": len(members), "stepwise_pass": pass_idx,
                }}
                label = "+".join(specs[g].label for g in members)
                with obs.span("auto_fit.order", grid=members[0],
                              order=label, stage="stepwise",
                              fused=len(members), sw_pass=pass_idx):
                    t_g = time.perf_counter()
                    res = fit_chunked(
                        fit_fn, values,
                        checkpoint_dir=_grid_dir(pass_dir, members[0]),
                        grid=(members[0], g_total, tuple(members)),
                        job_budget_s=budget,
                        journal_extra=extra, **walk_knobs)
                    wall = time.perf_counter() - t_g
                per = _demux_fused(res, [specs[g] for g in members],
                                   include_intercept)
                for pos, (j, g) in enumerate(zip(local, members)):
                    pass_results[j] = per[pos]
                    order_meta.append(_entry(g, wall / len(members), res,
                                             pass_idx, fused_with=members))
            pass_wall += wall
        results.extend(pass_results)
        wall_total += pass_wall
        sel = select_orders(tuple(specs), results, nv0, criterion=criterion,
                            include_intercept=include_intercept)
        order_idx = np.asarray(sel["order_index"])
        new_rows_won = int(np.sum(order_idx >= base_g))
        passes_meta.append({
            "pass": pass_idx,
            "dir": f"stepwise_{pass_idx:02d}",
            "orders": list(range(base_g, g_total)),
            "new_rows_won": new_rows_won,
            "wall_s": round(pass_wall, 4),
        })
        obs.event("auto_fit.stepwise_pass", sw_pass=pass_idx,
                  orders=g_total - base_g, new_rows_won=new_rows_won)
        if pass_idx > 0 and new_rows_won == 0:
            converged = True
            break
        # expand around the distinct winning orders: every untried p/q
        # neighbor, collected in ascending order so global trial indices
        # are a deterministic function of the journaled results
        tried = {(s.order, s.seasonal) for s in specs}
        winner_orders = sorted({specs[int(g)].order
                                for g in np.unique(order_idx) if g >= 0})
        cand = []
        for o in winner_orders:
            for nb in _stepwise_neighbors(o, max_order):
                if (nb, None) not in tried:
                    tried.add((nb, None))
                    cand.append(nb)
        cand.sort()
        frontier = [OrderSpec(o) for o in cand]
    converged = converged or not frontier
    order_meta.sort(key=lambda m: m["grid_index"])
    return (sel, tuple(specs), order_meta, passes_meta, wall_total,
            tuple(sw_groups), diff_hits, converged)


def _write_auto_manifest(checkpoint_dir: str, auto_meta: dict,
                         grid_dirs: list) -> None:
    """Atomically write the search-level ``auto_manifest.json`` next to
    the per-order ``grid_*`` journals (single writer: the search driver,
    after selection — the per-order manifests carry the durable chunk
    state; this file is the grid-level accounting the tools read).
    ``grid_dirs`` is the exact set of journal dirs THIS search walked."""
    from ..reliability import journal as journal_mod

    os.makedirs(checkpoint_dir, exist_ok=True)
    payload = {
        "kind": "auto_fit",
        "written_at": time.time(),  # lint: nondet(manifest wall-clock metadata)
        "auto_fit": auto_meta,
        "grid_dirs": grid_dirs,
    }
    journal_mod._atomic_write_bytes(
        os.path.join(checkpoint_dir, "auto_manifest.json"),
        json.dumps(payload, indent=1, sort_keys=True).encode())
