"""Auto model selection at panel scale (ISSUE 9 / ROADMAP item 4).

Real users rarely know their ``(p, d, q)`` — upstream spark-ts exposes
model selection as a first-class workflow, and seasonal order choice is
the paper's largest still-unreproduced scenario surface.  :func:`auto_fit`
fits a STATIC grid of candidate ARIMA (optionally seasonal SARIMA) orders
per series, computes an information criterion per (row, order) ON DEVICE,
and arg-selects per row — the batched rebuild of "loop statsmodels'
``auto_arima`` over a million series".

**Execution model.**  Each candidate order is one ordinary journaled chunk
walk (``reliability.fit_chunked`` with a ``grid=(g, G)`` coordinate on its
:class:`~..reliability.plan.ExecutionPlan`): the search therefore inherits
EVERYTHING the driver already earns — write-ahead journaling with
SIGKILL-resume that replays only uncommitted chunks (a kill mid-grid
resumes with completed orders loaded from their manifests and the
in-flight order continuing mid-walk), OOM chunk backoff, wall-clock
budgets, pipelined commits/prefetch, mesh sharding (``shard=True``), and
``ChunkSource`` streaming for larger-than-HBM panels.  Within each order's
walk the lazy stage-1/stage-2 straggler split in ``utils.optim`` does the
per-order amortization: stage 1 (the cheap lockstep sweep) runs for every
order, and the compacted stage-2 straggler program is traced/compiled/
dispatched ONLY when an order's rows actually need it.  One compiled
program per (order, chunk shape) is reused across every chunk of that
order's walk — measured by the ``compile_cache.hit``/``miss`` counters
(``utils.compile_cache``).

**Selection.**  Criteria (AICc default; AIC/BIC) are computed from each
order's concentrated CSS likelihood and the row's valid-span length in ONE
jitted program over the stacked ``[G, B]`` results — per-row argmin, tie
broken toward the earlier grid entry, no host round-trip per candidate.
Rows where no candidate produced a finite criterion come back with
``order_index = -1`` and NaN params.  The default (``stage2="full"``)
selection is bitwise-identical to an exhaustive per-order full-fit argmin
on the same panel with the same chunk layout.

**Stage-2 economy** (``stage2="winners"``): run every order at a small
stage-1 iteration budget first, rank basins per row by the stage-1
criterion, then spend the FULL budget only on each row's winning order
(gathered into ``optim.retry_cap``-aligned sub-batches, one journaled
refit walk per winning order).  Selection then follows the stage-1
ranking — documented as approximate (a basin that looks worse at the
stage-1 budget can win under full convergence) in exchange for spending
full-fit iterations on ~1/G of the (row, order) grid.

Durability artifacts: per-order journals live under
``checkpoint_dir/grid_00000/…`` (each manifest carrying an
``extra.auto_fit`` block) and the search writes a root
``auto_manifest.json`` recording orders tried, per-order stage-2 spend,
and the selection histogram — rendered/validated by
``tools/obs_report.py`` and turned into next-run knobs by
``tools/advise_budget.py``.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..utils import compile_cache as _compile_cache
from ..utils import optim
from . import arima
from .base import jit_program

__all__ = [
    "AutoFitResult",
    "DEFAULT_ORDERS",
    "OrderSpec",
    "auto_fit",
    "criterion_matrix",
    "normalize_orders",
    "select_orders",
]

CRITERIA = ("aicc", "aic", "bic")

# pragmatic default grid: the low-order workhorses statsmodels' stepwise
# search visits first — differencing once covers most trending panels, and
# anything richer is cheap to pass explicitly
DEFAULT_ORDERS = (
    (1, 0, 0), (0, 0, 1), (1, 0, 1),
    (0, 1, 1), (1, 1, 0), (1, 1, 1),
)


class OrderSpec(NamedTuple):
    """One candidate on the search grid: an ARIMA order plus an optional
    multiplicative seasonal ``(P, D, Q, s)`` extension."""

    order: Tuple[int, int, int]
    seasonal: Optional[Tuple[int, int, int, int]] = None

    @property
    def label(self) -> str:
        if self.seasonal is None:
            return str(tuple(self.order))
        return f"{tuple(self.order)}x{tuple(self.seasonal)}"

    def n_params(self, include_intercept: bool) -> int:
        if self.seasonal is None:
            return arima._n_params(self.order, include_intercept)
        return arima._n_params_seasonal(self.order, self.seasonal,
                                        include_intercept)

    def lag_span(self) -> Tuple[int, int, int]:
        """``(p_full, q_full, d_full)`` of the (expanded) recursion."""
        return arima.seasonal_lag_span(self.order, self.seasonal)


def normalize_orders(orders) -> Tuple[OrderSpec, ...]:
    """Coerce a grid spec into a validated tuple of :class:`OrderSpec`.

    Accepts ``(p, d, q)`` triples, ``(p, d, q, (P, D, Q, s))`` pairs,
    ``OrderSpec`` instances, or ``None`` (the default grid).  Duplicates
    are rejected — a duplicate candidate can never win a strict argmin
    and only burns a full walk.
    """
    if orders is None:
        orders = DEFAULT_ORDERS
    specs = []
    for entry in orders:
        if isinstance(entry, OrderSpec):
            order, seasonal = entry.order, entry.seasonal
        else:
            entry = tuple(entry)
            if len(entry) == 4 and isinstance(entry[3], (tuple, list)):
                order, seasonal = entry[:3], tuple(entry[3])
            elif len(entry) == 3:
                order, seasonal = entry, None
            else:
                raise ValueError(
                    f"order spec must be (p, d, q) or (p, d, q, (P, D, Q, "
                    f"s)), got {entry!r}")
        p, d, q = (int(v) for v in order)
        if min(p, d, q) < 0:
            raise ValueError(f"orders must be >= 0, got {(p, d, q)}")
        seasonal = arima._validate_seasonal(seasonal)
        specs.append(OrderSpec((p, d, q), seasonal))
    if not specs:
        raise ValueError("orders grid is empty")
    seen = set()
    for s in specs:
        key = (s.order, s.seasonal)
        if key in seen:
            raise ValueError(f"duplicate order on the grid: {s.label}")
        seen.add(key)
    return tuple(specs)


class AutoFitResult(NamedTuple):
    """Per-row winner of the order search plus the selection record.

    ``params`` is ``[B, k_max]`` with each row's tail beyond its winning
    order's parameter count NaN-padded; ``order_index`` is the winning
    grid position (``-1``: no candidate produced a finite criterion);
    ``criterion`` is the winning criterion value per row, always
    consistent with the returned ``neg_log_likelihood`` (under
    ``stage2="winners"`` it is recomputed from the full-budget refit, so
    it is NOT comparable with stage-1 sweep values).  ``orders`` is
    the normalized grid and ``meta["auto_fit"]`` the search accounting
    (per-order spend, selection histogram, stage-2 mode).
    """

    params: np.ndarray  # [B, k_max]
    neg_log_likelihood: np.ndarray  # [B]
    converged: np.ndarray  # [B] bool
    iters: np.ndarray  # [B]
    status: np.ndarray  # [B] int8 FitStatus
    order_index: np.ndarray  # [B] int32, -1 = none eligible
    criterion: np.ndarray  # [B] winning criterion value
    orders: Tuple[OrderSpec, ...]
    meta: dict


# ---------------------------------------------------------------------------
# criterion + selection (one jitted program over the stacked grid)
# ---------------------------------------------------------------------------


def _criterion_one(nll, nv, k: int, p_full: int, d_full: int,
                   criterion: str):
    """Per-row criterion of one order from its concentrated CSS nll and
    the row's valid-span length ``nv`` (pre-differencing).  ``n_eff``
    matches the likelihood's own concentration denominator
    (``nv - d_full - p_full``); degenerate denominators and non-finite
    likelihoods map to +inf so the row cannot select this order."""
    n_eff = nv - float(d_full) - float(p_full)
    kf = float(k)
    if criterion == "bic":
        c = 2.0 * nll + kf * jnp.log(jnp.maximum(n_eff, 1.0))
        c = jnp.where(n_eff > 0, c, jnp.inf)
    else:
        c = 2.0 * nll + 2.0 * kf
        if criterion == "aicc":
            denom = n_eff - kf - 1.0
            c = c + jnp.where(
                denom > 0, 2.0 * kf * (kf + 1.0) / jnp.maximum(denom, 1.0),
                jnp.inf)
    return jnp.where(jnp.isfinite(c), c, jnp.inf)


@jit_program
def _select_program(meta: Tuple[Tuple[int, int, int], ...], criterion: str):
    """Stacked-grid criterion + per-row argmin, one compiled program.

    ``meta`` is the static per-order ``(k, p_full, d_full)`` tuple; inputs
    are the ``[G, B, k_max]`` params stack, ``[G, B]`` nll/converged/
    iters/status stacks, and the ``[B]`` valid-span lengths.  Ties break
    toward the EARLIER grid entry (``jnp.argmin`` first-min), so grid
    order is part of the selection contract.
    """

    def run(params, nll, conv, iters, status, nv0):
        nv = nv0.astype(nll.dtype)
        crit = jnp.stack([
            _criterion_one(nll[g], nv, k, p_full, d_full, criterion)
            for g, (k, p_full, d_full) in enumerate(meta)
        ])  # [G, B]
        best = jnp.argmin(crit, axis=0).astype(jnp.int32)
        bestc = jnp.min(crit, axis=0)
        has = jnp.isfinite(bestc)
        rows = jnp.arange(nll.shape[1])
        idx = jnp.where(has, best, 0)
        params_sel = jnp.where(has[:, None], params[idx, rows], jnp.nan)
        nll_sel = jnp.where(has, nll[idx, rows], jnp.nan)
        conv_sel = conv[idx, rows] & has
        iters_sel = jnp.where(has, iters[idx, rows], 0)
        # a row with no eligible candidate keeps the WORST thing that
        # happened to it anywhere on the grid (codes are severity-ordered)
        status_sel = jnp.where(has, status[idx, rows],
                               jnp.max(status, axis=0))
        order_idx = jnp.where(has, best, jnp.int32(-1))
        counts = jnp.stack(
            [jnp.sum(order_idx == g) for g in range(len(meta))]
            + [jnp.sum(~has)]).astype(jnp.int32)
        crit_sel = jnp.where(has, bestc, jnp.nan)
        return (params_sel, nll_sel, conv_sel, iters_sel, status_sel,
                order_idx, crit_sel, crit, counts)

    return run


def criterion_matrix(specs, nll_stack, nv0, *, criterion: str = "aicc",
                     include_intercept: bool = True):
    """``[G, B]`` criterion values for a stacked grid of fit results —
    the standalone spelling of the selection program's first half, shared
    with the exhaustive-argmin reference in tests."""
    specs = normalize_orders(specs)
    nll_stack = jnp.asarray(nll_stack)
    nv = jnp.asarray(nv0).astype(nll_stack.dtype)
    rows = []
    for spec in specs:
        p_full, _, d_full = spec.lag_span()
        rows.append(_criterion_one(
            nll_stack[len(rows)], nv, spec.n_params(include_intercept),
            p_full, d_full, criterion))
    return jnp.stack(rows)


def select_orders(specs, results, nv0, *, criterion: str = "aicc",
                  include_intercept: bool = True):
    """Run the on-device selection over per-order fit results.

    ``results`` is a sequence (one per order, grid order) of objects with
    ``params`` / ``neg_log_likelihood`` / ``converged`` / ``iters`` /
    ``status`` arrays (``FitResult`` and ``ResilientFitResult`` both
    qualify); ``nv0`` is the ``[B]`` per-row valid-span length
    (:func:`panel_n_valid`).  Returns the host-side selection dict the
    :func:`auto_fit` result is assembled from — and IS the exhaustive
    argmin when the results are exhaustive full fits, which is exactly
    how the bitwise acceptance test uses it.
    """
    specs = normalize_orders(specs)
    if len(results) != len(specs):
        raise ValueError(f"{len(specs)} orders but {len(results)} results")
    if criterion not in CRITERIA:
        raise ValueError(f"unknown criterion {criterion!r} "
                         f"(one of {CRITERIA})")
    kmax = max(s.n_params(include_intercept) for s in specs)
    b = np.asarray(results[0].neg_log_likelihood).shape[0]
    dtype = np.asarray(results[0].neg_log_likelihood).dtype
    params = np.full((len(specs), b, kmax), np.nan, dtype)
    nll = np.empty((len(specs), b), dtype)
    conv = np.empty((len(specs), b), bool)
    iters = np.empty((len(specs), b), np.int32)
    status = np.empty((len(specs), b), np.int8)
    for g, (spec, res) in enumerate(zip(specs, results)):
        k = spec.n_params(include_intercept)
        rp = np.asarray(res.params)
        # an all-TIMEOUT walk synthesizes width-1 NaN params (the driver
        # never learned the real k); those rows' NaN nll keeps them
        # unselectable, so the narrow copy is purely defensive
        w = min(k, rp.shape[1])
        params[g, :, :w] = rp[:, :w]
        nll[g] = np.asarray(res.neg_log_likelihood)
        conv[g] = np.asarray(res.converged)
        iters[g] = np.asarray(res.iters, np.int32)
        status[g] = np.asarray(res.status, np.int8)
    meta = []
    for s in specs:
        p_full, _, d_full = s.lag_span()
        meta.append((s.n_params(include_intercept), p_full, d_full))
    meta = tuple(meta)
    out = _select_program(meta, criterion)(
        jnp.asarray(params), jnp.asarray(nll), jnp.asarray(conv),
        jnp.asarray(iters), jnp.asarray(status),
        jnp.asarray(np.asarray(nv0, np.int32)))
    (params_sel, nll_sel, conv_sel, iters_sel, status_sel, order_idx,
     crit_sel, crit, counts) = (np.asarray(a) for a in out)
    return {
        "params": params_sel,
        "neg_log_likelihood": nll_sel,
        "converged": conv_sel,
        "iters": iters_sel,
        "status": status_sel.astype(np.int8),
        "order_index": order_idx,
        "criterion": crit_sel,
        "criteria_matrix": crit,
        "counts": counts,
    }


def panel_n_valid(y) -> np.ndarray:
    """``[B] int32`` valid-span length per row: ``last_non_nan -
    first_non_nan + 1`` (0 for all-NaN rows) — the one row property every
    criterion on the grid shares, identical to the span
    ``base.align_right`` fits against.  Accepts a device/host array or a
    ``reliability.source.ChunkSource`` (streamed on the host, so an
    oversubscribed panel never touches the device for this)."""
    from ..reliability import source as source_mod

    if isinstance(y, source_mod.ChunkSource):
        b, t = y.shape
        out = np.empty((b,), np.int32)
        step = max(1, int(y.default_chunk_rows or 4096))
        buf = np.empty((step, t), y.dtype)
        for lo in range(0, b, step):
            hi = min(lo + step, b)
            y.read_rows(lo, hi, buf[: hi - lo])
            out[lo:hi] = _nv_host(buf[: hi - lo])
        return out
    if isinstance(y, jax.Array) and not isinstance(y, jax.core.Tracer):
        return np.asarray(_nv_program()(y), np.int32)
    return _nv_host(np.asarray(y))


def _nv_host(y: np.ndarray) -> np.ndarray:
    valid = ~np.isnan(y)
    any_valid = valid.any(axis=1)
    first = valid.argmax(axis=1)
    last = y.shape[1] - 1 - valid[:, ::-1].argmax(axis=1)
    return np.where(any_valid, last - first + 1, 0).astype(np.int32)


@jit_program
def _nv_program():
    def run(yb):
        valid = ~jnp.isnan(yb)
        any_valid = jnp.any(valid, axis=1)
        first = jnp.argmax(valid, axis=1)
        last = yb.shape[1] - 1 - jnp.argmax(valid[:, ::-1], axis=1)
        return jnp.where(any_valid, last - first + 1, 0).astype(jnp.int32)

    return run


# ---------------------------------------------------------------------------
# the search driver
# ---------------------------------------------------------------------------


def _order_fit_fn(spec: OrderSpec, include_intercept: bool, fit_kwargs: dict):
    """The per-order fit partial handed to ``fit_chunked`` — keyword-bound
    so the journal's config hash covers the order AND every hyperknob."""
    kw = dict(fit_kwargs)
    if spec.seasonal is not None:
        kw["seasonal"] = spec.seasonal
    return functools.partial(arima.fit, order=spec.order,
                             include_intercept=include_intercept, **kw)


def _grid_dir(checkpoint_dir: Optional[str], g: int,
              stage: str = "") -> Optional[str]:
    if checkpoint_dir is None:
        return None
    return os.path.join(checkpoint_dir, f"grid_{g:05d}{stage}")


def _remaining_budget(job_budget_s: Optional[float],
                      t0: float) -> Optional[float]:
    """The job budget LEFT for the next order's walk: the whole search
    shares one wall-clock allowance, so orders dispatched after it is
    spent mark their chunks TIMEOUT without dispatch (the driver's
    normal budget semantics) instead of running unbounded."""
    if job_budget_s is None:
        return None
    return max(1e-6, job_budget_s - (time.perf_counter() - t0))


def auto_fit(
    y,
    orders=None,
    *,
    criterion: str = "aicc",
    include_intercept: bool = True,
    stage2: str = "full",
    stage1_iters: int = 12,
    return_criteria: bool = False,
    chunk_rows: Optional[int] = None,
    resilient: bool = False,
    policy: str = "impute",
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    align_mode: Optional[str] = None,
    shard: bool = False,
    mesh=None,
    _journal_commit_hook=None,
    **fit_kwargs,
) -> AutoFitResult:
    """Batched order search over ``y [B, T]`` (array or ``ChunkSource``).

    Fits every candidate on ``orders`` (default :data:`DEFAULT_ORDERS`;
    entries ``(p, d, q)`` or ``(p, d, q, (P, D, Q, s))`` for seasonal
    SARIMA candidates) as one journaled chunk walk per order, computes
    ``criterion`` (``"aicc"`` default, ``"aic"``/``"bic"``) per (row,
    order) on device, and arg-selects per row.  All ``fit_chunked`` knobs
    ride through per order (``checkpoint_dir`` fans out into per-order
    ``grid_00000/…`` journals; ``job_budget_s`` bounds the WHOLE search);
    remaining ``fit_kwargs`` (``max_iters``, ``backend``, ``method``,
    ``tol``, ...) go to every order's ``models.arima.fit``.

    ``stage2="full"`` (default): every order is fully fit — selection is
    bitwise-identical to an exhaustive per-order full-fit argmin on the
    same panel/chunk layout, and the stage-1/stage-2 economy lives inside
    each fit (the lazy straggler split only compiles/dispatches an
    order's stage-2 program when rows actually need it).
    ``stage2="winners"``: sweep every order at ``stage1_iters`` first,
    rank per row, then spend the full budget only on each row's winning
    order — approximate selection, full-quality winning params, with the
    stage-2 spend recorded per order in ``meta["auto_fit"]``.

    Durable: SIGKILL anywhere — mid-chunk, mid-order, between orders —
    and a re-run with the same panel/grid/config resumes from the
    per-order journals, replaying only uncommitted chunks, with selection
    (recomputed from the full grid) bitwise-identical to an uninterrupted
    search.  A root ``auto_manifest.json`` records orders tried, per-order
    spend, and the selection histogram for the tools.
    """
    specs = normalize_orders(orders)
    if criterion not in CRITERIA:
        raise ValueError(f"unknown criterion {criterion!r} "
                         f"(one of {CRITERIA})")
    if stage2 not in ("full", "winners"):
        raise ValueError(f"stage2 must be 'full' or 'winners', got "
                         f"{stage2!r}")
    if stage2 == "winners" and int(stage1_iters) < 1:
        raise ValueError("stage1_iters must be >= 1")
    from ..reliability import fit_chunked
    from ..reliability import source as source_mod

    if isinstance(y, source_mod.ChunkSource):
        values = y
        b = int(y.shape[0])
    else:
        values = jnp.asarray(y)
        if values.ndim != 2:
            raise ValueError(
                f"auto_fit expects [batch, time], got {values.shape}")
        b = int(values.shape[0])
    nv0 = panel_n_valid(values)
    g_total = len(specs)
    t0 = time.perf_counter()
    cc0 = _compile_cache.program_cache_stats()
    tele = obs.enabled()

    walk_knobs = dict(
        chunk_rows=chunk_rows, resilient=resilient, policy=policy,
        resume=resume, chunk_budget_s=chunk_budget_s,
        pipeline=pipeline, pipeline_depth=pipeline_depth,
        prefetch_depth=prefetch_depth, align_mode=align_mode,
        shard=shard, mesh=mesh, _journal_commit_hook=_journal_commit_hook,
    )

    def _walk(spec, g, ckpt, *, stage_tag, max_iters_override=None,
              vals=None):
        """One order's walk — the full panel by default, or a gathered
        sub-panel (``vals``, the winners refit).  EVERY walk inherits the
        caller's knobs (resilient/policy/align_mode/budgets/pipeline/
        shard) so a stage-2 refit fits its rows under the same contract
        the stage-1 sweep did; the align hint stays valid on any row
        subset (it is a row-wise property of the panel)."""
        kw = dict(fit_kwargs)
        if max_iters_override is not None:
            kw["max_iters"] = max_iters_override
        fit_fn = _order_fit_fn(spec, include_intercept, kw)
        extra = {"auto_fit": {
            "grid_index": g, "grid_total": g_total,
            "order": list(spec.order),
            "seasonal": (list(spec.seasonal) if spec.seasonal is not None
                         else None),
            "criterion": criterion, "stage": stage_tag,
        }}
        with obs.span("auto_fit.order", grid=g, order=spec.label,
                      stage=stage_tag):
            t_g = time.perf_counter()
            res = fit_chunked(
                fit_fn, values if vals is None else vals,
                checkpoint_dir=ckpt, grid=(g, g_total),
                job_budget_s=_remaining_budget(job_budget_s, t0),
                journal_extra=extra, **walk_knobs)
            wall = time.perf_counter() - t_g
        return res, wall

    order_meta = []
    if stage2 == "full":
        results = []
        for g, spec in enumerate(specs):
            s2_0 = (obs.snapshot() or {}).get("counters", {}) if tele else {}
            res, wall = _walk(spec, g, _grid_dir(checkpoint_dir, g),
                              stage_tag="full")
            s2_1 = (obs.snapshot() or {}).get("counters", {}) if tele else {}
            results.append(res)
            order_meta.append({
                "grid_index": g,
                "order": list(spec.order),
                "seasonal": (list(spec.seasonal)
                             if spec.seasonal is not None else None),
                "label": spec.label,
                "k": spec.n_params(include_intercept),
                "wall_s": round(wall, 4),
                "chunks_run": res.meta.get("chunks_run"),
                "rows_fit": b,
                "stage2_traces": (
                    s2_1.get("optim.stage2_compact_traces", 0)
                    - s2_0.get("optim.stage2_compact_traces", 0))
                if tele else None,
                "timeouts": res.meta.get("timeouts", 0),
            })
        sel = select_orders(specs, results, nv0, criterion=criterion,
                            include_intercept=include_intercept)
        stage1_wall = sum(m["wall_s"] for m in order_meta)
        stage2_wall = 0.0
    else:
        sel, order_meta, stage1_wall, stage2_wall = _winners_search(
            specs, values, nv0, b, criterion, include_intercept,
            stage1_iters, checkpoint_dir, _walk)

    counts = sel["counts"]
    for m in order_meta:
        m["selected_rows"] = int(counts[m["grid_index"]])
    selection_counts = {specs[g].label: int(counts[g])
                        for g in range(g_total)}
    selection_counts["none"] = int(counts[g_total])
    cc1 = _compile_cache.program_cache_stats()
    cc_hits = cc1["hits"] - cc0["hits"]
    cc_misses = cc1["misses"] - cc0["misses"]
    total_wall = time.perf_counter() - t0
    auto_meta = {
        "criterion": criterion,
        "stage2": stage2,
        "stage1_iters": stage1_iters if stage2 == "winners" else None,
        "n_rows": b,
        "orders": order_meta,
        "selection_counts": selection_counts,
        "wall_s": round(total_wall, 4),
        "stage1_wall_s": round(stage1_wall, 4),
        "stage2_wall_s": round(stage2_wall, 4),
        "stage2_spend_share": (
            round(stage2_wall / max(stage1_wall + stage2_wall, 1e-9), 4)),
        "compile_cache": {
            "hits": cc_hits, "misses": cc_misses,
            "hit_rate": (round(cc_hits / (cc_hits + cc_misses), 4)
                         if (cc_hits + cc_misses) else None)},
    }
    meta = {"auto_fit": auto_meta}
    if return_criteria:
        meta["criteria_matrix"] = sel["criteria_matrix"]
    if checkpoint_dir is not None:
        # the dirs THIS search used, derived from its own plan (never a
        # disk glob: a previous search in the same directory — e.g. a
        # full run before a winners run — must not be advertised as part
        # of this one, or the tools would read the wrong journals)
        if stage2 == "full":
            grid_dirs = [f"grid_{g:05d}" for g in range(g_total)]
        else:
            grid_dirs = [f"grid_{g:05d}_s1" for g in range(g_total)]
            grid_dirs += [f"grid_{m['grid_index']:05d}_winners"
                          for m in order_meta
                          if m.get("stage2_rows")]
        _write_auto_manifest(checkpoint_dir, auto_meta, sorted(grid_dirs))
        meta["auto_manifest"] = os.path.join(checkpoint_dir,
                                             "auto_manifest.json")
    obs.counter("auto_fit.searches").inc()
    obs.event("auto_fit.selected", orders=g_total, rows=b,
              none=selection_counts["none"])
    return AutoFitResult(
        sel["params"], sel["neg_log_likelihood"], sel["converged"],
        sel["iters"], sel["status"], sel["order_index"], sel["criterion"],
        specs, meta)


def _winners_search(specs, values, nv0, b, criterion, include_intercept,
                    stage1_iters, checkpoint_dir, _walk):
    """The ``stage2="winners"`` economy: rank on cheap stage-1 sweeps,
    spend the full budget only on each row's winning order."""
    g_total = len(specs)
    order_meta = []
    stage1_results = []
    stage1_wall = 0.0
    for g, spec in enumerate(specs):
        res, wall = _walk(spec, g, _grid_dir(checkpoint_dir, g, "_s1"),
                          stage_tag="stage1",
                          max_iters_override=stage1_iters)
        stage1_results.append(res)
        stage1_wall += wall
        order_meta.append({
            "grid_index": g,
            "order": list(spec.order),
            "seasonal": (list(spec.seasonal)
                         if spec.seasonal is not None else None),
            "label": spec.label,
            "k": spec.n_params(include_intercept),
            "wall_s": round(wall, 4),
            "chunks_run": res.meta.get("chunks_run"),
            "rows_fit": b,
            "stage2_traces": None,
            "timeouts": res.meta.get("timeouts", 0),
        })
    sel = select_orders(specs, stage1_results, nv0, criterion=criterion,
                        include_intercept=include_intercept)
    # the winner refits scatter into the selection arrays: make them
    # writable host copies (device-backed np views are read-only)
    for key in ("params", "neg_log_likelihood", "converged", "iters",
                "status", "criterion"):
        sel[key] = np.array(sel[key])
    order_idx = sel["order_index"]
    stage2_wall = 0.0
    # refit each winning order's rows at the FULL budget: gathered into a
    # retry_cap-aligned sub-batch (bounded compiled shapes — the resilient
    # ladder's contract) and scattered back over the stage-1 selection.
    # The refit walk runs under the SAME knobs as the sweeps (resilient
    # ladder, align hint, budgets, pipeline) via _walk, journaled under
    # grid_{g}_winners — the sub-panel is a deterministic function of the
    # journaled stage-1 results, so a resumed search gathers the same
    # rows and the journal fingerprint matches.
    for g, spec in enumerate(specs):
        rows = np.nonzero(order_idx == g)[0]
        if rows.size == 0:
            order_meta[g]["stage2_rows"] = 0
            continue
        cap = optim.retry_cap(rows.size)
        pad_idx = np.concatenate([rows, np.full(cap - rows.size, rows[0])])
        sub = _gather_rows(values, pad_idx)
        res, wall = _walk(spec, g, _grid_dir(checkpoint_dir, g, "_winners"),
                          stage_tag="winners", vals=sub)
        stage2_wall += wall
        keep = np.arange(rows.size)
        k = spec.n_params(include_intercept)
        sel["params"][rows, :k] = np.asarray(res.params)[keep]
        sel["params"][rows, k:] = np.nan
        sel["neg_log_likelihood"][rows] = np.asarray(
            res.neg_log_likelihood)[keep]
        sel["converged"][rows] = np.asarray(res.converged)[keep]
        sel["iters"][rows] = np.asarray(res.iters)[keep]
        sel["status"][rows] = np.asarray(res.status)[keep]
        # the reported criterion must match the RETURNED nll, not the
        # truncated stage-1 sweep's — recompute it from the refit (NaN
        # where the refit itself diverged: the row keeps its selection
        # but carries no comparable criterion value)
        p_full, _, d_full = spec.lag_span()
        crit = np.asarray(_criterion_one(
            jnp.asarray(sel["neg_log_likelihood"][rows]),
            jnp.asarray(np.asarray(nv0)[rows].astype(
                sel["neg_log_likelihood"].dtype)),
            k, p_full, d_full, criterion))
        sel["criterion"][rows] = np.where(np.isfinite(crit), crit, np.nan)
        order_meta[g]["stage2_rows"] = int(rows.size)
        order_meta[g]["stage2_wall_s"] = round(wall, 4)
    return sel, order_meta, stage1_wall, stage2_wall


def _gather_rows(values, idx: np.ndarray):
    """Row gather tolerant of device arrays and ``ChunkSource`` panels.

    A source-backed panel stays OFF the device: contiguous index runs
    are read host-side in batches (one ``read_rows`` per run, not per
    row) and the gathered sub-panel comes back as a
    ``HostChunkSource`` — the winners refit then STREAMS it through the
    staging pool like any other host-resident walk instead of
    materializing a possibly HBM-sized sub-panel.  Device panels keep
    the on-device gather (they are resident by definition).
    """
    from ..reliability import source as source_mod

    if isinstance(values, source_mod.ChunkSource):
        t = int(values.shape[1])
        out = np.empty((idx.size, t), values.dtype)
        pos = 0
        # contiguous ascending runs -> one batched host read per run
        # (the pad tail repeats idx[0], its own run)
        run_start = 0
        for i in range(1, idx.size + 1):
            if i == idx.size or idx[i] != idx[i - 1] + 1:
                lo, hi = int(idx[run_start]), int(idx[i - 1]) + 1
                values.read_rows(lo, hi, out[pos: pos + (hi - lo)])
                pos += hi - lo
                run_start = i
        return source_mod.HostChunkSource(out)
    return jnp.asarray(values)[jnp.asarray(idx)]


def _write_auto_manifest(checkpoint_dir: str, auto_meta: dict,
                         grid_dirs: list) -> None:
    """Atomically write the search-level ``auto_manifest.json`` next to
    the per-order ``grid_*`` journals (single writer: the search driver,
    after selection — the per-order manifests carry the durable chunk
    state; this file is the grid-level accounting the tools read).
    ``grid_dirs`` is the exact set of journal dirs THIS search walked."""
    from ..reliability import journal as journal_mod

    os.makedirs(checkpoint_dir, exist_ok=True)
    payload = {
        "kind": "auto_fit",
        "written_at": time.time(),
        "auto_fit": auto_meta,
        "grid_dirs": grid_dirs,
    }
    journal_mod._atomic_write_bytes(
        os.path.join(checkpoint_dir, "auto_manifest.json"),
        json.dumps(payload, indent=1, sort_keys=True).encode())
