"""AR(p) by ordinary least squares (L4).

Rebuild of the reference's ``sparkts/models/Autoregression.scala``
(SURVEY.md Section 2.2, upstream path unverified): lag-matrix OLS — no
iterative optimizer.  Batched here as one normal-equations solve per series,
vmapped over the panel (MXU matmuls).

Parameter layout matches ARIMA: ``[c, phi_1..phi_p]`` (c = 0 when
``no_intercept``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.lagmat import lag_mat_trim_both
from . import arima as _arima
from ..utils.linalg import ols as _ols
from .base import (FitResult, align_right, debatch, derive_status,
                   ensure_batched, jit_program)


def fit(y, max_lag: int = 1, no_intercept: bool = False) -> FitResult:
    """OLS fit of y_t on [1?, y_{t-1} .. y_{t-max_lag}].

    Leading/trailing NaNs are tolerated (right-aligned 0/1 row weights in the
    normal equations); too-short series come back NaN, ``converged=False``.
    """
    yb, single = ensure_batched(y)
    return debatch(_fit_program(max_lag, no_intercept)(yb), single)


@jit_program
def _fit_program(max_lag, no_intercept):
    def run(yb):
        def one(yv, nv):
            start = yv.shape[0] - nv
            X = lag_mat_trim_both(yv, max_lag)  # [n - p, p]
            target = yv[max_lag:]
            if not no_intercept:
                X = jnp.concatenate([jnp.ones((X.shape[0], 1), yv.dtype), X], axis=1)
            # row i regresses t = max_lag + i; lags reach back to t - max_lag,
            # so rows with t - max_lag < start carry padding -> weight 0
            w = (jnp.arange(target.shape[0]) >= start).astype(yv.dtype)
            beta = _ols(X * w[:, None], target * w)
            if no_intercept:
                beta = jnp.concatenate([jnp.zeros((1,), yv.dtype), beta])
            resid = (target - X @ (beta[1:] if no_intercept else beta)) * w
            n = nv - max_lag
            sigma2 = jnp.sum(resid**2) / n
            nll = 0.5 * n * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)
            return beta, nll

        ya, nv = jax.vmap(align_right)(yb)
        params, nll = jax.vmap(one)(ya, nv)
        ok = nv >= max_lag + (1 if no_intercept else 2) + 1
        b = yb.shape[0]
        params = jnp.where(ok[:, None], params, jnp.nan)
        return FitResult(
            params,
            jnp.where(ok, nll, jnp.nan),
            ok,
            jnp.zeros((b,), jnp.int32),
            derive_status(ok, ok, params),
        )

    return run


def forecast(params, y, max_lag: int, n_future: int):
    """Iterate the AR recursion forward (ARIMA(p,0,0) forecast)."""
    return _arima.forecast(params, y, (max_lag, 0, 0), n_future)


def sample(params, key, n: int, max_lag: int, sigma: float = 1.0):
    return _arima.sample(params, key, n, (max_lag, 0, 0), sigma=sigma)


def remove_time_dependent_effects(params, y, max_lag: int):
    """Series -> innovations: e_t = y_t - c - sum phi_i y_{t-i}."""
    return _arima.remove_time_dependent_effects(params, y, (max_lag, 0, 0))


def add_time_dependent_effects(params, x, max_lag: int):
    return _arima.add_time_dependent_effects(params, x, (max_lag, 0, 0))
