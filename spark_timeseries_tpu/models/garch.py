"""GARCH(1,1) and AR(1)+GARCH(1,1) volatility models (L4).

Rebuild of the reference's ``sparkts/models/GARCH.scala`` (SURVEY.md
Section 2.2, upstream path unverified).  Variance recursion
``h_t = omega + alpha * r_{t-1}^2 + beta * h_{t-1}`` with Gaussian
log-likelihood; the reference maximizes per series with Commons-Math
gradient ascent/CG.  Here the likelihood is a ``lax.scan`` and the
constraints (omega > 0, alpha, beta >= 0, alpha + beta < 1) are enforced by
a softplus/sigmoid reparameterization through the shared vmapped L-BFGS
(the BOBYQA-replacement strategy, SURVEY.md Section 7).

Parameter layouts (natural space):
- GARCH:   ``[omega, alpha, beta]``
- ARGARCH: ``[c, phi, omega, alpha, beta]``
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import optim
from .base import (FitResult, align_right, debatch,
                   debatch_fit, derive_status,
                   require_pallas_for_count_evals,
                   ensure_batched, maybe_align,
                   jit_program, resolve_align_mode, resolve_backend)


# -- transforms -------------------------------------------------------------


def _to_natural(u):
    """R^3 -> constrained (omega, alpha, beta)."""
    omega = jax.nn.softplus(u[0]) + 1e-12
    persistence = jax.nn.sigmoid(u[1]) * (1.0 - 1e-6)  # alpha + beta
    frac = jax.nn.sigmoid(u[2])  # alpha share
    alpha = persistence * frac
    beta = persistence * (1.0 - frac)
    return jnp.stack([omega, alpha, beta])


def _from_natural(params):
    omega, alpha, beta = params[0], params[1], params[2]
    u0 = optim.softplus_inverse(omega)
    pers = jnp.clip(alpha + beta, 1e-6, 1.0 - 1e-6)
    u1 = optim.interval_to_sigmoid(pers, 0.0, 1.0)
    u2 = optim.interval_to_sigmoid(alpha / pers, 0.0, 1.0)
    return jnp.stack([u0, u1, u2])


# -- likelihood -------------------------------------------------------------


def _variance_scan(params, h0, r_sq_prev):
    """The single GARCH recursion used everywhere:
    h_t = omega + alpha * r_sq_prev_t + beta * h_{t-1}."""
    omega, alpha, beta = params[0], params[1], params[2]

    def step(h, rt_prev_sq):
        h = omega + alpha * rt_prev_sq + beta * h
        return h, h

    _, h = lax.scan(step, h0, r_sq_prev)
    return h


def _unconditional_var(params):
    return params[0] / jnp.maximum(1.0 - params[1] - params[2], 1e-6)


def _masked_var(r, n_valid):
    """Variance over the right-aligned valid span."""
    t = jnp.arange(r.shape[0])
    m = (t >= r.shape[0] - n_valid).astype(r.dtype)
    n = jnp.maximum(n_valid, 1)
    mean = jnp.sum(r * m) / n
    return jnp.sum(m * (r - mean) ** 2) / n


def variances(params, r, n_valid=None):
    """Conditional variances h_t (h_0 = sample variance of r, which also
    stands in for the unobserved r_{-1}^2).

    ``n_valid`` marks a right-aligned valid span (``base.align_right``): the
    recursion holds h = h_0 through the zero prefix and seeds at the first
    valid step exactly as the full-series recursion seeds at t=0.
    """
    if n_valid is None:
        h0 = jnp.var(r)
        return _variance_scan(params, h0, jnp.concatenate([h0[None], r[:-1] ** 2]))

    h0 = _masked_var(r, n_valid)
    start = r.shape[0] - n_valid
    t = jnp.arange(r.shape[0])
    r_sq_prev = jnp.where(
        t == start, h0, jnp.concatenate([jnp.zeros((1,), r.dtype), r[:-1] ** 2])
    )
    omega, alpha, beta = params[0], params[1], params[2]

    def step(h, inp):
        rsq, ti = inp
        h = jnp.where(ti < start, h0, omega + alpha * rsq + beta * h)
        return h, h

    _, h = lax.scan(step, h0, (r_sq_prev, t))
    return h


def log_likelihood(params, r, n_valid=None):
    """Gaussian log-likelihood of returns under the variance recursion
    (summed over the valid span when ``n_valid`` is given)."""
    h = variances(params, r, n_valid)
    h = jnp.maximum(h, 1e-12)
    ll_t = jnp.log(2.0 * jnp.pi * h) + (r * r) / h
    if n_valid is not None:
        ll_t = jnp.where(jnp.arange(r.shape[0]) >= r.shape[0] - n_valid, ll_t, 0.0)
    return -0.5 * jnp.sum(ll_t)


def neg_log_likelihood(params, r, n_valid=None):
    return -log_likelihood(params, r, n_valid)


# -- fitting ----------------------------------------------------------------

# module-level so tests can monkeypatch the gate per model (sizing lives
# with the compaction feature: utils.optim)
_COMPACT_MIN_BATCH = optim.COMPACT_MIN_BATCH


def fit(r, *, max_iters: int = 80, tol: Optional[float] = None,
        backend: str = "auto", count_evals: bool = False,
        compact: bool = True, align_mode: Optional[str] = None) -> FitResult:
    """Fit GARCH(1,1) per series -> natural params ``[batch?, 3]``.

    ``count_evals=True`` (pallas backend only) returns ``(FitResult, info)``
    with the optimizer's pass-accounting dict (``utils.optim``).

    ``compact=False`` disables straggler compaction for run-to-run
    reproducibility (it engages on the pallas backend at batches >=
    ``utils.optim.COMPACT_MIN_BATCH`` = 4096 and is a different compiled
    program — bitwise outputs can differ from the uncompacted run).

    ``align_mode`` is the static alignment hint (``base.resolve_align_mode``)
    the chunk driver threads through sliced walks to skip the per-chunk NaN
    probe; a hint too strong for the data flags the violating rows
    (DIVERGED / EXCLUDED) instead of silently misfitting them.
    ``FitResult.status`` carries per-row ``reliability.FitStatus`` codes."""
    rb, single = ensure_batched(r)
    if tol is None:
        tol = 1e-7 if rb.dtype == jnp.float64 else 1e-4
    backend = resolve_backend(backend, rb.dtype, rb.shape[1])
    require_pallas_for_count_evals(count_evals, backend)
    bsz = rb.shape[0]
    align_mode = resolve_align_mode(rb, align_mode)
    # lazy straggler compile (utils.optim stage-1/stage-2 split, ADVICE r5):
    # the compacted stage-2 program is traced/compiled only when stage 1
    # actually leaves unconverged rows — same gate and host check as
    # models.arima.fit.  count_evals keeps the inline instrumented driver.
    # traced inputs keep the fully traceable inline program (the lazy gate
    # needs a host check of the straggler count) — see models.arima.fit
    lazy = (compact and not count_evals
            and backend in ("pallas", "pallas-interpret")
            and not isinstance(rb, jax.core.Tracer)
            and bsz >= _COMPACT_MIN_BATCH
            and optim.compaction_cap(bsz) < bsz)
    if lazy:
        out, aux = _fit_stage1_program(
            max_iters, float(tol), backend, align_mode)(rb)
        if int(aux["carry"].undone) > 0 and int(aux["carry"].k) < max_iters:
            out = _fit_stage2_program(max_iters, float(tol), backend)(aux)
        return debatch_fit(out, single, False)
    out = _fit_program(max_iters, float(tol), backend, align_mode,
                       count_evals, compact)(rb)
    return debatch_fit(out, single, count_evals)


def _garch_prep(rb, align_mode: str):
    """Shared front half of both GARCH fit programs (inline + lazy
    stage-1): alignment, the moment-ish start (omega = 0.1*var, alpha=0.1,
    beta=0.8) in transformed space, and the mean-nll denominator (see
    models.arima: same argmin, O(1) gradients keep the relative stopping
    rule reachable at f32).  ONE implementation so the seeds can never
    diverge between the two paths."""
    ra, nv = maybe_align(rb, align_mode)
    var0 = jax.vmap(_masked_var)(ra, nv)
    nat0 = jnp.stack(
        [0.1 * jnp.maximum(var0, 1e-10), jnp.full_like(var0, 0.1),
         jnp.full_like(var0, 0.8)], axis=1
    )
    u0 = jax.vmap(_from_natural)(nat0)
    n_eff = jnp.maximum(nv, 1).astype(ra.dtype)
    return ra, nv, u0, n_eff


@jit_program
def _fit_program(max_iters, tol, backend, align_mode="general",
                 count_evals=False, compact=True):
    def run(rb):
        ra, nv, u0, n_eff = _garch_prep(rb, align_mode)
        if backend in ("pallas", "pallas-interpret"):
            from ..ops import pallas_kernels as pk

            interp = backend == "pallas-interpret"

            def fb(u):
                nat = jax.vmap(_to_natural)(u)
                return pk.garch_neg_loglik(nat, ra, nv, interpret=interp) / n_eff

            # straggler compaction (utils.optim): the objective closes over
            # the NATURAL-layout panel (the kernel folds internally), so the
            # subset gather is a plain row gather
            bsz = ra.shape[0]
            cap = optim.compaction_cap(bsz)
            straggler_fun = None
            if compact and bsz >= _COMPACT_MIN_BATCH:

                def straggler_fun(idxc):
                    ras, nvs, nes = ra[idxc], nv[idxc], n_eff[idxc]

                    def fb_s(u):
                        nat = jax.vmap(_to_natural)(u)
                        return pk.garch_neg_loglik(
                            nat, ras, nvs, interpret=interp) / nes

                    return fb_s

            res = optim.minimize_lbfgs_batched(
                fb, u0, max_iters=max_iters, tol=tol, count_evals=count_evals,
                straggler_fun=straggler_fun, straggler_cap=cap)
            info = None
            if count_evals:
                res, info = res
        else:
            def objective(u, data):
                rv, n, ne = data
                return neg_log_likelihood(_to_natural(u), rv, n) / ne

            res = optim.batched_minimize(
                objective, u0, (ra, nv, n_eff), max_iters=max_iters, tol=tol
            )
        ok = nv >= 10  # GARCH needs a handful of observations to identify
        params = jnp.where(ok[:, None], jax.vmap(_to_natural)(res.x), jnp.nan)
        out = FitResult(
            params,
            jnp.where(ok, res.f * n_eff, jnp.nan),
            res.converged & ok,
            res.iters,
            derive_status(ok, res.converged, params),
        )
        return (out, info) if count_evals else out

    return run


def _finalize_garch_fit(res, ok, n_eff):
    """Optimizer result -> FitResult (same ops as the inline program)."""
    params = jnp.where(ok[:, None], jax.vmap(_to_natural)(res.x), jnp.nan)
    return FitResult(
        params,
        jnp.where(ok, res.f * n_eff, jnp.nan),
        res.converged & ok,
        res.iters,
        derive_status(ok, res.converged, params),
    )


@jit_program
def _fit_stage1_program(max_iters, tol, backend, align_mode="general"):
    """Stage 1 of the lazily compiled compact GARCH fit (see
    ``models.arima._fit_stage1_program``): lockstep loop + straggler
    gather, stage 2 compiled only when needed.  Pallas backends only."""

    def run(rb):
        ra, nv, u0, n_eff = _garch_prep(rb, align_mode)
        from ..ops import pallas_kernels as pk

        interp = backend == "pallas-interpret"

        def fb(u):
            nat = jax.vmap(_to_natural)(u)
            return pk.garch_neg_loglik(nat, ra, nv, interpret=interp) / n_eff

        cap = optim.compaction_cap(ra.shape[0])
        res1, carry = optim.lbfgs_batched_stage1(
            fb, u0, straggler_cap=cap, max_iters=max_iters, tol=tol)
        ok = nv >= 10
        # the objective closes over the NATURAL-layout panel, so the
        # compacted problem's data is a plain row gather, done here so the
        # stage-2 program is a pure function of its inputs
        aux = {"carry": carry, "res": res1, "ras": ra[carry.idxc],
               "nvs": nv[carry.idxc], "nes": n_eff[carry.idxc],
               "ok": ok, "n_eff": n_eff}
        return _finalize_garch_fit(res1, ok, n_eff), aux

    return run


@jit_program
def _fit_stage2_program(max_iters, tol, backend):
    """Stage 2 of the lazy compact GARCH fit: finish the gathered
    stragglers and scatter back (compiled on first actual need)."""
    interp = backend == "pallas-interpret"

    def run(aux):
        from ..ops import pallas_kernels as pk

        def fb_s(u):
            nat = jax.vmap(_to_natural)(u)
            return pk.garch_neg_loglik(
                nat, aux["ras"], aux["nvs"], interpret=interp) / aux["nes"]

        res = optim.lbfgs_batched_stage2(
            fb_s, aux["res"], aux["carry"], max_iters=max_iters, tol=tol)
        return _finalize_garch_fit(res, aux["ok"], aux["n_eff"])

    return run


def forecast(params, r, n_future: int):
    """Variance-path forecast -> ``[batch?, n_future]`` conditional variances.

    GARCH's mean forecast is identically zero; what users forecast is the
    VOLATILITY path: ``h_{T+1} = omega + alpha r_T^2 + beta h_T`` from the
    in-sample recursion's end state, then — future squared returns
    entering at their conditional expectation ``E[r^2] = h`` —
    ``h_{T+k} = omega + (alpha + beta) h_{T+k-1}``, decaying geometrically
    toward the unconditional variance.  Leading/trailing NaNs are
    tolerated (right-aligned span, same contract as :func:`fit`); rows
    with non-finite params or fewer than 2 valid observations come back
    NaN rather than a plausible-looking zero.
    """
    rb, single = ensure_batched(r)
    pb = jnp.atleast_2d(params)
    out = _forecast_program(n_future)(pb, rb)
    return out[0] if single else out


@jit_program
def _forecast_program(n_future):
    def run(pb, rb):
        def one(pr, rv):
            ra, nv = align_right(rv)
            h = variances(pr, ra, nv)
            omega, alpha, beta = pr[0], pr[1], pr[2]
            h1 = omega + alpha * ra[-1] ** 2 + beta * h[-1]

            def step(hp, _):
                return omega + (alpha + beta) * hp, hp

            _, hs = lax.scan(step, h1, None, length=n_future)
            ok = (nv >= 2) & jnp.all(jnp.isfinite(pr))
            return jnp.where(ok, hs, jnp.nan)

        return jax.vmap(one)(pb, rb)

    return run


def sample(params, key, n: int):
    """Simulate n returns from GARCH(1,1) (reference ``GARCHModel.sample``):
    standard-normal innovations through :func:`add_time_dependent_effects`."""
    params = jnp.asarray(params, jnp.result_type(float))
    eps = jax.random.normal(key, (n,), params.dtype)
    return add_time_dependent_effects(params, eps)


def add_time_dependent_effects(params, x):
    """White noise -> GARCH returns: scale by the running conditional vol.

    The recursion needs r_{t-1}, which it itself produces, so this scan
    carries (h, r_prev); the variance path it induces is exactly
    ``_variance_scan`` seeded with r_prev = 0 and h_0 = the unconditional
    variance — which is what :func:`remove_time_dependent_effects` replays.
    """
    xb, single = ensure_batched(x)
    pb = jnp.atleast_2d(params)
    out = _add_effects_batched(pb, xb)
    return out[0] if single else out


@jax.jit
def _add_effects_batched(pb, xb):
    def one(pr, xv):
        omega, alpha, beta = pr[0], pr[1], pr[2]

        def step(carry, e):
            h, r_prev = carry
            h = omega + alpha * r_prev**2 + beta * h
            r = jnp.sqrt(jnp.maximum(h, 1e-12)) * e
            return (h, r), r

        _, r = lax.scan(step, (_unconditional_var(pr), jnp.zeros((), xv.dtype)), xv)
        return r

    return jax.vmap(one)(pb, xb)


def remove_time_dependent_effects(params, r):
    """GARCH returns -> standardized residuals r_t / sqrt(h_t), replaying
    :func:`add_time_dependent_effects`'s variance path so the pair
    round-trips exactly."""
    rb, single = ensure_batched(r)
    pb = jnp.atleast_2d(params)
    out = _remove_effects_batched(pb, rb)
    return out[0] if single else out


@jax.jit
def _remove_effects_batched(pb, rb):
    def one(pr, rv):
        r_sq_prev = jnp.concatenate([jnp.zeros((1,), rv.dtype), rv[:-1] ** 2])
        h = _variance_scan(pr, _unconditional_var(pr), r_sq_prev)
        return rv / jnp.sqrt(jnp.maximum(h, 1e-12))

    return jax.vmap(one)(pb, rb)


# ---------------------------------------------------------------------------
# AR(1) + GARCH(1,1)
# ---------------------------------------------------------------------------


def _argarch_to_natural(u):
    return jnp.concatenate([u[:2], _to_natural(u[2:])])


def _argarch_from_natural(params):
    return jnp.concatenate([params[:2], _from_natural(params[2:])])


def argarch_neg_log_likelihood(params, y, n_valid=None):
    """y_t = c + phi y_{t-1} + r_t with GARCH(1,1) innovations r."""
    c, phi = params[0], params[1]
    n = y.shape[0]
    prev = jnp.concatenate([y[:1], y[:-1]])
    r = y - c - phi * prev
    # one code path for trimmed and padded series: condition on the first
    # valid observation, whose residual is excluded from both the variance
    # seed and the likelihood sum (one fewer residual than observations)
    nv = jnp.asarray(n, jnp.int32) if n_valid is None else n_valid
    start = n - nv
    r = jnp.where(jnp.arange(n) <= start, 0.0, r)
    return neg_log_likelihood(params[2:], r, nv - 1)


def fit_argarch(y, *, max_iters: int = 100, tol: Optional[float] = None,
                backend: str = "auto", compact: bool = True,
                align_mode: Optional[str] = None) -> FitResult:
    """Fit AR(1)+GARCH(1,1) -> natural params ``[batch?, 5]``
    (reference ``ARGARCH.fitModel``).

    ``compact=False`` disables straggler compaction (see :func:`fit`);
    ``align_mode`` is the static alignment hint (``base.resolve_align_mode``)
    — a hint too strong for the data flags the violating rows instead of
    silently misfitting them;
    ``FitResult.status`` carries per-row ``reliability.FitStatus`` codes."""
    yb, single = ensure_batched(y)
    if tol is None:
        tol = 1e-7 if yb.dtype == jnp.float64 else 1e-4
    backend = resolve_backend(backend, yb.dtype, yb.shape[1])
    bsz = yb.shape[0]
    align_mode = resolve_align_mode(yb, align_mode)
    # lazy straggler compile: same stage-1/stage-2 split (and gate) as
    # fit() above — the compacted stage-2 program is traced/compiled only
    # when stage 1 actually leaves unconverged rows (ROADMAP follow-on)
    lazy = (compact and backend in ("pallas", "pallas-interpret")
            and not isinstance(yb, jax.core.Tracer)
            and bsz >= _COMPACT_MIN_BATCH
            and optim.compaction_cap(bsz) < bsz)
    if lazy:
        out, aux = _fit_argarch_stage1_program(
            max_iters, float(tol), backend, align_mode)(yb)
        if int(aux["carry"].undone) > 0 and int(aux["carry"].k) < max_iters:
            out = _fit_argarch_stage2_program(
                max_iters, float(tol), backend)(aux)
        return debatch(out, single)
    return debatch(
        _fit_argarch_program(max_iters, float(tol), backend, compact,
                             align_mode)(yb),
        single)


def _argarch_prep(yb, align_mode: str):
    """Shared front half of the ARGARCH fit programs (inline + lazy
    stage-1): alignment, the AR(1)-by-autocorrelation + GARCH-moment init
    in transformed space, and the mean-nll denominator.  ONE implementation
    so the seeds can never diverge between the two paths (see
    :func:`_garch_prep`)."""
    ya, nv = maybe_align(yb, align_mode)

    # init: OLS-ish AR(1) by autocorrelation, then GARCH moments on resid
    # (masked over each right-aligned valid span)
    T = ya.shape[1]
    m = (jnp.arange(T)[None, :] >= (T - nv)[:, None]).astype(ya.dtype)
    nvf = jnp.maximum(nv, 1).astype(ya.dtype)
    mean = jnp.sum(ya * m, axis=1) / nvf
    yc = (ya - mean[:, None]) * m
    phi0 = jnp.sum(yc[:, 1:] * yc[:, :-1], axis=1) / jnp.maximum(
        jnp.sum(yc * yc, axis=1), 1e-12
    )
    phi0 = jnp.clip(phi0, -0.95, 0.95)
    c0 = mean * (1.0 - phi0)
    resid = (ya[:, 1:] - c0[:, None] - phi0[:, None] * ya[:, :-1]) * m[:, 1:]
    resid_var = jnp.sum(resid**2, axis=1) / nvf
    nat0 = jnp.stack(
        [
            c0,
            phi0,
            0.1 * jnp.maximum(resid_var, 1e-8),
            jnp.full_like(c0, 0.1),
            jnp.full_like(c0, 0.8),
        ],
        axis=1,
    )
    u0 = jax.vmap(_argarch_from_natural)(nat0)
    n_eff = jnp.maximum(nv - 1, 1).astype(ya.dtype)
    return ya, nv, u0, n_eff


def _finalize_argarch_fit(res, ok, n_eff):
    """Optimizer result -> FitResult (same ops as the inline program)."""
    params = jnp.where(
        ok[:, None], jax.vmap(_argarch_to_natural)(res.x), jnp.nan)
    return FitResult(
        params,
        jnp.where(ok, res.f * n_eff, jnp.nan),
        res.converged & ok,
        res.iters,
        derive_status(ok, res.converged, params),
    )


def _argarch_fb(ya, prev, nv, n_eff, interp):
    """The fused ARGARCH objective over the natural-layout panel — shared
    by the inline program, its straggler subset, and both lazy stages (the
    compacted data is a plain row gather of each closed-over array)."""
    from ..ops import pallas_kernels as pk

    t_idx = jnp.arange(ya.shape[1])
    start = ya.shape[1] - nv

    def fb(u):
        nat = jax.vmap(_argarch_to_natural)(u)
        r = ya - nat[:, 0:1] - nat[:, 1:2] * prev
        # condition on the first valid observation (see
        # argarch_neg_log_likelihood): its residual is excluded
        r = jnp.where(t_idx[None, :] <= start[:, None], 0.0, r)
        return pk.garch_neg_loglik(nat[:, 2:], r, nv - 1,
                                   interpret=interp) / n_eff

    return fb


@jit_program
def _fit_argarch_program(max_iters, tol, backend, compact=True,
                         align_mode="general"):
    def run(yb):
        ya, nv, u0, n_eff = _argarch_prep(yb, align_mode)
        if backend in ("pallas", "pallas-interpret"):
            interp = backend == "pallas-interpret"
            prev = jnp.concatenate([ya[:, :1], ya[:, :-1]], axis=1)
            fb = _argarch_fb(ya, prev, nv, n_eff, interp)

            # straggler compaction: row gathers, as in fit()
            bsz = ya.shape[0]
            cap = optim.compaction_cap(bsz)
            straggler_fun = None
            if compact and bsz >= _COMPACT_MIN_BATCH:

                def straggler_fun(idxc):
                    return _argarch_fb(ya[idxc], prev[idxc], nv[idxc],
                                       n_eff[idxc], interp)

            res = optim.minimize_lbfgs_batched(
                fb, u0, max_iters=max_iters, tol=tol,
                straggler_fun=straggler_fun, straggler_cap=cap)
        else:
            def obj_scaled(u, data):
                yv, n, ne = data
                return argarch_neg_log_likelihood(_argarch_to_natural(u), yv, n) / ne

            res = optim.batched_minimize(
                obj_scaled, u0, (ya, nv, n_eff), max_iters=max_iters, tol=tol
            )
        ok = nv >= 12
        return _finalize_argarch_fit(res, ok, n_eff)

    return run


@jit_program
def _fit_argarch_stage1_program(max_iters, tol, backend, align_mode="general"):
    """Stage 1 of the lazily compiled compact ARGARCH fit (see
    ``models.arima._fit_stage1_program``): lockstep loop + straggler
    gather, stage 2 compiled only when needed.  Pallas backends only."""

    def run(yb):
        ya, nv, u0, n_eff = _argarch_prep(yb, align_mode)
        interp = backend == "pallas-interpret"
        prev = jnp.concatenate([ya[:, :1], ya[:, :-1]], axis=1)
        fb = _argarch_fb(ya, prev, nv, n_eff, interp)
        cap = optim.compaction_cap(ya.shape[0])
        res1, carry = optim.lbfgs_batched_stage1(
            fb, u0, straggler_cap=cap, max_iters=max_iters, tol=tol)
        ok = nv >= 12
        # the objective closes over the NATURAL-layout panel, so the
        # compacted problem's data is a plain row gather of each array,
        # done here so the stage-2 program is a pure function of its inputs
        aux = {"carry": carry, "res": res1, "yas": ya[carry.idxc],
               "prevs": prev[carry.idxc], "nvs": nv[carry.idxc],
               "nes": n_eff[carry.idxc], "ok": ok, "n_eff": n_eff}
        return _finalize_argarch_fit(res1, ok, n_eff), aux

    return run


@jit_program
def _fit_argarch_stage2_program(max_iters, tol, backend):
    """Stage 2 of the lazy compact ARGARCH fit: finish the gathered
    stragglers and scatter back (compiled on first actual need)."""
    interp = backend == "pallas-interpret"

    def run(aux):
        fb_s = _argarch_fb(aux["yas"], aux["prevs"], aux["nvs"],
                           aux["nes"], interp)
        res = optim.lbfgs_batched_stage2(
            fb_s, aux["res"], aux["carry"], max_iters=max_iters, tol=tol)
        return _finalize_argarch_fit(res, aux["ok"], aux["n_eff"])

    return run


def argarch_sample(params, key, n: int):
    """Simulate AR(1)+GARCH(1,1)."""
    return _argarch_sample_program(n)(params, key)


@jit_program
def _argarch_sample_program(n):
    def run(params, key):
        params = jnp.asarray(params, jnp.result_type(float))
        c, phi = params[0], params[1]
        r = sample(params[2:], key, n)

        def step(y_prev, rt):
            y = c + phi * y_prev + rt
            return y, y

        _, y = lax.scan(step, c / jnp.maximum(1.0 - phi, 1e-6), r)
        return y

    return run
