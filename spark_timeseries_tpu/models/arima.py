"""ARIMA(p, d, q) — the flagship model family (L4).

TPU-native rebuild of the reference's ``sparkts/models/ARIMA.scala``
(SURVEY.md Sections 2.2 and 3.3, upstream path unverified).  Same algorithm
family, redesigned for batch execution:

===============================  ==========================================
reference (per series, JVM)      here (whole panel, one XLA computation)
===============================  ==========================================
order-d differencing             static slicing (``ops.univariate``)
Hannan-Rissanen init             batched OLS via ``jnp.linalg.lstsq`` on
                                 stacked lag matrices (MXU matmuls)
conditional-sum-of-squares       ``lax.scan`` over time computing one-step
likelihood (hand-coded loop)     prediction errors; vmapped over series
hand-derived CSS gradient        ``jax.grad`` through the scan
Commons-Math CG / BOBYQA         fixed-budget vmapped L-BFGS
                                 (``utils.optim``) with per-series
                                 convergence masks
===============================  ==========================================

Parameter vector layout (matching the reference's ``coefficients``):
``[c (if intercept), phi_1..phi_p, theta_1..theta_q]``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import univariate as uv
from ..utils import optim
from ..utils.linalg import ols as _ols
from ..utils.linalg import ridge_solve as _ridge_solve
from .base import (FitResult, align_mode_on_host, align_right, debatch,
                   debatch_fit, derive_status, ensure_batched, jit_program,
                   maybe_align, require_pallas_for_count_evals,
                   resolve_align_mode, resolve_backend)

Order = Tuple[int, int, int]
Seasonal = Tuple[int, int, int, int]  # (P, D, Q, s)

# module-level so tests can monkeypatch the gate per model; the value and
# the cap sizing live with the compaction feature (utils.optim)
_COMPACT_MIN_BATCH = optim.COMPACT_MIN_BATCH


def _n_params(order: Order, include_intercept: bool) -> int:
    p, _, q = order
    return int(include_intercept) + p + q


def _split_params(params, order: Order, include_intercept: bool):
    p, _, q = order
    i = int(include_intercept)
    c = params[0] if include_intercept else jnp.zeros((), params.dtype)
    phi = params[i : i + p]
    theta = params[i + p : i + p + q]
    return c, phi, theta


def _difference(y, d: int):
    """Order-d differencing with the first d entries dropped (static shape)."""
    for _ in range(d):
        y = y[1:] - y[:-1]
    return y


def _lagged(yd, p: int):
    """``[n, p]`` matrix of lags 1..p, zero-padded before the start."""
    n = yd.shape[0]
    cols = []
    for k in range(1, p + 1):
        cols.append(jnp.concatenate([jnp.zeros((k,), yd.dtype), yd[: n - k]]))
    if not cols:
        return jnp.zeros((n, 0), yd.dtype)
    return jnp.stack(cols, axis=1)


# ---------------------------------------------------------------------------
# CSS likelihood
# ---------------------------------------------------------------------------


def _css_errors_poly(c, phi, theta, yd, condition: bool = True, n_valid=None,
                     condition_lags=None):
    """One-step-ahead prediction errors of the ARMA recursion with EXPLICIT
    lag-coefficient vectors ``phi [p_full]`` / ``theta [q_full]`` — the one
    scan the plain ARMA path (:func:`_css_errors`), the seasonal
    expanded-polynomial path (:func:`_sarima_css_errors`), and the fused
    multi-order grid fit (:func:`fit_grid`) all run.

    ``condition=True`` zeroes errors for the first ``p_full`` valid steps
    (conditional likelihood — the reference's CSS); ``condition=False``
    keeps zero-padded-lag errors for every valid t, which makes the
    transform exactly invertible (remove/add_time_dependent_effects).

    ``n_valid`` (traced scalar) marks a right-aligned valid span (see
    ``base.align_right``): errors in the zero prefix are forced to 0 so
    padded series contribute nothing there.

    ``condition_lags`` overrides the conditioning depth: the fused grid
    fit zero-pads every order's coefficient vectors to the grid maximum
    (``phi.shape[0]`` is then the GRID's depth, not this order's), but
    the likelihood must still condition out exactly this order's
    ``p_full`` steps — the padded slots multiply by exact 0.0 and change
    nothing else.
    """
    p = phi.shape[0]
    q = theta.shape[0]
    n = yd.shape[0]
    t_idx = jnp.arange(n)
    start = 0
    if n_valid is not None:
        start = n - n_valid
        # differencing across the padding boundary leaves a garbage raw-level
        # value at yd[start-1]; zero the prefix so lags reaching below start
        # bring exactly the zeros a trimmed series would see
        yd = jnp.where(t_idx >= start, yd, 0.0)
    ylags = _lagged(yd, p)  # [n, p]
    cond_p = p if condition_lags is None else condition_lags
    zero_before = start + cond_p if condition else start

    def step(errs, inp):
        yt, yl, t = inp
        pred = c + jnp.dot(phi, yl) + (jnp.dot(theta, errs) if q else 0.0)
        e = yt - pred
        e = jnp.where(t >= zero_before, e, 0.0)
        new_errs = jnp.concatenate([e[None], errs[:-1]]) if q else errs
        return new_errs, e

    errs0 = jnp.zeros((max(q, 1),), yd.dtype)
    _, e = lax.scan(step, errs0, (yd, ylags, t_idx))
    return e


def _css_errors(params, yd, order: Order, include_intercept: bool, condition: bool = True,
                n_valid=None):
    """ARMA(p,q) CSS errors from the packed parameter vector (see
    :func:`_css_errors_poly` for the recursion's contract)."""
    c, phi, theta = _split_params(params, order, include_intercept)
    return _css_errors_poly(c, phi, theta, yd, condition=condition,
                            n_valid=n_valid)


def css_neg_loglik(params, yd, order: Order, include_intercept: bool, n_valid=None):
    """Negative conditional-sum-of-squares Gaussian log-likelihood with the
    innovation variance concentrated out (sigma^2 = CSS / n_eff)."""
    p = order[0]
    nv = yd.shape[0] if n_valid is None else n_valid
    e = _css_errors(params, yd, order, include_intercept, n_valid=n_valid)
    n_eff = nv - p
    css = jnp.sum(e * e)
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


def approx_aic(params, yd, order: Order, include_intercept: bool):
    k = _n_params(order, include_intercept)
    return 2.0 * css_neg_loglik(params, yd, order, include_intercept) + 2.0 * k


# ---------------------------------------------------------------------------
# Seasonal extension: SARIMA(p,d,q)(P,D,Q)_s through the same CSS recursion
# ---------------------------------------------------------------------------
#
# The multiplicative seasonal model
#   Phi(L^s) phi(L) (1-L)^d (1-L^s)^D y_t = c + Theta(L^s) theta(L) e_t
# is the paper's most-missed scenario (PAPER.md section 0/L-map: upstream
# spark-ts users pick seasonal orders as part of model selection).  Rather
# than a second likelihood implementation, the seasonal polynomials are
# EXPANDED into plain lag-coefficient vectors (static shapes: p+P*s AR lags,
# q+Q*s MA lags) and run through the exact `_css_errors_poly` scan the
# non-seasonal fit uses — one recursion, one conditioning rule, one
# concentrated-variance likelihood.  Seasonal fits run on the portable scan
# backend (the fused Pallas kernel's folded layout has no seasonal lag
# structure); `auto_fit` (models.auto) is the intended high-volume caller.


def _validate_seasonal(seasonal) -> Optional[Seasonal]:
    """Normalize a ``(P, D, Q, s)`` seasonal spec; ``None`` (or an all-zero
    structure) means "no seasonal terms" and returns None."""
    if seasonal is None:
        return None
    try:
        P, D, Q, s = (int(v) for v in seasonal)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"seasonal must be a (P, D, Q, s) tuple, got {seasonal!r}") from e
    if P == 0 and D == 0 and Q == 0:
        return None
    if min(P, D, Q) < 0:
        raise ValueError(f"seasonal orders must be >= 0, got {seasonal!r}")
    if s < 2:
        raise ValueError(
            f"seasonal period s must be >= 2 when (P, D, Q) != 0, "
            f"got {seasonal!r}")
    return (P, D, Q, s)


def _difference_seasonal(y, D: int, s: int):
    """Order-D seasonal differencing at lag s (static shapes: drops D*s)."""
    for _ in range(D):
        y = y[s:] - y[:-s]
    return y


def _n_params_seasonal(order: Order, seasonal: Seasonal,
                       include_intercept: bool) -> int:
    p, _, q = order
    P, _, Q, _ = seasonal
    return int(include_intercept) + p + q + P + Q


def _split_params_seasonal(params, order: Order, seasonal: Seasonal,
                           include_intercept: bool):
    """Layout: ``[c (if intercept), phi_1..p, theta_1..q, PHI_1..P,
    THETA_1..Q]`` — the non-seasonal prefix matches :func:`_split_params`
    so a caller can warm-start a seasonal fit from a plain ARMA one."""
    p, _, q = order
    P, _, Q, _ = seasonal
    i = int(include_intercept)
    c = params[0] if include_intercept else jnp.zeros((), params.dtype)
    phi = params[i: i + p]
    theta = params[i + p: i + p + q]
    sphi = params[i + p + q: i + p + q + P]
    stheta = params[i + p + q + P: i + p + q + P + Q]
    return c, phi, theta, sphi, stheta


def _expand_seasonal_poly(vals, svals, s: int, cross: float):
    """Lag coefficients of the multiplicative polynomial product.

    For the AR side (``cross=-1``): ``(1 - sum v_i L^i)(1 - sum w_j L^js)``
    gives the recursion coefficients ``a`` with ``y_t = c + sum a_k y_{t-k}
    + ...`` — ``a[:p] = v``, ``a[js-1] = w_j``, ``a[js+i-1] = -v_i w_j``.
    For the MA side (``cross=+1``): ``(1 + sum v L)(1 + sum w L^js)`` gives
    ``b`` with the cross terms ADDED.  All shapes static (p, P, s are
    Python ints), so the expansion unrolls into a handful of scatter-adds
    at trace time.
    """
    p = int(vals.shape[0])
    P = int(svals.shape[0])
    n = p + P * s
    if n == 0:
        return jnp.zeros((0,), vals.dtype)
    full = jnp.zeros((n,), vals.dtype)
    if p:
        full = full.at[:p].add(vals)
    for j in range(P):
        lag = (j + 1) * s
        full = full.at[lag - 1].add(svals[j])
        if p:
            full = full.at[lag: lag + p].add(cross * svals[j] * vals)
    return full


def _sarima_css_errors(params, yd, order: Order, seasonal: Seasonal,
                       include_intercept: bool, condition: bool = True,
                       n_valid=None):
    """CSS errors of the expanded seasonal recursion (``yd`` already both
    plain- and seasonally-differenced)."""
    _, _, _, s = seasonal
    c, phi, theta, sphi, stheta = _split_params_seasonal(
        params, order, seasonal, include_intercept)
    phi_full = _expand_seasonal_poly(phi, sphi, s, -1.0)
    theta_full = _expand_seasonal_poly(theta, stheta, s, 1.0)
    return _css_errors_poly(c, phi_full, theta_full, yd,
                            condition=condition, n_valid=n_valid)


def seasonal_lag_span(order: Order, seasonal: Optional[Seasonal]
                      ) -> Tuple[int, int, int]:
    """``(p_full, q_full, d_full)`` — the expanded AR/MA lag depths and the
    total differencing the (optionally seasonal) model conditions on.
    The criterion layer (``models.auto``) uses these to compute the same
    effective sample size the concentrated likelihood divides by."""
    p, d, q = order
    if seasonal is None:
        return p, q, d
    P, D, Q, s = seasonal
    return p + P * s, q + Q * s, d + D * s


def sarima_neg_loglik(params, yd, order: Order, seasonal: Seasonal,
                      include_intercept: bool, n_valid=None):
    """Concentrated Gaussian CSS likelihood of the seasonal recursion —
    same concentration rule as :func:`css_neg_loglik` with the expanded
    AR depth ``p + P*s`` conditioned out."""
    p_full, _, _ = seasonal_lag_span(order, seasonal)
    nv = yd.shape[0] if n_valid is None else n_valid
    e = _sarima_css_errors(params, yd, order, seasonal, include_intercept,
                           n_valid=n_valid)
    n_eff = nv - p_full
    css = jnp.sum(e * e)
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


# ---------------------------------------------------------------------------
# Hannan-Rissanen initialization
# ---------------------------------------------------------------------------


def hannan_rissanen(yd, order: Order, include_intercept: bool, n_valid=None):
    """Two-stage startup values: long-AR residuals stand in for the
    unobserved MA innovations, then one OLS of y on [1, y-lags, e-lags].

    With ``n_valid`` (right-aligned span), row selection becomes 0/1 row
    weights — zeroed rows add nothing to the normal equations, keeping the
    math identical to the static-slice full-series case.
    """
    p, _, q = order
    n = yd.shape[0]
    m = min(p + q + 1, max(n // 4, 1))  # long-AR order, static
    start = 0 if n_valid is None else n - n_valid
    t = jnp.arange(n)

    # stage 1: AR(m) by OLS -> residual estimates of the innovations
    ylags_m = _lagged(yd, m)
    ones = jnp.ones((n, 1), yd.dtype)
    Xar = jnp.concatenate([ones, ylags_m], axis=1)
    # rows with any zero-padded lag (t < start + m) get weight 0
    w1 = (t >= start + m).astype(yd.dtype)
    beta_ar = _ols(Xar * w1[:, None], yd * w1)
    ehat = (yd - Xar @ beta_ar) * w1

    # stage 2: OLS of y on [1?, y-lags 1..p, e-lags 1..q]
    cols = []
    if include_intercept:
        cols.append(ones)
    if p:
        cols.append(_lagged(yd, p))
    if q:
        cols.append(_lagged(ehat, q))
    if not cols:
        return jnp.zeros((0,), yd.dtype)
    X = jnp.concatenate(cols, axis=1)
    w2 = (t >= start + m + q).astype(yd.dtype)  # rows where every regressor is real
    return _ols(X * w2[:, None], yd * w2)


def _shift_cols(x2, k: int):
    """``[B, T]`` shifted right by ``k`` along time (zero-fill), static k."""
    if k == 0:
        return x2
    return jnp.pad(x2, ((0, 0), (k, 0)))[:, : x2.shape[1]]


def _wols_cols(cols, y2, w, ridge: float = 1e-8):
    """Weighted OLS from ``[B, T]`` column vectors: the same ridge-stabilized
    normal equations as ``utils.linalg.ols`` on the design ``X * w`` (binary
    weights: w^2 = w), assembled from masked inner products so no
    ``[B, T, k]`` design matrix is ever materialized."""
    XtX = jnp.stack(
        [jnp.stack([jnp.sum(w * ci * cj, axis=1) for cj in cols], -1)
         for ci in cols], -2,
    )  # [B, k, k]
    Xty = jnp.stack([jnp.sum(w * ci * y2, axis=1) for ci in cols], -1)  # [B, k]
    return _ridge_solve(XtX, Xty, ridge)


def hannan_rissanen_batched(yd, order: Order, include_intercept: bool, nvd):
    """Whole-batch Hannan-Rissanen init ``[B, k]`` — same math as
    ``vmap(hannan_rissanen)`` (identical weighted normal equations), built
    from masked lagged products with STATIC shifts.

    The vmapped version materializes a ``[B, T, m+1]`` lag design and runs
    batched small solves per stage; at panel scale (100k x 1k) building and
    re-reading those designs costs more than the entire L-BFGS fit.  Here
    every Gram entry is a masked elementwise product + row reduction that
    XLA fuses over a handful of shifted views.
    """
    p, _, q = order
    b, n = yd.shape
    m = min(p + q + 1, max(n // 4, 1))
    t = jnp.arange(n)[None, :]
    start = n - nvd  # [B]
    w1 = (t >= (start + m)[:, None]).astype(yd.dtype)

    shifts = [_shift_cols(yd, i) for i in range(max(m, p) + 1)]
    ones = jnp.ones_like(yd)

    # stage 1: AR(m) of yd on [1, lags 1..m] -> innovation estimates
    cols1 = [ones] + shifts[1 : m + 1]
    beta1 = _wols_cols(cols1, yd, w1)  # [B, m+1]
    pred = sum(beta1[:, j, None] * c for j, c in enumerate(cols1))
    ehat = (yd - pred) * w1

    # stage 2: OLS of yd on [1?, y-lags 1..p, e-lags 1..q]
    cols2 = ([ones] if include_intercept else [])
    cols2 += shifts[1 : p + 1]
    cols2 += [_shift_cols(ehat, j) for j in range(1, q + 1)]
    if not cols2:
        return jnp.zeros((b, 0), yd.dtype)
    w2 = (t >= (start + m + q)[:, None]).astype(yd.dtype)
    return _wols_cols(cols2, yd, w2)


# ---------------------------------------------------------------------------
# Fitting
# ---------------------------------------------------------------------------


def fit(
    y,
    order: Order,
    include_intercept: bool = True,
    *,
    seasonal: Optional[Seasonal] = None,
    method: str = "css-lbfgs",
    init_params: Optional[jax.Array] = None,
    max_iters: int = 60,
    tol: Optional[float] = None,
    backend: str = "auto",
    count_evals: bool = False,
    compact: bool = True,
    align_mode: Optional[str] = None,
) -> FitResult:
    """Fit ARIMA(p,d,q) to one series ``[time]`` or a batch ``[batch, time]``.

    The entire batch is one jitted computation: differencing -> vmapped
    Hannan-Rissanen -> batched L-BFGS on the CSS objective.  ``method``
    accepts ``"css-lbfgs"`` (also aliased from the reference's ``"css-cgd"``
    and ``"css-bobyqa"``) and ``"hannan-rissanen"`` (init only, no MLE).

    ``backend`` selects the CSS objective implementation: ``"scan"``
    (``vmap(lax.scan)``, runs everywhere), ``"pallas"`` (fused TPU kernel
    with hand-derived adjoint, ``ops.pallas_kernels``), or ``"auto"``
    (pallas whenever :func:`ops.pallas_kernels.supported` says so).

    ``count_evals=True`` (pallas backend only) returns ``(FitResult, info)``
    where ``info`` is the optimizer's pass-accounting dict
    (``utils.optim.minimize_lbfgs_batched``) — the benchmark publishes it so
    "how many objective passes does a fit spend" is a recorded number, not
    an estimate.

    ``compact=False`` disables straggler compaction (``utils.optim``) for
    run-to-run reproducibility: compaction engages automatically on the
    pallas backend at batches >= ``utils.optim.COMPACT_MIN_BATCH`` (4096;
    tests may monkeypatch the module-level ``_COMPACT_MIN_BATCH`` gate)
    and — while parity-gated at the distribution level — is a different
    compiled program, so individual rows on flat/non-convex stretches can
    reach different (equally valid) optima than an uncompacted run.

    ``align_mode`` (``"dense"`` / ``"no-trailing"`` / ``"general"``) is a
    static alignment hint that skips the per-panel NaN probe and its host
    sync (``base.resolve_align_mode``) — the chunk driver threads the
    panel-level mode into every sliced chunk fit.  Hint contract: an
    unknown name raises; a hint too strong for the data surfaces as
    flagged rows (DIVERGED under ``"dense"``, EXCLUDED with NaN params
    under ``"no-trailing"``), never as silently wrong estimates.

    ``seasonal=(P, D, Q, s)`` extends the recursion with multiplicative
    seasonal terms (SARIMA): the seasonal polynomials are expanded into
    plain lag coefficients and run through the SAME CSS scan, with the
    parameter layout ``[c?, phi_1..p, theta_1..q, PHI_1..P, THETA_1..Q]``.
    Seasonal fits run on the portable scan backend only (``backend`` must
    resolve away from pallas) and support the optimizing CSS methods.

    ``FitResult.status`` reports per-row ``reliability.FitStatus`` codes
    (OK / DIVERGED / EXCLUDED for a plain fit).
    """
    if method not in ("css-lbfgs", "css-cgd", "css-bobyqa", "hannan-rissanen"):
        raise ValueError(f"unknown method {method!r}")
    if count_evals and method == "hannan-rissanen":
        raise ValueError("count_evals requires an optimizing method")
    seasonal = _validate_seasonal(seasonal)
    if seasonal is not None:
        return _fit_seasonal(
            y, order, seasonal, include_intercept, method=method,
            init_params=init_params, max_iters=max_iters, tol=tol,
            backend=backend, count_evals=count_evals,
            align_mode=align_mode)
    p, d, q = order
    yb, single = ensure_batched(y)
    k = _n_params(order, include_intercept)
    if tol is None:
        # f32 gradients of a ~1k-term CSS bottom out near 1e-4 relative noise
        tol = 1e-6 if yb.dtype == jnp.float64 else 1e-4
    from ..ops import pallas_kernels as pk

    backend = resolve_backend(backend, yb.dtype, yb.shape[1] - d,
                              structural_ok=pk.css_structural_ok(p, q))
    require_pallas_for_count_evals(count_evals, backend)

    bsz = yb.shape[0]
    # lazy straggler compile (utils.optim stage-1/stage-2 split): compact
    # fits run stage 1 as their own program and only dispatch — and
    # therefore only ever trace+compile — the stage-2 straggler program
    # when stage 1 actually leaves unconverged rows (ADVICE r5: the inline
    # two-stage program roughly doubles compile time for batches that never
    # need it).  count_evals keeps the inline driver (pass accounting
    # instruments it); the gate mirrors the inline compaction gate.
    # traced inputs (fit called under an outer jit) cannot host-check the
    # straggler count — they keep the fully traceable inline program, same
    # as align_mode_on_host's tracer branch
    lazy = (compact and not count_evals and method != "hannan-rissanen"
            and backend in ("pallas", "pallas-interpret")
            and not isinstance(yb, jax.core.Tracer)
            and bsz >= _COMPACT_MIN_BATCH
            and optim.compaction_cap(bsz) < bsz)
    align_mode = resolve_align_mode(yb, align_mode)
    if lazy:
        run1 = _fit_stage1_program(
            order, include_intercept, backend, max_iters, float(tol),
            init_params is not None, align_mode)
        if init_params is None:
            out, aux = run1(yb)
        else:
            out, aux = run1(yb, jnp.asarray(init_params))
        # host gate: tiny scalar sync; stage 2 shares stage 1's iteration
        # budget, so an exhausted budget skips the dispatch entirely (the
        # scatter of unchanged state would be an identity)
        if int(aux["carry"].undone) > 0 and int(aux["carry"].k) < max_iters:
            run2 = _fit_stage2_program(
                order, include_intercept, backend, max_iters, float(tol),
                int(yb.shape[1] - d))
            out = run2(aux)
        return debatch_fit(out, single, False)
    run = _fit_program(
        order, include_intercept, method, backend, max_iters, float(tol),
        init_params is not None, align_mode, count_evals,
        compact,
    )
    if init_params is None:
        out = run(yb)
    else:
        out = run(yb, jnp.asarray(init_params))
    return debatch_fit(out, single, count_evals)


def _css_prep(yb, init_params, order: Order, include_intercept: bool,
              backend: str, align_mode: str, has_init: bool):
    """Shared front half of every CSS fit program: align + difference, the
    one-time folded layout (pallas backends), the Hannan-Rissanen (or
    caller-provided) init, the identifiability gate, and the mean-scaling
    denominator.  ONE implementation serves the inline `_fit_program` and
    the lazy `_fit_stage1_program` — the `ok` eligibility formulas must
    never diverge between them (the lazy path serves large batches, the
    inline one everything else, and the same panel content must get the
    same eligibility regardless of batch size)."""
    p, d, q = order
    k = _n_params(order, include_intercept)
    with jax.named_scope("arima.align_and_difference"):
        ya, nv0 = maybe_align(yb, align_mode)  # ragged: NaN head/tail
        yd = jax.vmap(lambda v: _difference(v, d))(ya)
        nvd = nv0 - d  # valid length after differencing
    from ..ops import pallas_kernels as _pk

    y3 = zb3 = None
    if backend in ("pallas", "pallas-interpret"):
        # fold ONCE per fit: the init sweeps and every optimizer
        # evaluation share this layout (css_prefold)
        y3, zb3 = _pk.css_prefold(yd, order, nvd)
    with jax.named_scope("arima.hannan_rissanen_init"):
        if has_init:
            init = jnp.broadcast_to(init_params, (yd.shape[0], k))
        elif y3 is not None and _pk.hr_structural_ok(p, q):
            # fused two-sweep moment kernels: same normal equations,
            # ~15x less HBM traffic than the shifted-reduce construction
            init = _pk.hr_init(yd, order, include_intercept, nvd,
                               interpret=backend == "pallas-interpret",
                               y3=y3)
        else:
            init = hannan_rissanen_batched(yd, order, include_intercept, nvd)
    # too-short series cannot be fit: need lags + a few dof
    ok = nvd >= p + q + max(p + q + 1, 1) + k + 2
    if not has_init:
        # Hannan-Rissanen's long-AR order m = min(p+q+1, n//4) is static
        # (shapes), so it is computed from the PADDED length; requiring
        # nvd >= 4*(p+q+1) ensures m would be p+q+1 either way, keeping
        # padded and trimmed inits identical inside the supported region
        ok = ok & (nvd >= 4 * (p + q + 1))
    # optimize the MEAN log-likelihood (nll / effective obs): same argmin,
    # but gradients are O(1) so the relative grad-norm stopping rule is
    # reachable at f32 instead of stalling on the accumulation noise floor
    # of a ~1k-term sum (the reported nll is unscaled)
    n_eff = jnp.maximum(nvd - p, 1).astype(yd.dtype)
    return yd, nvd, y3, zb3, init, ok, n_eff


@jit_program
def _fit_program(order: Order, include_intercept: bool, method: str,
                 backend: str, max_iters: int, tol: float, has_init: bool,
                 align_mode: str = "general", count_evals: bool = False,
                 compact: bool = True):
    p, d, q = order
    k = _n_params(order, include_intercept)

    def run(yb, init_params=None):
        yd, nvd, y3, zb3, init, ok, n_eff = _css_prep(
            yb, init_params, order, include_intercept, backend, align_mode,
            has_init)
        if method == "hannan-rissanen":
            nll = jax.vmap(
                lambda pr, v, n: css_neg_loglik(pr, v, order, include_intercept, n)
            )(init, yd, nvd)
            z = jnp.zeros((yd.shape[0],), jnp.int32)
            params = jnp.where(ok[:, None], init, jnp.nan)
            return FitResult(params, jnp.where(ok, nll, jnp.nan), ok, z,
                             derive_status(ok, ok, params))
        info = None
        if backend in ("pallas", "pallas-interpret"):
            from ..ops import pallas_kernels as _pk

            interp = backend == "pallas-interpret"
            bsz, T = yd.shape

            # straggler compaction (utils.optim): after most rows converge,
            # lockstep passes still stream the whole panel; gather the tail
            # into a 1/8-size problem instead.  The gather repacks folded
            # COLUMNS (series ride the lanes), grid-aligned by the cap
            cap = optim.compaction_cap(bsz)
            straggler_fun = None
            if compact and bsz >= _COMPACT_MIN_BATCH:
                tp = y3.shape[0]

                def straggler_fun(idxc, _y3=y3, _zb3=zb3):
                    y3s = _y3.reshape(tp, -1)[:, idxc].reshape(
                        tp, cap // 128, 128)
                    zb3s = _zb3.reshape(1, -1)[:, idxc].reshape(
                        1, cap // 128, 128)
                    nvs = nvd[idxc]
                    nes = n_eff[idxc]
                    return lambda P: _pk.css_neg_loglik_folded(
                        P, y3s, zb3s, T, order, include_intercept, nvs,
                        interpret=interp
                    ) / nes

            res = optim.minimize_lbfgs_batched(
                lambda P: _pk.css_neg_loglik_folded(
                    P, y3, zb3, T, order, include_intercept, nvd,
                    interpret=interp
                ) / n_eff,
                init,
                max_iters=max_iters,
                tol=tol,
                straggler_fun=straggler_fun,
                straggler_cap=cap,
                count_evals=count_evals,
            )
            if count_evals:
                res, info = res
        else:
            res = optim.batched_minimize(
                lambda pr, data: css_neg_loglik(
                    pr, data[0], order, include_intercept, data[1]
                ) / data[2],
                init,
                (yd, nvd, n_eff),
                max_iters=max_iters,
                tol=tol,
            )
        params = jnp.where(ok[:, None], res.x, jnp.nan)
        out = FitResult(
            params, jnp.where(ok, res.f * n_eff, jnp.nan),
            res.converged & ok, res.iters,
            derive_status(ok, res.converged, params),
        )
        return (out, info) if count_evals else out

    return run


def _finalize_css_fit(res, ok, n_eff):
    """Optimizer result -> FitResult (same ops as the inline program)."""
    params = jnp.where(ok[:, None], res.x, jnp.nan)
    return FitResult(
        params, jnp.where(ok, res.f * n_eff, jnp.nan),
        res.converged & ok, res.iters,
        derive_status(ok, res.converged, params),
    )


@jit_program
def _fit_stage1_program(order, include_intercept, backend, max_iters, tol,
                        has_init, align_mode="general"):
    """Stage 1 of the lazily compiled compact fit (ADVICE r5): the full
    prep + lockstep L-BFGS with the straggler early-exit, returning the
    finalized as-if-done result PLUS the compacted carry — so the stage-2
    program is only traced/compiled when ``carry.undone`` says rows
    actually remain.  Pallas backends only (the gate lives in ``fit``)."""
    def run(yb, init_params=None):
        yd, nvd, y3, zb3, init, ok, n_eff = _css_prep(
            yb, init_params, order, include_intercept, backend, align_mode,
            has_init)
        from ..ops import pallas_kernels as _pk

        interp = backend == "pallas-interpret"
        bsz, T = yd.shape
        cap = optim.compaction_cap(bsz)
        res1, carry = optim.lbfgs_batched_stage1(
            lambda P: _pk.css_neg_loglik_folded(
                P, y3, zb3, T, order, include_intercept, nvd,
                interpret=interp
            ) / n_eff,
            init, straggler_cap=cap, max_iters=max_iters, tol=tol)
        # repack the compacted objective data HERE (the same folded-COLUMN
        # gather the inline straggler_fun performs — series ride the lanes,
        # grid-aligned by the cap), so the stage-2 program is a pure
        # function of its inputs and compiles against stable shapes
        tp = y3.shape[0]
        y3s = y3.reshape(tp, -1)[:, carry.idxc].reshape(tp, cap // 128, 128)
        zb3s = zb3.reshape(1, -1)[:, carry.idxc].reshape(1, cap // 128, 128)
        aux = {"carry": carry, "res": res1, "y3s": y3s, "zb3s": zb3s,
               "nvs": nvd[carry.idxc], "nes": n_eff[carry.idxc],
               "ok": ok, "n_eff": n_eff}
        return _finalize_css_fit(res1, ok, n_eff), aux

    return run


@jit_program
def _fit_stage2_program(order, include_intercept, backend, max_iters, tol,
                        t_len):
    """Stage 2 of the lazy compact fit: finish the gathered stragglers on
    the compacted objective and scatter back — compiled only on the first
    call where stage 1 left unconverged rows (per static config)."""
    interp = backend == "pallas-interpret"

    def run(aux):
        from ..ops import pallas_kernels as _pk

        def fb_s(P):
            return _pk.css_neg_loglik_folded(
                P, aux["y3s"], aux["zb3s"], t_len, order, include_intercept,
                aux["nvs"], interpret=interp) / aux["nes"]

        res = optim.lbfgs_batched_stage2(
            fb_s, aux["res"], aux["carry"], max_iters=max_iters, tol=tol)
        return _finalize_css_fit(res, aux["ok"], aux["n_eff"])

    return run


def _fit_seasonal(
    y,
    order: Order,
    seasonal: Seasonal,
    include_intercept: bool,
    *,
    method: str,
    init_params: Optional[jax.Array],
    max_iters: int,
    tol: Optional[float],
    backend: str,
    count_evals: bool,
    align_mode: Optional[str],
) -> FitResult:
    """Seasonal branch of :func:`fit` (validated ``seasonal`` only)."""
    if method == "hannan-rissanen":
        raise ValueError(
            "seasonal orders require an optimizing CSS method "
            "(hannan-rissanen has no seasonal init stage)")
    if count_evals:
        raise ValueError(
            "count_evals instruments the fused pallas objective; seasonal "
            "fits run on the scan backend")
    if backend not in ("auto", "scan"):
        raise ValueError(
            f"seasonal orders run on the portable scan backend (the fused "
            f"kernel's folded layout has no seasonal lag structure); got "
            f"backend={backend!r}")
    p_full, q_full, d_full = seasonal_lag_span(order, seasonal)
    yb, single = ensure_batched(y)
    if yb.shape[1] - d_full < max(p_full + q_full + 2, 2):
        raise ValueError(
            f"series of length {yb.shape[1]} too short for seasonal order "
            f"{order} x {seasonal} (needs > {d_full + p_full + q_full + 2} "
            "observations)")
    if tol is None:
        tol = 1e-6 if yb.dtype == jnp.float64 else 1e-4
    align_mode = resolve_align_mode(yb, align_mode)
    run = _fit_sarima_program(order, seasonal, include_intercept, max_iters,
                              float(tol), init_params is not None, align_mode)
    if init_params is None:
        out = run(yb)
    else:
        out = run(yb, jnp.asarray(init_params))
    return debatch_fit(out, single, False)


@jit_program
def _fit_sarima_program(order, seasonal, include_intercept, max_iters, tol,
                        has_init, align_mode="general"):
    """One compiled program per (order, seasonal, ...) static config —
    align + both differencings, the non-seasonal Hannan-Rissanen warm
    start (seasonal terms start at 0: the optimizer owns them), the
    identifiability gate, and the vmapped L-BFGS on the expanded-
    polynomial CSS objective."""
    p, d, q = order
    P, D, Q, s = seasonal
    k = _n_params_seasonal(order, seasonal, include_intercept)
    p_full, q_full, d_full = seasonal_lag_span(order, seasonal)

    def run(yb, init_params=None):
        with jax.named_scope("arima.sarima_align_and_difference"):
            ya, nv0 = maybe_align(yb, align_mode)  # ragged: NaN head/tail
            yd = jax.vmap(
                lambda v: _difference_seasonal(_difference(v, d), D, s))(ya)
            nvd = nv0 - d_full  # valid length after both differencings
        with jax.named_scope("arima.sarima_init"):
            if has_init:
                init = jnp.broadcast_to(init_params, (yd.shape[0], k))
            else:
                # short-memory (p, q) warm start on the fully differenced
                # series; the P+Q seasonal terms start at 0 so the init is
                # deterministic and the gate below keeps HR's long-AR order
                # static (same nvd >= 4*(p+q+1) contract as _css_prep)
                base = hannan_rissanen_batched(
                    yd, (p, 0, q), include_intercept, nvd)
                init = jnp.concatenate(
                    [base, jnp.zeros((yd.shape[0], P + Q), yd.dtype)], axis=1)
        ok = nvd >= p_full + q_full + max(p_full + q_full + 1, 1) + k + 2
        if not has_init:
            ok = ok & (nvd >= 4 * (p + q + 1))
        # optimize the MEAN log-likelihood (same rationale as _css_prep)
        n_eff = jnp.maximum(nvd - p_full, 1).astype(yd.dtype)
        res = optim.batched_minimize(
            lambda pr, data: sarima_neg_loglik(
                pr, data[0], order, seasonal, include_intercept, data[1]
            ) / data[2],
            init,
            (yd, nvd, n_eff),
            max_iters=max_iters,
            tol=tol,
        )
        return _finalize_css_fit(res, ok, n_eff)

    return run


# ---------------------------------------------------------------------------
# Fused multi-order grid fit (ISSUE 10): K same-d orders, ONE program
# ---------------------------------------------------------------------------
#
# The auto-fit order search (models.auto) runs one chunk walk per candidate
# order, so a G-order search stages/prefetches/journals every chunk G times.
# fit_grid makes the candidate grid a BATCH dimension instead of a loop: K
# orders that share the plain differencing order d are fitted by ONE
# compiled program — every order's AR/MA lag-coefficient vectors are
# expanded (_expand_seasonal_poly) and zero-padded to the grid's max
# (p+P*s, q+Q*s), the CSS objective runs as a [K]-leading-axis vmap of the
# one _css_errors_poly scan (conditioning depth stays per-order via
# condition_lags), and one lockstep batched L-BFGS optimizes the flattened
# [K*B] problem.  Orders whose FULL differencing signature (d, D, s)
# matches share one differenced panel through a per-trace cache (the
# shared-prep half of the tentpole); variants are embedded right-aligned
# into the group's common length so every order sees one static shape.
#
# The K per-order results are PACKED into the params matrix — per row,
# per order: [params(k_max), nll, converged, iters, status] — so a fused
# chunk rides the journal/commit/resume machinery of fit_chunked
# unchanged (one npz shard per chunk carries the whole fusion group) and
# models.auto demuxes per-order results after the walk.  Scan backend
# only: the fused Pallas kernel's folded layout is per-(p, q) static.

GRID_PACK_COLS = 5  # nll, eligible, converged, iters, status per order


def _grid_spec_info(order: Order, seasonal: Optional[Seasonal],
                    include_intercept: bool) -> dict:
    p, d, q = order
    seasonal = _validate_seasonal(seasonal)
    if seasonal is None:
        k = _n_params(order, include_intercept)
        P = D = Q = 0
        s = 0
    else:
        P, D, Q, s = seasonal
        k = _n_params_seasonal(order, seasonal, include_intercept)
    p_full, q_full, d_full = seasonal_lag_span(order, seasonal)
    return dict(order=(p, d, q), seasonal=seasonal, k=k, P=P, D=D, Q=Q, s=s,
                p_full=p_full, q_full=q_full, d_full=d_full)


def grid_pack_width(specs, include_intercept: bool = True) -> int:
    """Packed-row width of a :func:`fit_grid` result for ``specs``."""
    infos = [_grid_spec_info(tuple(o), sea, include_intercept)
             for o, sea in specs]
    k_max = max(i["k"] for i in infos)
    return len(infos) * (k_max + GRID_PACK_COLS)


def grid_diff_cache_keys(specs) -> int:
    """Distinct differencing signatures ``(d, D, s)`` a fused group of
    ``specs`` needs — the group differences the panel once per key, so
    ``len(specs) - grid_diff_cache_keys(specs)`` orders hit the shared-prep
    cache instead of re-differencing."""
    keys = set()
    for order, seasonal in specs:
        seasonal = _validate_seasonal(seasonal)
        d = int(order[1])
        if seasonal is None or seasonal[1] == 0:
            keys.add((d, 0, 0))
        else:
            keys.add((d, int(seasonal[1]), int(seasonal[3])))
    return len(keys)


def _grid_coef_maps(infos, include_intercept: bool, k_max: int, p_max: int,
                    q_max: int):
    """Per-order packed-params -> expanded-lag-coefficient maps, as
    CONSTANTS: ``phi_full = lin_phi[g] @ P + P^T quad_phi[g] P`` (and the
    theta analog, cross ``+1``), ``c = lin_c[g] @ P``.

    The multiplicative seasonal expansion (:func:`_expand_seasonal_poly`)
    is linear in the own-lag and seasonal coefficients plus BILINEAR
    cross terms — so per order it is exactly a (linear, quadratic-form)
    pair of 0/±1 constant tensors.  That makes the fused grid objective a
    uniform per-cell computation gatherable by CELL index, which is what
    lets straggler compaction run on the flattened ``[K*B]`` problem
    (the static-unrolled main objective cannot be gathered across mixed
    orders)."""
    K = len(infos)
    lin_c = np.zeros((K, k_max), np.float32)
    lin_phi = np.zeros((K, max(p_max, 1), k_max), np.float32)
    quad_phi = np.zeros((K, max(p_max, 1), k_max, k_max), np.float32)
    lin_th = np.zeros((K, max(q_max, 1), k_max), np.float32)
    quad_th = np.zeros((K, max(q_max, 1), k_max, k_max), np.float32)
    i0 = int(include_intercept)
    for g, info in enumerate(infos):
        p, _, q = info["order"]
        P, Q, s = info["P"], info["Q"], info["s"]
        if include_intercept:
            lin_c[g, 0] = 1.0
        for i in range(p):
            lin_phi[g, i, i0 + i] = 1.0
        for j in range(q):
            lin_th[g, j, i0 + p + j] = 1.0
        for j in range(P):  # seasonal AR: lag (j+1)s - 1, cross = -1
            lag = (j + 1) * s
            lin_phi[g, lag - 1, i0 + p + q + j] += 1.0
            for i in range(p):
                quad_phi[g, lag + i, i0 + p + q + j, i0 + i] += -1.0
        for j in range(Q):  # seasonal MA: cross = +1
            lag = (j + 1) * s
            lin_th[g, lag - 1, i0 + p + q + P + j] += 1.0
            for i in range(q):
                quad_th[g, lag + i, i0 + p + q + P + j,
                        i0 + p + i] += 1.0
    return lin_c, lin_phi, quad_phi, lin_th, quad_th


def fit_grid(
    y,
    specs,
    include_intercept: bool = True,
    *,
    method: str = "css-lbfgs",
    max_iters: int = 60,
    tol: Optional[float] = None,
    backend: str = "auto",
    align_mode: Optional[str] = None,
) -> FitResult:
    """Fit a fused grid of K same-``d`` (S)ARIMA candidates in ONE program.

    ``specs`` is a sequence of ``(order, seasonal_or_None)`` pairs that all
    share the plain differencing order ``d`` (seasonal ``(D, s)`` may vary
    — each distinct ``(d, D, s)`` signature differences the panel once
    through the shared-prep cache).  Returns a :class:`FitResult` whose
    ``params`` matrix packs the K per-order results per row — ALL-FINITE
    by construction, with per-order eligibility as its own column
    (layout: :data:`GRID_PACK_COLS`; width :func:`grid_pack_width`) —
    and whose row-level nll/converged/iters/status summarize the row's
    BEST outcome across the grid (min nll / any-converged / max iters /
    min-severity status): a resilient caller therefore retries only rows
    with NO usable candidate, and an all-excluded row keeps the
    retry-cannot-help shield.  ``models.auto`` demuxes the pack into
    per-order results.

    Scan backend only (``backend`` must resolve away from pallas); the
    optimizing CSS methods only.  Numerics match the per-order scan fits
    up to f32 fusion differences (zero-padded coefficient slots and the
    shared lockstep loop) — selection built on top is tested to agree
    with the per-order search; ``fuse=1`` in ``auto_fit`` remains the
    bitwise per-order path.
    """
    if method not in ("css-lbfgs", "css-cgd", "css-bobyqa"):
        raise ValueError(
            f"fit_grid requires an optimizing CSS method, got {method!r}")
    if backend not in ("auto", "scan"):
        raise ValueError(
            f"fit_grid runs on the portable scan backend (the fused pallas "
            f"kernel's folded layout is per-order static); got "
            f"backend={backend!r}")
    specs = tuple((tuple(int(v) for v in o),
                   _validate_seasonal(sea)) for o, sea in specs)
    if not specs:
        raise ValueError("fit_grid needs at least one order spec")
    d0 = specs[0][0][1]
    if any(o[1] != d0 for o, _ in specs):
        raise ValueError(
            f"fit_grid fuses same-d orders only (shared differencing); got "
            f"d values {sorted({o[1] for o, _ in specs})}")
    yb, single = ensure_batched(y)
    if tol is None:
        tol = 1e-6 if yb.dtype == jnp.float64 else 1e-4
    align_mode = resolve_align_mode(yb, align_mode)
    run = _grid_fit_program(specs, include_intercept, max_iters, float(tol),
                            align_mode)
    return debatch_fit(run(yb), single, False)


@jit_program
def _grid_fit_program(specs, include_intercept, max_iters, tol,
                      align_mode="general"):
    """One compiled program per fused grid: shared align + per-(d, D, s)
    differencing, per-order Hannan-Rissanen warm starts, the [K]-axis
    vmapped padded-polynomial CSS objective, and one lockstep batched
    L-BFGS over the flattened ``[K*B]`` problem."""
    from .. import obs as _obs

    infos = [_grid_spec_info(o, sea, include_intercept) for o, sea in specs]
    K = len(infos)
    d = infos[0]["order"][1]
    k_max = max(i["k"] for i in infos)
    p_max = max(i["p_full"] for i in infos)
    q_max = max(i["q_full"] for i in infos)
    i0 = int(include_intercept)
    lin_c, lin_phi, quad_phi, lin_th, quad_th = _grid_coef_maps(
        infos, include_intercept, k_max, p_max, q_max)
    any_seasonal = any(i["seasonal"] is not None for i in infos)
    # distinct differencing signatures -> trace-time shared-prep accounting
    # (mirrors grid_diff_cache_keys; the obs counter records the saved
    # differencings once per compile, like optim.stage2_compact_traces)
    n_keys = grid_diff_cache_keys(tuple((i["order"], i["seasonal"])
                                        for i in infos))
    if K > n_keys:
        _obs.counter("auto_fit.diff_cache_hits").add(K - n_keys)

    def run(yb):
        bsz, t_len = yb.shape
        with jax.named_scope("arima.grid_align"):
            ya, nv0 = maybe_align(yb, align_mode)  # ragged: NaN head/tail
        n = t_len - d
        # shared-prep cache (the tentpole's second half): ONE differencing
        # per (d, D, s) signature across the fusion group; seasonal
        # variants embed right-aligned into the group's common length n
        # (the scan's n_valid masking zeroes the pad, so the embedded
        # recursion sees the bytes a per-order fit of length n - D*s would)
        cache = {}

        def differenced(D, s):
            key = (D, s) if D else (0, 0)
            if key in cache:
                return cache[key]
            with jax.named_scope("arima.grid_difference"):
                yd = jax.vmap(lambda v: _difference(v, d))(ya)
                if D:
                    yd = jax.vmap(
                        lambda v: _difference_seasonal(v, D, s))(yd)
                    yd = jnp.pad(yd, ((0, 0), (n - yd.shape[1], 0)))
            cache[key] = yd
            return yd

        inits, oks, n_effs, nvds, yds = [], [], [], [], []
        for info in infos:
            p, _, q = info["order"]
            yd = differenced(info["D"], info["s"])
            nvd = nv0 - info["d_full"]
            with jax.named_scope("arima.grid_init"):
                # non-seasonal HR warm start on the (fully) differenced
                # panel; seasonal terms start at 0 (same contract as
                # _fit_sarima_program).  Inside the ok region the
                # embedding cannot change HR's static long-AR order m
                # (the nvd >= 4*(p+q+1) gate pins m = p+q+1 either way).
                base = hannan_rissanen_batched(
                    yd, (p, 0, q), include_intercept, nvd)
                if info["P"] + info["Q"]:
                    base = jnp.concatenate(
                        [base, jnp.zeros((bsz, info["P"] + info["Q"]),
                                         yd.dtype)], axis=1)
            # zero-pad to k_max: the objective never reads the pad, so its
            # gradient (and therefore its trajectory) stays exactly 0
            init = jnp.pad(base, ((0, 0), (0, k_max - info["k"])))
            pf, qf, k = info["p_full"], info["q_full"], info["k"]
            ok = nvd >= pf + qf + max(pf + qf + 1, 1) + k + 2
            ok = ok & (nvd >= 4 * (p + q + 1))
            # optimize the MEAN log-likelihood (same rationale as _css_prep)
            n_eff = jnp.maximum(nvd - pf, 1).astype(yd.dtype)
            inits.append(init)
            oks.append(ok)
            n_effs.append(n_eff)
            nvds.append(nvd)
            yds.append(yd)

        def row_nll(c, phi_f, theta_f, ydr, nvr, cond_p, ner):
            e = _css_errors_poly(c, phi_f, theta_f, ydr, n_valid=nvr,
                                 condition_lags=cond_p)
            css = jnp.sum(e * e)
            sigma2 = css / ner
            return 0.5 * ner * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)

        # over rows; the panel (ydr) is per row, the conditioning depth is
        # shared by the order
        nll_rows = jax.vmap(row_nll, in_axes=(0, 0, 0, 0, 0, None, 0))
        # over the leading [K] order axis of one diff-signature's stack;
        # the shared differenced panel broadcasts instead of tiling K x B
        nll_grid = jax.vmap(nll_rows, in_axes=(0, 0, 0, None, 0, 0, 0))

        def fb(p_flat):
            pk = p_flat.reshape(K, bsz, k_max)
            cs, phis, thetas = [], [], []
            for g, info in enumerate(infos):
                p, _, q = info["order"]
                pg = pk[g]
                c = (pg[:, 0] if include_intercept
                     else jnp.zeros((bsz,), pg.dtype))
                phi = pg[:, i0: i0 + p]
                theta = pg[:, i0 + p: i0 + p + q]
                if info["seasonal"] is not None:
                    P, Q, s = info["P"], info["Q"], info["s"]
                    sphi = pg[:, i0 + p + q: i0 + p + q + P]
                    stheta = pg[:, i0 + p + q + P: i0 + p + q + P + Q]
                    phi = jax.vmap(
                        lambda a, b: _expand_seasonal_poly(a, b, s, -1.0)
                    )(phi, sphi)
                    theta = jax.vmap(
                        lambda a, b: _expand_seasonal_poly(a, b, s, 1.0)
                    )(theta, stheta)
                phi = jnp.pad(phi, ((0, 0), (0, p_max - phi.shape[1])))
                theta = jnp.pad(theta, ((0, 0), (0, q_max - theta.shape[1])))
                cs.append(c)
                phis.append(phi)
                thetas.append(theta)
            # one vmapped objective per diff signature: the [K_sig] stack
            # shares its differenced panel via broadcast (in_axes=None)
            out = [None] * K
            by_sig: dict = {}
            for g, info in enumerate(infos):
                sig = ((info["D"], info["s"]) if info["D"] else (0, 0))
                by_sig.setdefault(sig, []).append(g)
            for sig, gs in by_sig.items():
                nll_sig = nll_grid(
                    jnp.stack([cs[g] for g in gs]),
                    jnp.stack([phis[g] for g in gs]),
                    jnp.stack([thetas[g] for g in gs]),
                    yds[gs[0]],
                    jnp.stack([nvds[g] for g in gs]),
                    jnp.asarray([infos[g]["p_full"] for g in gs]),
                    jnp.stack([n_effs[g] for g in gs]),
                )  # [K_sig, B]
                for j, g in enumerate(gs):
                    out[g] = nll_sig[j] / n_effs[g]
            return jnp.concatenate(out)  # [K*B]

        # straggler compaction over the flattened [K*B] CELL grid: the
        # lockstep loop runs to the slowest (order, row) cell while every
        # pass evaluates all K*B cells — with per-order convergence rates
        # this skewed (an HR-init order can converge in 0 iterations while
        # a neighbor runs 16), the tail would cost more than the fusion
        # saves.  Once at most `cap` cells remain, they are gathered into
        # one small uniform problem whose objective reconstructs each
        # cell's expanded coefficients from the per-order (linear,
        # quadratic) constant maps (_grid_coef_maps) — gatherable by cell
        # index, which the static-unrolled main objective is not.
        # Single-signature groups only: a mixed-signature gather would
        # need per-cell panel selection; those groups stay lockstep.
        cells = K * bsz
        straggler_fun = None
        cap = None
        if n_keys == 1 and cells >= 512:
            # cap at cells/4 (128-aligned): the cross-ORDER skew makes the
            # tail fat (a whole order can sit converged while another
            # runs), so exiting the full-width lockstep earlier buys more
            # than the compacted problem's extra quarter-width costs
            cap = -(-max(128, cells // 4) // 128) * 128
            if cap >= cells:
                cap = None
        if cap is not None:
            lc_a = jnp.asarray(lin_c)
            lphi_a = jnp.asarray(lin_phi)
            lth_a = jnp.asarray(lin_th)
            qphi_a = jnp.asarray(quad_phi) if any_seasonal else None
            qth_a = jnp.asarray(quad_th) if any_seasonal else None
            yd0 = yds[0]
            nvd_all = jnp.concatenate(nvds)
            ne_all = jnp.concatenate(n_effs)
            cp_all = jnp.concatenate([
                jnp.full((bsz,), info["p_full"], jnp.int32)
                for info in infos])

            def straggler_fun(idxc):
                gcell = idxc // bsz
                rcell = idxc % bsz
                lc_s = lc_a[gcell]
                lphi_s = lphi_a[gcell]
                lth_s = lth_a[gcell]
                qphi_s = qphi_a[gcell] if any_seasonal else None
                qth_s = qth_a[gcell] if any_seasonal else None
                yd_s = yd0[rcell]
                nvd_s = nvd_all[idxc]
                ne_s = ne_all[idxc]
                cp_s = cp_all[idxc]
                cell_nll = jax.vmap(row_nll)

                def fb_s(p_sub):
                    c = jnp.einsum("ck,ck->c", lc_s, p_sub)
                    phi = jnp.einsum("cpk,ck->cp", lphi_s, p_sub)
                    th = jnp.einsum("cqk,ck->cq", lth_s, p_sub)
                    if any_seasonal:
                        phi = phi + jnp.einsum("cpkl,ck,cl->cp", qphi_s,
                                               p_sub, p_sub)
                        th = th + jnp.einsum("cqkl,ck,cl->cq", qth_s,
                                             p_sub, p_sub)
                    return cell_nll(c, phi, th, yd_s, nvd_s, cp_s,
                                    ne_s) / ne_s

                return fb_s

        with jax.named_scope("arima.grid_lbfgs"):
            res = optim.minimize_lbfgs_batched(
                fb, jnp.concatenate(inits), max_iters=max_iters, tol=tol,
                straggler_fun=straggler_fun, straggler_cap=cap)

        xk = res.x.reshape(K, bsz, k_max)
        fk = res.f.reshape(K, bsz)
        convk = res.converged.reshape(K, bsz)
        itk = res.iters.reshape(K, bsz)
        blocks, nlls, convs, statuses = [], [], [], []
        for g, info in enumerate(infos):
            ok = oks[g]
            colmask = jnp.arange(k_max) < info["k"]
            params_g = jnp.where(ok[:, None] & colmask[None, :], xk[g],
                                 jnp.nan)
            nll_g = jnp.where(ok, fk[g] * n_effs[g], jnp.nan)
            conv_g = convk[g] & ok
            # status judges THIS order's own parameter columns: the
            # k_max padding is NaN by the pack convention, and letting
            # derive_status's finiteness check read it would flag every
            # narrower order on the grid DIVERGED
            status_g = derive_status(
                ok, convk[g], jnp.where(colmask[None, :], params_g, 0.0))
            # the PACK must be all-finite: the resilient runner's
            # failed-row mask requires finite(params).all(axis=-1) per
            # ROW, and the pack IS the row — NaN slots (excluded orders,
            # k_g padding) would mark every row failed and feed the
            # whole panel through the retry ladder.  Eligibility rides
            # as its own column; _demux_fused restores the per-order NaN
            # conventions from it and the status column.
            elig_g = ok & jnp.isfinite(nll_g)
            dt = params_g.dtype
            blocks += [jnp.where(jnp.isfinite(params_g), params_g, 0.0),
                       jnp.where(elig_g, nll_g, 0.0)[:, None],
                       elig_g.astype(dt)[:, None],
                       conv_g.astype(dt)[:, None],
                       itk[g].astype(dt)[:, None],
                       status_g.astype(dt)[:, None]]
            nlls.append(jnp.where(elig_g, nll_g, jnp.nan))
            convs.append(conv_g)
            statuses.append(status_g)
        wide = jnp.concatenate(blocks, axis=1)  # [B, K*(k_max+5)]
        nll_all = jnp.stack(nlls)
        best = jnp.min(jnp.where(jnp.isnan(nll_all), jnp.inf, nll_all),
                       axis=0)
        row_nll_out = jnp.where(jnp.isfinite(best), best, jnp.nan)
        # row-level summaries feed the DRIVER's accounting and the
        # resilient runner's per-ROW decisions — the per-order truth
        # lives in the pack.  A row's summary is its BEST outcome across
        # the grid: converged = ANY order usable (the ladder retries
        # rows with NO usable candidate — a single stubborn order must
        # not send the row through the ladder, and an exhausted ladder
        # must not wipe the orders that DID fit; per-candidate rescue is
        # fuse=1's contract), status = min severity (EXCLUDED only when
        # EVERY order structurally refused the row, which is when the
        # runner's retry-cannot-help shield is actually true).
        return FitResult(
            wide, row_nll_out,
            jnp.any(jnp.stack(convs), axis=0),
            jnp.max(itk, axis=0),
            jnp.min(jnp.stack(statuses), axis=0),
        )

    return run


# ---------------------------------------------------------------------------
# Forecasting / sampling / effects
# ---------------------------------------------------------------------------


def forecast(params, y, order: Order, n_future: int, include_intercept: bool = True,
             *, backend: str = "auto"):
    """Forecast ``n_future`` steps ahead -> ``[batch?, n_future]``.

    In-sample errors are rebuilt with the CSS recursion, then the ARMA
    recursion runs forward with future innovations set to zero and the
    order-d differencing is inverted step by step (reference
    ``ARIMAModel.forecast`` semantics).

    ``backend`` mirrors :func:`fit`: the in-sample error rebuild — the whole
    panel-scale cost of a forecast — runs on the fused Pallas ``css_errors``
    kernel when available (``"auto"``/``"pallas"``), so fit + forecast share
    one kernel family; the forward extension and inverse differencing are
    O(batch * n_future) jnp either way.
    """
    yb, single = ensure_batched(y)
    params_b = jnp.atleast_2d(params)
    p, d, q = order
    from ..ops import pallas_kernels as pk

    backend = resolve_backend(backend, yb.dtype, yb.shape[1] - d,
                              structural_ok=pk.css_structural_ok(p, q))
    out = _forecast_program(order, n_future, include_intercept, backend,
                            align_mode_on_host(yb))(params_b, yb)
    return out[0] if single else out


@jit_program
def _forecast_program(order, n_future, include_intercept, backend="scan",
                      align_mode="general"):
    p, d, q = order

    def run(params_b, yb):
        b = yb.shape[0]
        with jax.named_scope("arima.forecast_errors"):
            ya, nv0 = maybe_align(yb, align_mode)  # ragged: NaN head/tail
            yd = ya
            for _ in range(d):
                yd = yd[:, 1:] - yd[:, :-1]
            nvd = nv0 - d
            n = yd.shape[1]
            start = (n - nvd).astype(yd.dtype)  # [B]
            # differencing across the padding boundary leaves garbage at
            # yd[start-1]; zero the prefix (same contract as the fit path)
            t_idx = jnp.arange(n, dtype=yd.dtype)
            ydz = jnp.where(t_idx[None, :] >= start[:, None], yd, 0.0)
            if q == 0:
                # pure-AR forecasts never read past errors: skip the rebuild
                elast = jnp.zeros((b, 1), yd.dtype)
            elif backend in ("pallas", "pallas-interpret"):
                from ..ops import pallas_kernels as _pk

                if include_intercept:
                    params_k = params_b
                else:  # kernel layout always carries an intercept slot
                    params_k = jnp.concatenate(
                        [jnp.zeros((b, 1), params_b.dtype), params_b], axis=1
                    )
                # zb = start (not start + p) is exactly condition=False;
                # only the last q errors leave the kernel (read-only pass)
                tail = _pk.css_last_errors(p, q, backend == "pallas-interpret",
                                           params_k, ydz, start)
                elast = tail[:, ::-1]  # newest first
            else:
                e = jax.vmap(
                    lambda pr, v, nv: _css_errors(
                        pr, v, order, include_intercept, condition=False,
                        n_valid=nv)
                )(params_b, ydz, nvd)
                elast = e[:, ::-1][:, :q]
        with jax.named_scope("arima.forecast_extend"):
            i0 = int(include_intercept)
            c = params_b[:, 0] if include_intercept else jnp.zeros((b,), yd.dtype)
            phi = params_b[:, i0 : i0 + p]
            theta = params_b[:, i0 + p : i0 + p + q]
            # carries: last p differenced values (newest first); elast (the
            # last q errors, newest first) was built above
            ydlast = ydz[:, ::-1][:, :p] if p else jnp.zeros((b, 0), yd.dtype)
            # last value of each difference level 0..d-1 for integration
            levels = []
            lv = ya
            for _ in range(d):
                levels.append(lv[:, -1])
                lv = lv[:, 1:] - lv[:, :-1]
            levels = (jnp.stack(levels, axis=1) if d
                      else jnp.zeros((b, 0), yd.dtype))

            def step(carry, _):
                ydl, el, lvl = carry
                pred = c
                if p:
                    pred = pred + jnp.einsum("bi,bi->b", phi, ydl)
                if q:
                    pred = pred + jnp.einsum("bj,bj->b", theta, el)
                new_ydl = (jnp.concatenate([pred[:, None], ydl[:, :-1]], axis=1)
                           if p else ydl)
                new_el = (jnp.concatenate(
                    [jnp.zeros((b, 1), el.dtype), el[:, :-1]], axis=1)
                    if q else el)
                # integrate: v_d = pred; v_i = lvl[i] + v_{i+1}
                acc = pred
                new_lvl = lvl
                for i in reversed(range(d)):
                    acc = lvl[:, i] + acc
                    new_lvl = new_lvl.at[:, i].set(acc)
                out = acc if d else pred
                return (new_ydl, new_el, new_lvl), out

            _, future = lax.scan(step, (ydlast, elast, levels), None,
                                 length=n_future)
            return future.T  # [n_future, B] -> [B, n_future]

    return run


def sample(params, key, n: int, order: Order, include_intercept: bool = True, sigma: float = 1.0):
    """Generate a series of length ``n`` from the model with N(0, sigma^2)
    innovations (reference ``ARIMAModel.sample``)."""
    return _sample_program(order, n, include_intercept, float(sigma))(params, key)


@jit_program
def _sample_program(order, n, include_intercept, sigma):
    p, d, q = order

    def run(params, key):
        params = jnp.asarray(params, jnp.result_type(float))
        c, phi, theta = _split_params(params, order, include_intercept)
        e = sigma * jax.random.normal(key, (n + d,), params.dtype)

        def step(carry, et):
            ydl, el = carry
            yt = c + (jnp.dot(phi, ydl) if p else 0.0) + (jnp.dot(theta, el) if q else 0.0) + et
            new_ydl = jnp.concatenate([yt[None], ydl[:-1]]) if p else ydl
            new_el = jnp.concatenate([et[None], el[:-1]]) if q else el
            return (new_ydl, new_el), yt

        init = (jnp.zeros((max(p, 1),), e.dtype), jnp.zeros((max(q, 1),), e.dtype))
        _, yd = lax.scan(step, init, e)
        y = yd
        for _ in range(d):
            y = jnp.cumsum(y)
        return y[d:] if d else y

    return run


def remove_time_dependent_effects(params, y, order: Order, include_intercept: bool = True):
    """Destructure a series into its innovations (zero-padded-lag recursion;
    exactly inverted by :func:`add_time_dependent_effects`).  The first ``d``
    output entries carry the integration constants."""
    yb, single = ensure_batched(y)
    params_b = jnp.atleast_2d(params)
    out = _remove_effects_program(order, include_intercept)(params_b, yb)
    return out[0] if single else out


@jit_program
def _remove_effects_program(order, include_intercept):
    _, d, _ = order

    def run(params_b, yb):
        def one(pr, yv):
            # integration constants: the FIRST value of each difference level
            lv = yv
            inits = []
            for _ in range(d):
                inits.append(lv[0])
                lv = lv[1:] - lv[:-1]
            yd = lv
            e = _css_errors(pr, yd, order, include_intercept, condition=False)
            inits_arr = (
                jnp.stack(inits) if d else jnp.zeros((0,), yv.dtype)
            )
            return jnp.concatenate([inits_arr, e])

        return jax.vmap(one)(params_b, yb)

    return run


def add_time_dependent_effects(params, x, order: Order, include_intercept: bool = True):
    """Inverse of :func:`remove_time_dependent_effects`: innovations (with
    integration constants in the first ``d`` slots) -> the observed series."""
    xb, single = ensure_batched(x)
    params_b = jnp.atleast_2d(params)
    out = _add_effects_program(order, include_intercept)(params_b, xb)
    return out[0] if single else out


@jit_program
def _add_effects_program(order, include_intercept):
    p, d, q = order

    def run(params_b, xb):
        def one(pr, xv):
            c, phi, theta = _split_params(pr, order, include_intercept)
            init_vals, e = xv[:d], xv[d:]

            def step(carry, et):
                ydl, el = carry
                yt = (
                    c
                    + (jnp.dot(phi, ydl) if p else 0.0)
                    + (jnp.dot(theta, el) if q else 0.0)
                    + et
                )
                new_ydl = jnp.concatenate([yt[None], ydl[:-1]]) if p else ydl
                new_el = jnp.concatenate([et[None], el[:-1]]) if q else el
                return (new_ydl, new_el), yt

            init = (jnp.zeros((max(p, 1),), xv.dtype), jnp.zeros((max(q, 1),), xv.dtype))
            _, yd = lax.scan(step, init, e)
            # integrate d times using the stored initial values
            y = yd
            for i in reversed(range(d)):
                y = init_vals[i] + jnp.cumsum(y)
                y = jnp.concatenate([init_vals[i][None], y])
            return y

        return jax.vmap(one)(params_b, xb)

    return run


def is_stationary(params, order: Order, include_intercept: bool = True) -> np.ndarray:
    """AR-polynomial roots outside the unit circle (host-side diagnostic)."""
    p, _, _ = order
    if p == 0:
        return np.asarray(True)
    c, phi, _ = _split_params(np.asarray(params), order, include_intercept)
    if not np.all(np.isfinite(phi)):  # failed fit (e.g. all-NaN series)
        return np.asarray(False)
    roots = np.roots(np.concatenate([[1.0], -np.asarray(phi)])[::-1])
    return np.asarray(np.all(np.abs(roots) > 1.0 + 1e-9))


def is_invertible(params, order: Order, include_intercept: bool = True) -> np.ndarray:
    """MA-polynomial roots outside the unit circle (host-side diagnostic)."""
    _, _, q = order
    if q == 0:
        return np.asarray(True)
    _, _, theta = _split_params(np.asarray(params), order, include_intercept)
    if not np.all(np.isfinite(theta)):  # failed fit (e.g. all-NaN series)
        return np.asarray(False)
    roots = np.roots(np.concatenate([[1.0], np.asarray(theta)])[::-1])
    return np.asarray(np.all(np.abs(roots) > 1.0 + 1e-9))
