"""ARIMA(p, d, q) — the flagship model family (L4).

TPU-native rebuild of the reference's ``sparkts/models/ARIMA.scala``
(SURVEY.md Sections 2.2 and 3.3, upstream path unverified).  Same algorithm
family, redesigned for batch execution:

===============================  ==========================================
reference (per series, JVM)      here (whole panel, one XLA computation)
===============================  ==========================================
order-d differencing             static slicing (``ops.univariate``)
Hannan-Rissanen init             batched OLS via ``jnp.linalg.lstsq`` on
                                 stacked lag matrices (MXU matmuls)
conditional-sum-of-squares       ``lax.scan`` over time computing one-step
likelihood (hand-coded loop)     prediction errors; vmapped over series
hand-derived CSS gradient        ``jax.grad`` through the scan
Commons-Math CG / BOBYQA         fixed-budget vmapped L-BFGS
                                 (``utils.optim``) with per-series
                                 convergence masks
===============================  ==========================================

Parameter vector layout (matching the reference's ``coefficients``):
``[c (if intercept), phi_1..phi_p, theta_1..theta_q]``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import univariate as uv
from ..utils import optim
from ..utils.linalg import ols as _ols
from .base import FitResult, debatch, ensure_batched

Order = Tuple[int, int, int]


def _n_params(order: Order, include_intercept: bool) -> int:
    p, _, q = order
    return int(include_intercept) + p + q


def _split_params(params, order: Order, include_intercept: bool):
    p, _, q = order
    i = int(include_intercept)
    c = params[0] if include_intercept else jnp.zeros((), params.dtype)
    phi = params[i : i + p]
    theta = params[i + p : i + p + q]
    return c, phi, theta


def _difference(y, d: int):
    """Order-d differencing with the first d entries dropped (static shape)."""
    for _ in range(d):
        y = y[1:] - y[:-1]
    return y


def _lagged(yd, p: int):
    """``[n, p]`` matrix of lags 1..p, zero-padded before the start."""
    n = yd.shape[0]
    cols = []
    for k in range(1, p + 1):
        cols.append(jnp.concatenate([jnp.zeros((k,), yd.dtype), yd[: n - k]]))
    if not cols:
        return jnp.zeros((n, 0), yd.dtype)
    return jnp.stack(cols, axis=1)


# ---------------------------------------------------------------------------
# CSS likelihood
# ---------------------------------------------------------------------------


def _css_errors(params, yd, order: Order, include_intercept: bool, condition: bool = True):
    """One-step-ahead prediction errors of the ARMA(p,q) recursion.

    ``condition=True`` zeroes errors for t < p (conditional likelihood —
    the reference's CSS).  ``condition=False`` keeps zero-padded-lag errors
    for every t, which makes the transform exactly invertible
    (remove/add_time_dependent_effects).
    """
    p, _, q = order
    c, phi, theta = _split_params(params, order, include_intercept)
    ylags = _lagged(yd, p)  # [n, p]
    t_idx = jnp.arange(yd.shape[0])

    def step(errs, inp):
        yt, yl, t = inp
        pred = c + jnp.dot(phi, yl) + (jnp.dot(theta, errs) if q else 0.0)
        e = yt - pred
        if condition:
            e = jnp.where(t >= p, e, 0.0)
        new_errs = jnp.concatenate([e[None], errs[:-1]]) if q else errs
        return new_errs, e

    errs0 = jnp.zeros((max(q, 1),), yd.dtype)
    _, e = lax.scan(step, errs0, (yd, ylags, t_idx))
    return e


def css_neg_loglik(params, yd, order: Order, include_intercept: bool):
    """Negative conditional-sum-of-squares Gaussian log-likelihood with the
    innovation variance concentrated out (sigma^2 = CSS / n_eff)."""
    p = order[0]
    e = _css_errors(params, yd, order, include_intercept)
    n_eff = yd.shape[0] - p
    css = jnp.sum(e * e)
    sigma2 = css / n_eff
    return 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)


def approx_aic(params, yd, order: Order, include_intercept: bool):
    k = _n_params(order, include_intercept)
    return 2.0 * css_neg_loglik(params, yd, order, include_intercept) + 2.0 * k


# ---------------------------------------------------------------------------
# Hannan-Rissanen initialization
# ---------------------------------------------------------------------------


def hannan_rissanen(yd, order: Order, include_intercept: bool):
    """Two-stage startup values: long-AR residuals stand in for the
    unobserved MA innovations, then one OLS of y on [1, y-lags, e-lags]."""
    p, _, q = order
    n = yd.shape[0]
    m = min(p + q + 1, max(n // 4, 1))  # long-AR order, static

    # stage 1: AR(m) by OLS -> residual estimates of the innovations
    ylags_m = _lagged(yd, m)
    ones = jnp.ones((n, 1), yd.dtype)
    Xar = jnp.concatenate([ones, ylags_m], axis=1)
    # rows t < m have zero-padded lags; drop them from the fit (static slice)
    beta_ar = _ols(Xar[m:], yd[m:])
    ehat = yd - Xar @ beta_ar
    ehat = jnp.concatenate([jnp.zeros((m,), yd.dtype), ehat[m:]])

    # stage 2: OLS of y on [1?, y-lags 1..p, e-lags 1..q]
    cols = []
    if include_intercept:
        cols.append(ones)
    if p:
        cols.append(_lagged(yd, p))
    if q:
        cols.append(_lagged(ehat, q))
    if not cols:
        return jnp.zeros((0,), yd.dtype)
    X = jnp.concatenate(cols, axis=1)
    start = m + q  # rows where every regressor is real
    return _ols(X[start:], yd[start:])


# ---------------------------------------------------------------------------
# Fitting
# ---------------------------------------------------------------------------


def fit(
    y,
    order: Order,
    include_intercept: bool = True,
    *,
    method: str = "css-lbfgs",
    init_params: Optional[jax.Array] = None,
    max_iters: int = 60,
    tol: Optional[float] = None,
) -> FitResult:
    """Fit ARIMA(p,d,q) to one series ``[time]`` or a batch ``[batch, time]``.

    The entire batch is one jitted computation: differencing -> vmapped
    Hannan-Rissanen -> vmapped L-BFGS on the CSS objective.  ``method``
    accepts ``"css-lbfgs"`` (also aliased from the reference's ``"css-cgd"``
    and ``"css-bobyqa"``) and ``"hannan-rissanen"`` (init only, no MLE).
    """
    if method not in ("css-lbfgs", "css-cgd", "css-bobyqa", "hannan-rissanen"):
        raise ValueError(f"unknown method {method!r}")
    p, d, q = order
    yb, single = ensure_batched(y)
    k = _n_params(order, include_intercept)
    if tol is None:
        # f32 gradients of a ~1k-term CSS bottom out near 1e-4 relative noise
        tol = 1e-6 if yb.dtype == jnp.float64 else 1e-4

    @jax.jit
    def run(yb):
        yd = jax.vmap(lambda v: _difference(v, d))(yb)
        init = (
            jnp.broadcast_to(init_params, (yd.shape[0], k))
            if init_params is not None
            else jax.vmap(lambda v: hannan_rissanen(v, order, include_intercept))(yd)
        )
        if method == "hannan-rissanen":
            nll = jax.vmap(lambda pr, v: css_neg_loglik(pr, v, order, include_intercept))(
                init, yd
            )
            z = jnp.zeros((yd.shape[0],), jnp.int32)
            return FitResult(init, nll, jnp.ones((yd.shape[0],), bool), z)
        res = optim.batched_minimize(
            lambda pr, v: css_neg_loglik(pr, v, order, include_intercept),
            init,
            yd,
            max_iters=max_iters,
            tol=tol,
        )
        return FitResult(res.x, res.f, res.converged, res.iters)

    return debatch(run(yb), single)


# ---------------------------------------------------------------------------
# Forecasting / sampling / effects
# ---------------------------------------------------------------------------


def forecast(params, y, order: Order, n_future: int, include_intercept: bool = True):
    """Forecast ``n_future`` steps ahead -> ``[batch?, n_future]``.

    In-sample errors are rebuilt with the CSS recursion, then the ARMA
    recursion runs forward with future innovations set to zero and the
    order-d differencing is inverted step by step (reference
    ``ARIMAModel.forecast`` semantics).
    """
    p, d, q = order
    yb, single = ensure_batched(y)
    params_b = jnp.atleast_2d(params)

    @jax.jit
    def run(params_b, yb):
        def one(pr, yv):
            yd = _difference(yv, d)
            c, phi, theta = _split_params(pr, order, include_intercept)
            e = _css_errors(pr, yd, order, include_intercept, condition=False)
            # carries: last p differenced values (newest first), last q errors
            ydlast = yd[::-1][:p] if p else jnp.zeros((0,), yd.dtype)
            elast = e[::-1][: max(q, 1)]
            # last value of each difference level 0..d-1 for integration
            levels = []
            lv = yv
            for _ in range(d):
                levels.append(lv[-1])
                lv = lv[1:] - lv[:-1]
            levels = jnp.asarray(levels, yd.dtype) if d else jnp.zeros((0,), yd.dtype)

            def step(carry, _):
                ydl, el, lvl = carry
                pred = c + (jnp.dot(phi, ydl) if p else 0.0) + (jnp.dot(theta, el) if q else 0.0)
                new_ydl = jnp.concatenate([pred[None], ydl[:-1]]) if p else ydl
                new_el = jnp.concatenate([jnp.zeros((1,), el.dtype), el[:-1]]) if q else el
                # integrate: v_d = pred; v_i = lvl[i] + v_{i+1}
                acc = pred
                new_lvl = lvl
                for i in reversed(range(d)):
                    acc = lvl[i] + acc
                    new_lvl = new_lvl.at[i].set(acc)
                out = acc if d else pred
                return (new_ydl, new_el, new_lvl), out

            _, future = lax.scan(step, (ydlast, elast, levels), None, length=n_future)
            return future

        return jax.vmap(one)(params_b, yb)

    out = run(params_b, yb)
    return out[0] if single else out


def sample(params, key, n: int, order: Order, include_intercept: bool = True, sigma: float = 1.0):
    """Generate a series of length ``n`` from the model with N(0, sigma^2)
    innovations (reference ``ARIMAModel.sample``)."""
    p, d, q = order

    @jax.jit
    def run(params, key):
        params = jnp.asarray(params, jnp.result_type(float))
        c, phi, theta = _split_params(params, order, include_intercept)
        e = sigma * jax.random.normal(key, (n + d,), params.dtype)

        def step(carry, et):
            ydl, el = carry
            yt = c + (jnp.dot(phi, ydl) if p else 0.0) + (jnp.dot(theta, el) if q else 0.0) + et
            new_ydl = jnp.concatenate([yt[None], ydl[:-1]]) if p else ydl
            new_el = jnp.concatenate([et[None], el[:-1]]) if q else el
            return (new_ydl, new_el), yt

        init = (jnp.zeros((max(p, 1),), e.dtype), jnp.zeros((max(q, 1),), e.dtype))
        _, yd = lax.scan(step, init, e)
        y = yd
        for _ in range(d):
            y = jnp.cumsum(y)
        return y[d:] if d else y

    return run(params, key)


def remove_time_dependent_effects(params, y, order: Order, include_intercept: bool = True):
    """Destructure a series into its innovations (zero-padded-lag recursion;
    exactly inverted by :func:`add_time_dependent_effects`).  The first ``d``
    output entries carry the integration constants."""
    _, d, _ = order
    yb, single = ensure_batched(y)
    params_b = jnp.atleast_2d(params)

    @jax.jit
    def run(params_b, yb):
        def one(pr, yv):
            # integration constants: the FIRST value of each difference level
            lv = yv
            inits = []
            for _ in range(d):
                inits.append(lv[0])
                lv = lv[1:] - lv[:-1]
            yd = lv
            e = _css_errors(pr, yd, order, include_intercept, condition=False)
            inits_arr = (
                jnp.stack(inits) if d else jnp.zeros((0,), yv.dtype)
            )
            return jnp.concatenate([inits_arr, e])

        return jax.vmap(one)(params_b, yb)

    out = run(params_b, yb)
    return out[0] if single else out


def add_time_dependent_effects(params, x, order: Order, include_intercept: bool = True):
    """Inverse of :func:`remove_time_dependent_effects`: innovations (with
    integration constants in the first ``d`` slots) -> the observed series."""
    p, d, q = order
    xb, single = ensure_batched(x)
    params_b = jnp.atleast_2d(params)

    @jax.jit
    def run(params_b, xb):
        def one(pr, xv):
            c, phi, theta = _split_params(pr, order, include_intercept)
            init_vals, e = xv[:d], xv[d:]

            def step(carry, et):
                ydl, el = carry
                yt = (
                    c
                    + (jnp.dot(phi, ydl) if p else 0.0)
                    + (jnp.dot(theta, el) if q else 0.0)
                    + et
                )
                new_ydl = jnp.concatenate([yt[None], ydl[:-1]]) if p else ydl
                new_el = jnp.concatenate([et[None], el[:-1]]) if q else el
                return (new_ydl, new_el), yt

            init = (jnp.zeros((max(p, 1),), xv.dtype), jnp.zeros((max(q, 1),), xv.dtype))
            _, yd = lax.scan(step, init, e)
            # integrate d times using the stored initial values
            y = yd
            for i in reversed(range(d)):
                y = init_vals[i] + jnp.cumsum(y)
                y = jnp.concatenate([init_vals[i][None], y])
            return y

        return jax.vmap(one)(params_b, xb)

    out = run(params_b, xb)
    return out[0] if single else out


def is_stationary(params, order: Order, include_intercept: bool = True) -> np.ndarray:
    """AR-polynomial roots outside the unit circle (host-side diagnostic)."""
    p, _, _ = order
    if p == 0:
        return np.asarray(True)
    c, phi, _ = _split_params(np.asarray(params), order, include_intercept)
    roots = np.roots(np.concatenate([[1.0], -np.asarray(phi)])[::-1])
    return np.asarray(np.all(np.abs(roots) > 1.0 + 1e-9))


def is_invertible(params, order: Order, include_intercept: bool = True) -> np.ndarray:
    """MA-polynomial roots outside the unit circle (host-side diagnostic)."""
    _, _, q = order
    if q == 0:
        return np.asarray(True)
    _, _, theta = _split_params(np.asarray(params), order, include_intercept)
    roots = np.roots(np.concatenate([[1.0], np.asarray(theta)])[::-1])
    return np.asarray(np.all(np.abs(roots) > 1.0 + 1e-9))
