"""Holt-Winters triple exponential smoothing (L4).

Rebuild of the reference's ``sparkts/models/HoltWinters.scala`` (SURVEY.md
Section 2.2, upstream path unverified): additive and multiplicative
seasonality with period ``m``; level/trend/seasonal start values taken from
the first two seasons; ``(alpha, beta, gamma)`` fitted by minimizing the
one-step-ahead SSE.  The reference uses BOBYQA per series; here the
smoothing recursion is a ``lax.scan``, the (0,1) bounds are a sigmoid
reparameterization, and the fit is the shared vmapped L-BFGS
(SURVEY.md Section 7's BOBYQA-replacement strategy).

Parameter layout (natural space): ``[alpha, beta, gamma]``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import optim
from .base import (FitResult, align_right, debatch,
                   debatch_fit, derive_status,
                   require_pallas_for_count_evals,
                   ensure_batched, maybe_align,
                   jit_program, resolve_align_mode, resolve_backend)


def _init_state(y, period: int, multiplicative: bool, start=None):
    """Start values from the first two seasons (upstream's scheme).

    ``start`` (traced scalar) points at the first valid observation of a
    right-aligned series; the seasons are sliced dynamically from there.
    """
    if start is None:
        s1 = y[:period]
        s2 = y[period : 2 * period]
    else:
        s1 = lax.dynamic_slice(y, (start,), (period,))
        s2 = lax.dynamic_slice(y, (start + period,), (period,))
    level0 = jnp.mean(s1)
    trend0 = (jnp.mean(s2) - jnp.mean(s1)) / period
    if multiplicative:
        seasonal0 = s1 / jnp.maximum(level0, 1e-12)
    else:
        seasonal0 = s1 - level0
    return level0, trend0, seasonal0


def _run(params, y, period: int, multiplicative: bool, n_valid=None):
    """Run the smoothing recursion; returns (one-step forecasts, final state).

    forecasts[t] is the prediction of y[t] made at t-1 (for t >= period... the
    first ``period`` entries predict using the seed state).  ``n_valid`` marks
    a right-aligned valid span: the state holds through the zero prefix so the
    recursion effectively starts at the first valid observation.
    """
    alpha, beta, gamma = params[0], params[1], params[2]
    start = None if n_valid is None else y.shape[0] - n_valid
    level0, trend0, seasonal0 = _init_state(y, period, multiplicative, start)

    def step(carry, inp):
        yt, t = inp
        level, trend, seasonal = carry  # seasonal: [period], rotating
        s = seasonal[0]
        if multiplicative:
            pred = (level + trend) * s
            new_level = alpha * yt / jnp.maximum(s, 1e-12) + (1 - alpha) * (level + trend)
            new_seasonal_last = gamma * yt / jnp.maximum(new_level, 1e-12) + (1 - gamma) * s
        else:
            pred = level + trend + s
            new_level = alpha * (yt - s) + (1 - alpha) * (level + trend)
            new_seasonal_last = gamma * (yt - new_level) + (1 - gamma) * s
        new_trend = beta * (new_level - level) + (1 - beta) * trend
        new_seasonal = jnp.concatenate([seasonal[1:], new_seasonal_last[None]])
        if start is not None:
            skip = t < start
            new_level = jnp.where(skip, level, new_level)
            new_trend = jnp.where(skip, trend, new_trend)
            new_seasonal = jnp.where(skip, seasonal, new_seasonal)
        return (new_level, new_trend, new_seasonal), pred

    (level, trend, seasonal), preds = lax.scan(
        step, (level0, trend0, seasonal0), (y, jnp.arange(y.shape[0]))
    )
    return preds, (level, trend, seasonal)


def sse(params, y, period: int, multiplicative: bool, n_valid=None):
    """One-step-ahead SSE, skipping the seeded first season."""
    preds, _ = _run(params, y, period, multiplicative, n_valid)
    err = y - preds
    start = 0 if n_valid is None else y.shape[0] - n_valid
    err = jnp.where(jnp.arange(y.shape[0]) >= start + period, err, 0.0)
    return jnp.sum(err * err)


# module-level so tests can monkeypatch the gate per model (sizing lives
# with the compaction feature: utils.optim)
_COMPACT_MIN_BATCH = optim.COMPACT_MIN_BATCH

# seeded multi-start inits (natural (alpha, beta, gamma) space), probed in
# order: the long-standing default first, then two deterministic probes at
# opposite corners of the smoothing cube.  The multiplicative SSE surface is
# non-convex with a fat local-optimum tail (PRECISION.md round 5: p99 drift
# 0.74, f64 oracle non-converged on 9.8% of rows); re-running the optimizer
# from 2-3 spread inits and keeping each row's best final objective
# collapses that tail for ~(n_starts - 1) extra fit passes.
_MULTISTART_NATS = (
    (0.3, 0.1, 0.1),
    (0.7, 0.25, 0.4),
    (0.12, 0.05, 0.6),
)


def fit(
    y,
    period: int,
    model_type: str = "additive",
    *,
    max_iters: int = 60,
    tol: Optional[float] = None,
    backend: str = "auto",
    count_evals: bool = False,
    compact: bool = True,
    n_starts: Optional[int] = None,
    align_mode: Optional[str] = None,
) -> FitResult:
    """Fit (alpha, beta, gamma) per series -> params ``[batch?, 3]``.

    ``backend``: ``"scan"`` (portable), ``"pallas"`` (fused TPU kernel —
    additive and multiplicative, ragged panels via the right-aligned span),
    or ``"auto"`` (pallas whenever the platform/dtype/period allow).

    ``count_evals=True`` (pallas backend only) returns ``(FitResult, info)``
    with the optimizer's pass-accounting dict (``utils.optim``; multi-start
    fits report the FIRST start's passes plus an ``n_starts`` multiplier).

    ``compact=False`` disables straggler compaction for run-to-run
    reproducibility (it engages on the pallas backend at batches >=
    ``utils.optim.COMPACT_MIN_BATCH`` = 4096 and is a different compiled
    program — bitwise outputs can differ from the uncompacted run).

    ``n_starts`` (default: 3 for multiplicative, 1 for additive; at most
    ``len(_MULTISTART_NATS)`` = 3 — extend that table for more) runs the
    optimizer from that many deterministic seeded inits
    (``_MULTISTART_NATS``) and keeps each row's best final objective —
    preferring converged starts — so rows stranded in a bad local optimum
    of the non-convex (especially multiplicative) SSE surface are rescued
    by a better basin instead of shipping a 0.7-drift parameter tail.

    ``align_mode`` is the static alignment hint (``base.resolve_align_mode``)
    the chunk driver threads through sliced walks to skip the per-chunk NaN
    probe; a hint too strong for the data flags the violating rows
    (DIVERGED / EXCLUDED) instead of silently misfitting them.
    ``FitResult.status`` carries per-row ``reliability.FitStatus`` codes."""
    if model_type not in ("additive", "multiplicative"):
        raise ValueError(f"model_type must be additive|multiplicative, got {model_type!r}")
    multiplicative = model_type == "multiplicative"
    if n_starts is None:
        n_starts = 3 if multiplicative else 1
    if not 1 <= int(n_starts) <= len(_MULTISTART_NATS):
        raise ValueError(
            f"n_starts must be in [1, {len(_MULTISTART_NATS)}] (one per "
            "seeded init in holtwinters._MULTISTART_NATS — extend that "
            f"table to probe more basins), got {n_starts}")
    n_starts = int(n_starts)
    yb, single = ensure_batched(y)
    if yb.shape[1] < 2 * period:
        raise ValueError(
            f"need at least two seasons ({2 * period} points), got {yb.shape[1]}"
        )
    if tol is None:
        tol = 1e-7 if yb.dtype == jnp.float64 else 1e-4
    from ..ops import pallas_kernels as pk

    backend = resolve_backend(backend, yb.dtype, yb.shape[1],
                              structural_ok=pk.hw_structural_ok(period))
    require_pallas_for_count_evals(count_evals, backend)
    align_mode = resolve_align_mode(yb, align_mode)
    bsz = yb.shape[0]
    # lazy straggler compile (utils.optim stage-1/stage-2 split): the
    # compacted stage-2 program is traced/compiled only when a start's
    # stage 1 actually leaves unconverged rows — same gate and host check
    # as models.arima.fit, extended with a PER-START carry: the seeded
    # multi-start runs several optimizer passes per fit, and each start
    # gates its own stage-2 dispatch; the ONE stage-2 program (stable
    # shapes across starts) is shared by every start that needs it, and
    # the basin selection re-merges only when some start re-ran.
    lazy = (compact and not count_evals
            and backend in ("pallas", "pallas-interpret")
            and not isinstance(yb, jax.core.Tracer)
            and bsz >= _COMPACT_MIN_BATCH
            and optim.compaction_cap(bsz) < bsz)
    if lazy:
        out, aux = _fit_stage1_program(
            period, multiplicative, max_iters, float(tol), backend,
            align_mode, n_starts)(yb)
        finished, redo = [], False
        for a in aux["starts"]:
            c = a["carry"]
            if int(c.undone) > 0 and int(c.k) < max_iters:
                finished.append(_fit_stage2_program(
                    period, multiplicative, max_iters, float(tol),
                    backend)(a))
                redo = True
            else:
                finished.append(a["res"])
        if redo:
            out = _merge_starts_program(n_starts)(
                tuple(finished), aux["ok"], aux["n_err"])
        return debatch_fit(out, single, False)
    out = _fit_program(period, multiplicative, max_iters, float(tol), backend,
                       align_mode, count_evals, compact,
                       n_starts)(yb)
    return debatch_fit(out, single, count_evals)


@jit_program
def _fit_program(period, multiplicative, max_iters, tol, backend,
                 align_mode="general", count_evals=False, compact=True,
                 n_starts=1):
    def run(yb):
        ya, nv = maybe_align(yb, align_mode)

        # optimize the MEAN one-step squared error: same argmin as the SSE,
        # but the gradient scale is O(1), so the relative grad-norm stopping
        # rule fires when the fit is actually done instead of never
        n_err = jnp.maximum(nv - period, 1).astype(yb.dtype)
        if backend in ("pallas", "pallas-interpret"):
            from ..ops import pallas_kernels as pk

            interp = backend == "pallas-interpret"

            # seeds are data-only: compute ONCE, not per objective call or
            # per start (vmapped seed slices are batched gathers — recomputed
            # inside the loop they dominate an objective evaluation at panel
            # scale; the dense mode takes the gather-free static-slice path)
            seeds = pk.hw_seeds(
                ya, period, multiplicative,
                None if align_mode == "dense" else nv)

            def fb(u):
                nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                return pk.hw_sse_seeded(
                    nat, ya, seeds, period, multiplicative, interpret=interp
                ) / n_err

            # straggler compaction (utils.optim): the objective closes over
            # the NATURAL-layout panel + per-row seed state, so the subset
            # gather is a plain row gather of each
            bsz = ya.shape[0]
            cap = optim.compaction_cap(bsz)
            straggler_fun = None
            if compact and bsz >= _COMPACT_MIN_BATCH:

                def straggler_fun(idxc):
                    yas = ya[idxc]
                    seeds_s = tuple(s[idxc] for s in seeds)
                    nes = n_err[idxc]

                    def fb_s(u):
                        nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                        return pk.hw_sse_seeded(
                            nat, yas, seeds_s, period, multiplicative,
                            interpret=interp) / nes

                    return fb_s

            def one_start(nat0, want_info):
                u0 = jnp.broadcast_to(
                    optim.interval_to_sigmoid(
                        jnp.asarray(nat0, yb.dtype), 0.0, 1.0),
                    (yb.shape[0], 3))
                r = optim.minimize_lbfgs_batched(
                    fb, u0, max_iters=max_iters, tol=tol,
                    count_evals=want_info,
                    straggler_fun=straggler_fun, straggler_cap=cap)
                return r if want_info else (r, None)
        else:
            def objective(u, data):
                yv, n, ne = data
                nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                return sse(nat, yv, period, multiplicative, n) / ne

            def one_start(nat0, want_info):
                u0 = jnp.broadcast_to(
                    optim.interval_to_sigmoid(
                        jnp.asarray(nat0, yb.dtype), 0.0, 1.0),
                    (yb.shape[0], 3))
                r = optim.batched_minimize(
                    objective, u0, (ya, nv, n_err), max_iters=max_iters,
                    tol=tol)
                return r, None

        # seeded multi-start: run the optimizer from each init and keep,
        # per row, the best basin (_select_best_start).  Pass accounting
        # (count_evals) reports the first start's passes; n_starts rides
        # in the info dict as a multiplier.
        res, info = one_start(_MULTISTART_NATS[0], count_evals)
        if info is not None:
            info = {**info, "n_starts": n_starts}
        if n_starts > 1:
            starts = [res] + [one_start(_MULTISTART_NATS[s], False)[0]
                              for s in range(1, n_starts)]
            res = _select_best_start(starts)
        ok = nv >= 2 * period  # seed needs two full seasons of real data
        out = _finalize_hw_fit(res, ok, n_err)
        return (out, info) if count_evals else out

    return run


def _select_best_start(starts):
    """Per-row basin selection across seeded multi-start results.

    Selection is two-stage and designed to be DETERMINISTIC ACROSS
    PRECISIONS (PRECISION.md: the multiplicative surface has near-tied
    local optima, and picking by raw SSE order lets f32 and f64 flip
    coins on which basin float noise ranks first, shipping a fat
    cross-precision parameter-drift tail):

    1. candidates = converged starts (all starts when none converged)
       within 0.1% relative of the row's best final objective —
       statistically indistinguishable fits;
    2. among candidates, prefer the SMOOTHEST model (smallest
       alpha+beta+gamma; basins sit far apart in parameter space, so this
       comparison is float-noise-robust), ties to the earliest start.

    ONE implementation serves the inline multi-start program and the lazy
    stage-1/stage-2 split's re-merge — the basin choice must never diverge
    between them.
    """
    if len(starts) == 1:
        return starts[0]
    res = starts[0]
    xs = jnp.stack([r.x for r in starts])  # [S, B, 3]
    fs = jnp.stack([jnp.nan_to_num(r.f, nan=jnp.inf, posinf=jnp.inf)
                    for r in starts])
    convs = jnp.stack([r.converged for r in starts])
    any_conv = convs.any(axis=0)
    eligible = jnp.where(any_conv[None, :], convs, True)
    f_elig = jnp.where(eligible, fs, jnp.inf)
    best_f = jnp.min(f_elig, axis=0)
    near = eligible & (f_elig <= best_f[None, :] * (1 + 1e-3) + 1e-12)
    smooth = jnp.sum(
        optim.sigmoid_to_interval(xs, 0.0, 1.0), axis=-1)
    sel = jnp.argmin(jnp.where(near, smooth, jnp.inf), axis=0)
    take = lambda field: jnp.take_along_axis(  # noqa: E731
        jnp.stack([getattr(r, field) for r in starts]),
        sel[None, :], axis=0)[0]
    merged = {
        "x": jnp.take_along_axis(
            xs, sel[None, :, None], axis=0)[0],
        "f": take("f"),
        "converged": take("converged"),
        "iters": take("iters"),
    }
    if hasattr(res, "grad_norm"):
        merged["grad_norm"] = take("grad_norm")
    return res._replace(**merged)


def _finalize_hw_fit(res, ok, n_err):
    """Optimizer result -> FitResult (same ops as the inline program);
    the reported objective is the unscaled SSE."""
    params = jnp.where(
        ok[:, None], optim.sigmoid_to_interval(res.x, 0.0, 1.0), jnp.nan)
    return FitResult(
        params,
        jnp.where(ok, res.f * n_err, jnp.nan),
        res.converged & ok,
        res.iters,
        derive_status(ok, res.converged, params),
    )


@jit_program
def _fit_stage1_program(period, multiplicative, max_iters, tol, backend,
                        align_mode="general", n_starts=1):
    """Stage 1 of the lazily compiled compact Holt-Winters fit: the full
    prep (alignment + one-time seed state) and, PER SEEDED START, the
    lockstep L-BFGS with the straggler early-exit — returning the
    finalized as-if-done merged result PLUS one compacted carry per start,
    so the stage-2 program is traced/compiled only when some start's
    ``carry.undone`` says rows actually remain (and dispatched only for
    those starts).  Pallas backends only (the gate lives in ``fit``)."""

    def run(yb):
        ya, nv = maybe_align(yb, align_mode)
        n_err = jnp.maximum(nv - period, 1).astype(yb.dtype)
        from ..ops import pallas_kernels as pk

        interp = backend == "pallas-interpret"
        # seeds are data-only: compute ONCE and share across every start
        # (same contract as the inline program)
        seeds = pk.hw_seeds(
            ya, period, multiplicative,
            None if align_mode == "dense" else nv)

        def fb(u):
            nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
            return pk.hw_sse_seeded(
                nat, ya, seeds, period, multiplicative, interpret=interp
            ) / n_err

        bsz = ya.shape[0]
        cap = optim.compaction_cap(bsz)
        results, starts_aux = [], []
        for s in range(n_starts):
            u0 = jnp.broadcast_to(
                optim.interval_to_sigmoid(
                    jnp.asarray(_MULTISTART_NATS[s], yb.dtype), 0.0, 1.0),
                (bsz, 3))
            res1, carry = optim.lbfgs_batched_stage1(
                fb, u0, straggler_cap=cap, max_iters=max_iters, tol=tol)
            # gather the compacted objective data HERE (plain row gathers
            # of the natural-layout panel + per-row seed state) so the
            # stage-2 program is a pure function of its inputs and keeps
            # stable shapes across starts — ONE compiled stage-2 program
            # serves every start that needs it
            starts_aux.append({
                "carry": carry, "res": res1, "yas": ya[carry.idxc],
                "seeds_s": tuple(x[carry.idxc] for x in seeds),
                "nes": n_err[carry.idxc]})
            results.append(res1)
        ok = nv >= 2 * period
        out = _finalize_hw_fit(_select_best_start(results), ok, n_err)
        return out, {"starts": tuple(starts_aux), "ok": ok, "n_err": n_err}

    return run


@jit_program
def _fit_stage2_program(period, multiplicative, max_iters, tol, backend):
    """Stage 2 of the lazy compact Holt-Winters fit: finish ONE start's
    gathered stragglers on the compacted objective and scatter back into
    that start's full-batch result — compiled on the first call where any
    start left unconverged rows, then reused by every such start."""
    interp = backend == "pallas-interpret"

    def run(aux_s):
        from ..ops import pallas_kernels as pk

        def fb_s(u):
            nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
            return pk.hw_sse_seeded(
                nat, aux_s["yas"], aux_s["seeds_s"], period, multiplicative,
                interpret=interp) / aux_s["nes"]

        return optim.lbfgs_batched_stage2(
            fb_s, aux_s["res"], aux_s["carry"], max_iters=max_iters, tol=tol)

    return run


@jit_program
def _merge_starts_program(n_starts):
    """Re-merge the per-start results after lazy stage-2 dispatches: the
    same basin selection + finalize the inline program applies."""

    def run(results, ok, n_err):
        return _finalize_hw_fit(_select_best_start(list(results)), ok, n_err)

    return run


def forecast(params, y, period: int, n_future: int, model_type: str = "additive"):
    """h-step-ahead forecasts from the end state:
    additive: (level + h*trend) + seasonal; multiplicative: * seasonal."""
    multiplicative = model_type == "multiplicative"
    yb, single = ensure_batched(y)
    pb = jnp.atleast_2d(params)
    out = _forecast_program(period, multiplicative, n_future)(pb, yb)
    return out[0] if single else out


@jit_program
def _forecast_program(period, multiplicative, n_future):
    def run(pb, yb):
        def one(pr, yv):
            ya, nv = align_right(yv)
            _, (level, trend, seasonal) = _run(pr, ya, period, multiplicative, nv)
            h = jnp.arange(1, n_future + 1, dtype=yv.dtype)
            seas = seasonal[(jnp.arange(n_future)) % period]
            base = level + h * trend
            out = base * seas if multiplicative else base + seas
            # seeding needs two full seasons (same gate as fit): shorter
            # spans would return finite garbage from clamped seed windows
            return jnp.where(nv >= 2 * period, out, jnp.nan)

        return jax.vmap(one)(pb, yb)

    return run


def fitted(params, y, period: int, model_type: str = "additive"):
    """In-sample one-step-ahead predictions (``addTimeDependentEffects``
    analog for diagnostics)."""
    multiplicative = model_type == "multiplicative"
    yb, single = ensure_batched(y)
    pb = jnp.atleast_2d(params)
    out = _fitted_program(period, multiplicative)(pb, yb)
    return out[0] if single else out


@jit_program
def _fitted_program(period, multiplicative):
    return jax.vmap(lambda pr, yv: _run(pr, yv, period, multiplicative)[0])
