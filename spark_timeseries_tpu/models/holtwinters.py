"""Holt-Winters triple exponential smoothing (L4).

Rebuild of the reference's ``sparkts/models/HoltWinters.scala`` (SURVEY.md
Section 2.2, upstream path unverified): additive and multiplicative
seasonality with period ``m``; level/trend/seasonal start values taken from
the first two seasons; ``(alpha, beta, gamma)`` fitted by minimizing the
one-step-ahead SSE.  The reference uses BOBYQA per series; here the
smoothing recursion is a ``lax.scan``, the (0,1) bounds are a sigmoid
reparameterization, and the fit is the shared vmapped L-BFGS
(SURVEY.md Section 7's BOBYQA-replacement strategy).

Parameter layout (natural space): ``[alpha, beta, gamma]``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import optim
from .base import (FitResult, align_mode_on_host, align_right, debatch,
                   debatch_fit, derive_status,
                   require_pallas_for_count_evals,
                   ensure_batched, maybe_align,
                   jit_program, resolve_backend)


def _init_state(y, period: int, multiplicative: bool, start=None):
    """Start values from the first two seasons (upstream's scheme).

    ``start`` (traced scalar) points at the first valid observation of a
    right-aligned series; the seasons are sliced dynamically from there.
    """
    if start is None:
        s1 = y[:period]
        s2 = y[period : 2 * period]
    else:
        s1 = lax.dynamic_slice(y, (start,), (period,))
        s2 = lax.dynamic_slice(y, (start + period,), (period,))
    level0 = jnp.mean(s1)
    trend0 = (jnp.mean(s2) - jnp.mean(s1)) / period
    if multiplicative:
        seasonal0 = s1 / jnp.maximum(level0, 1e-12)
    else:
        seasonal0 = s1 - level0
    return level0, trend0, seasonal0


def _run(params, y, period: int, multiplicative: bool, n_valid=None):
    """Run the smoothing recursion; returns (one-step forecasts, final state).

    forecasts[t] is the prediction of y[t] made at t-1 (for t >= period... the
    first ``period`` entries predict using the seed state).  ``n_valid`` marks
    a right-aligned valid span: the state holds through the zero prefix so the
    recursion effectively starts at the first valid observation.
    """
    alpha, beta, gamma = params[0], params[1], params[2]
    start = None if n_valid is None else y.shape[0] - n_valid
    level0, trend0, seasonal0 = _init_state(y, period, multiplicative, start)

    def step(carry, inp):
        yt, t = inp
        level, trend, seasonal = carry  # seasonal: [period], rotating
        s = seasonal[0]
        if multiplicative:
            pred = (level + trend) * s
            new_level = alpha * yt / jnp.maximum(s, 1e-12) + (1 - alpha) * (level + trend)
            new_seasonal_last = gamma * yt / jnp.maximum(new_level, 1e-12) + (1 - gamma) * s
        else:
            pred = level + trend + s
            new_level = alpha * (yt - s) + (1 - alpha) * (level + trend)
            new_seasonal_last = gamma * (yt - new_level) + (1 - gamma) * s
        new_trend = beta * (new_level - level) + (1 - beta) * trend
        new_seasonal = jnp.concatenate([seasonal[1:], new_seasonal_last[None]])
        if start is not None:
            skip = t < start
            new_level = jnp.where(skip, level, new_level)
            new_trend = jnp.where(skip, trend, new_trend)
            new_seasonal = jnp.where(skip, seasonal, new_seasonal)
        return (new_level, new_trend, new_seasonal), pred

    (level, trend, seasonal), preds = lax.scan(
        step, (level0, trend0, seasonal0), (y, jnp.arange(y.shape[0]))
    )
    return preds, (level, trend, seasonal)


def sse(params, y, period: int, multiplicative: bool, n_valid=None):
    """One-step-ahead SSE, skipping the seeded first season."""
    preds, _ = _run(params, y, period, multiplicative, n_valid)
    err = y - preds
    start = 0 if n_valid is None else y.shape[0] - n_valid
    err = jnp.where(jnp.arange(y.shape[0]) >= start + period, err, 0.0)
    return jnp.sum(err * err)


# module-level so tests can monkeypatch the gate per model (sizing lives
# with the compaction feature: utils.optim)
_COMPACT_MIN_BATCH = optim.COMPACT_MIN_BATCH


def fit(
    y,
    period: int,
    model_type: str = "additive",
    *,
    max_iters: int = 60,
    tol: Optional[float] = None,
    backend: str = "auto",
    count_evals: bool = False,
    compact: bool = True,
) -> FitResult:
    """Fit (alpha, beta, gamma) per series -> params ``[batch?, 3]``.

    ``backend``: ``"scan"`` (portable), ``"pallas"`` (fused TPU kernel —
    additive and multiplicative, ragged panels via the right-aligned span),
    or ``"auto"`` (pallas whenever the platform/dtype/period allow).

    ``count_evals=True`` (pallas backend only) returns ``(FitResult, info)``
    with the optimizer's pass-accounting dict (``utils.optim``).

    ``compact=False`` disables straggler compaction for run-to-run
    reproducibility (it engages on the pallas backend at batches >=
    ``utils.optim.COMPACT_MIN_BATCH`` = 4096 and is a different compiled
    program — bitwise outputs can differ from the uncompacted run).
    ``FitResult.status`` carries per-row ``reliability.FitStatus`` codes."""
    if model_type not in ("additive", "multiplicative"):
        raise ValueError(f"model_type must be additive|multiplicative, got {model_type!r}")
    multiplicative = model_type == "multiplicative"
    yb, single = ensure_batched(y)
    if yb.shape[1] < 2 * period:
        raise ValueError(
            f"need at least two seasons ({2 * period} points), got {yb.shape[1]}"
        )
    if tol is None:
        tol = 1e-7 if yb.dtype == jnp.float64 else 1e-4
    from ..ops import pallas_kernels as pk

    backend = resolve_backend(backend, yb.dtype, yb.shape[1],
                              structural_ok=pk.hw_structural_ok(period))
    require_pallas_for_count_evals(count_evals, backend)
    out = _fit_program(period, multiplicative, max_iters, float(tol), backend,
                       align_mode_on_host(yb), count_evals, compact)(yb)
    return debatch_fit(out, single, count_evals)


@jit_program
def _fit_program(period, multiplicative, max_iters, tol, backend,
                 align_mode="general", count_evals=False, compact=True):
    def run(yb):
        ya, nv = maybe_align(yb, align_mode)

        nat0 = jnp.asarray([0.3, 0.1, 0.1], yb.dtype)
        u0 = jnp.broadcast_to(
            optim.interval_to_sigmoid(nat0, 0.0, 1.0), (yb.shape[0], 3)
        )
        # optimize the MEAN one-step squared error: same argmin as the SSE,
        # but the gradient scale is O(1), so the relative grad-norm stopping
        # rule fires when the fit is actually done instead of never
        n_err = jnp.maximum(nv - period, 1).astype(yb.dtype)
        if backend in ("pallas", "pallas-interpret"):
            from ..ops import pallas_kernels as pk

            interp = backend == "pallas-interpret"

            # seeds are data-only: compute ONCE, not per objective call
            # (vmapped seed slices are batched gathers — recomputed inside
            # the loop they dominate an objective evaluation at panel scale;
            # the dense mode takes the gather-free static-slice path)
            seeds = pk.hw_seeds(
                ya, period, multiplicative,
                None if align_mode == "dense" else nv)

            def fb(u):
                nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                return pk.hw_sse_seeded(
                    nat, ya, seeds, period, multiplicative, interpret=interp
                ) / n_err

            # straggler compaction (utils.optim): the objective closes over
            # the NATURAL-layout panel + per-row seed state, so the subset
            # gather is a plain row gather of each
            bsz = ya.shape[0]
            cap = optim.compaction_cap(bsz)
            straggler_fun = None
            if compact and bsz >= _COMPACT_MIN_BATCH:

                def straggler_fun(idxc):
                    yas = ya[idxc]
                    seeds_s = tuple(s[idxc] for s in seeds)
                    nes = n_err[idxc]

                    def fb_s(u):
                        nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                        return pk.hw_sse_seeded(
                            nat, yas, seeds_s, period, multiplicative,
                            interpret=interp) / nes

                    return fb_s

            res = optim.minimize_lbfgs_batched(
                fb, u0, max_iters=max_iters, tol=tol, count_evals=count_evals,
                straggler_fun=straggler_fun, straggler_cap=cap)
            info = None
            if count_evals:
                res, info = res
        else:
            def objective(u, data):
                yv, n, ne = data
                nat = optim.sigmoid_to_interval(u, 0.0, 1.0)
                return sse(nat, yv, period, multiplicative, n) / ne

            res = optim.batched_minimize(
                objective, u0, (ya, nv, n_err), max_iters=max_iters, tol=tol
            )
        ok = nv >= 2 * period  # seed needs two full seasons of real data
        params = jnp.where(
            ok[:, None], optim.sigmoid_to_interval(res.x, 0.0, 1.0), jnp.nan)
        out = FitResult(
            params,
            jnp.where(ok, res.f * n_err, jnp.nan),  # report the SSE as before
            res.converged & ok,
            res.iters,
            derive_status(ok, res.converged, params),
        )
        return (out, info) if count_evals else out

    return run


def forecast(params, y, period: int, n_future: int, model_type: str = "additive"):
    """h-step-ahead forecasts from the end state:
    additive: (level + h*trend) + seasonal; multiplicative: * seasonal."""
    multiplicative = model_type == "multiplicative"
    yb, single = ensure_batched(y)
    pb = jnp.atleast_2d(params)
    out = _forecast_program(period, multiplicative, n_future)(pb, yb)
    return out[0] if single else out


@jit_program
def _forecast_program(period, multiplicative, n_future):
    def run(pb, yb):
        def one(pr, yv):
            ya, nv = align_right(yv)
            _, (level, trend, seasonal) = _run(pr, ya, period, multiplicative, nv)
            h = jnp.arange(1, n_future + 1, dtype=yv.dtype)
            seas = seasonal[(jnp.arange(n_future)) % period]
            base = level + h * trend
            out = base * seas if multiplicative else base + seas
            # seeding needs two full seasons (same gate as fit): shorter
            # spans would return finite garbage from clamped seed windows
            return jnp.where(nv >= 2 * period, out, jnp.nan)

        return jax.vmap(one)(pb, yb)

    return run


def fitted(params, y, period: int, model_type: str = "additive"):
    """In-sample one-step-ahead predictions (``addTimeDependentEffects``
    analog for diagnostics)."""
    multiplicative = model_type == "multiplicative"
    yb, single = ensure_batched(y)
    pb = jnp.atleast_2d(params)
    out = _fitted_program(period, multiplicative)(pb, yb)
    return out[0] if single else out


@jit_program
def _fitted_program(period, multiplicative):
    return jax.vmap(lambda pr, yv: _run(pr, yv, period, multiplicative)[0])
