"""Regression with serially-correlated (AR) errors — Cochrane-Orcutt (L4).

Rebuild of the reference's ``sparkts/models/RegressionARIMA.scala``
(SURVEY.md Section 2.2, upstream path unverified): y = X beta + u with
u_t = rho * u_{t-1} + e_t, estimated by the iterative Cochrane-Orcutt
procedure.  The reference loops OLS -> AR(1)-on-residuals -> quasi-difference
until rho converges; here each iteration is a batched normal-equations solve
and the loop is a fixed-trip ``lax.fori_loop`` (vmapped over series).

Result layout: ``params = [beta_0 .. beta_{k-1}, rho]`` where beta_0 is the
intercept.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from typing import Optional

from ..utils.linalg import ols as _ols
from .base import (ALIGN_MODES, FitResult, debatch, derive_status,
                   jit_program)


def _design(X):
    """Prepend an intercept column."""
    return jnp.concatenate([jnp.ones((X.shape[0], 1), X.dtype), X], axis=1)


def fit_cochrane_orcutt(y, X, *, max_iter: int = 10) -> FitResult:
    """Fit y ``[batch?, n]`` on regressors X ``[batch?, n, k]``.

    Returns ``params = [batch?, k+2]``: intercept, k slopes, rho.
    """
    y = jnp.asarray(y)
    X = jnp.asarray(X)
    single = y.ndim == 1
    yb = y[None] if single else y
    Xb = X[None] if single else X
    return debatch(_co_program(max_iter)(yb, Xb), single)


@jit_program
def _co_program(max_iter):
    def run(yb, Xb):
        def one(yv, Xv):
            Xd = _design(Xv)  # [n, k+1]

            def body(_, carry):
                beta, rho = carry
                u = yv - Xd @ beta
                # AR(1) on residuals (no intercept)
                rho = jnp.sum(u[1:] * u[:-1]) / jnp.maximum(jnp.sum(u[:-1] ** 2), 1e-12)
                rho = jnp.clip(rho, -0.999, 0.999)
                # quasi-difference transform and re-estimate beta
                ys = yv[1:] - rho * yv[:-1]
                Xs = Xd[1:] - rho * Xd[:-1]
                # intercept column becomes (1 - rho); solve in transformed space
                beta_t = _ols(Xs, ys)
                # map intercept back: beta_0 = beta_t0 (Xs keeps scaled ones)
                return beta_t, rho

            beta0 = _ols(Xd, yv)
            beta, rho = lax.fori_loop(
                0, max_iter, body, (beta0, jnp.zeros((), yv.dtype))
            )
            u = yv - Xd @ beta
            e = u[1:] - rho * u[:-1]
            n = e.shape[0]
            sigma2 = jnp.sum(e * e) / n
            nll = 0.5 * n * (jnp.log(2.0 * jnp.pi * sigma2) + 1.0)
            return jnp.concatenate([beta, rho[None]]), nll

        params, nll = jax.vmap(one)(yb, Xb)
        b = yb.shape[0]
        ones = jnp.ones((b,), bool)
        return FitResult(params, nll, ones,
                         jnp.full((b,), max_iter, jnp.int32),
                         derive_status(ones, ones, params))

    return run


def fit(y, X, method: str = "cochrane-orcutt", *,
        align_mode: Optional[str] = None, **kwargs) -> FitResult:
    """Reference ``RegressionARIMA.fitModel`` dispatcher.

    ``align_mode`` is accepted for chunk-driver uniformity
    (``base.resolve_align_mode``): the name is validated, but Cochrane-
    Orcutt has no ragged-panel alignment — the design requires dense
    ``y``/``X`` (NaNs propagate to NaN params, flagged by ``status``).
    """
    if align_mode is not None and align_mode not in ALIGN_MODES:
        raise ValueError(
            f"unknown align_mode {align_mode!r} (one of {ALIGN_MODES})")
    if method not in ("cochrane-orcutt", "cochrane_orcutt"):
        raise ValueError(f"unknown method {method!r} (supported: cochrane-orcutt)")
    return fit_cochrane_orcutt(y, X, **kwargs)


def predict(params, X):
    """Regression part only: X ``[batch?, n, k]`` -> fitted values."""
    X = jnp.asarray(X)
    single = X.ndim == 2
    Xb = X[None] if single else X
    pb = jnp.atleast_2d(params)
    out = _predict_batched(pb, Xb)
    return out[0] if single else out


_predict_batched = jax.jit(jax.vmap(lambda pr, Xv: _design(Xv) @ pr[:-1]))
