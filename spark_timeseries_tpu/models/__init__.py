from . import arima, autoregression, ewma, garch, holtwinters, regression_arima
from .base import FitResult

__all__ = [
    "arima",
    "autoregression",
    "ewma",
    "garch",
    "holtwinters",
    "regression_arima",
    "FitResult",
]
