from . import arima
from .base import FitResult

__all__ = ["arima", "FitResult"]
