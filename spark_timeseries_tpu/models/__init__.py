from . import (arima, auto, autoregression, ewma, garch, holtwinters,
               regression_arima)
from .auto import AutoFitResult, auto_fit
from .base import FitResult

__all__ = [
    "arima",
    "auto",
    "autoregression",
    "ewma",
    "garch",
    "holtwinters",
    "regression_arima",
    "AutoFitResult",
    "FitResult",
    "auto_fit",
]
