"""Metrics registry: counters, gauges, histograms — host-side, zero-dep.

Spark's executor heartbeats shipped per-task metric maps (shuffle bytes,
GC time, spill counts) to the driver, which aggregated them per stage; our
single-process rebuild needs only a process-local registry, but the same
taxonomy: monotonically increasing **counters** (ladder-rung rescues, OOM
backoff halvings, kernel-cache hits), point-in-time **gauges** (peak device
memory, chunk size in effect), and **histograms** of repeated measurements
(journal commit latency, span wall times) summarized as
count/sum/min/max/last — enough for the ``tools/obs_report.py`` table and
the manifest telemetry block without retaining unbounded samples.

Everything here is plain Python on the host: no jax import, no device
work, safe to call from watchdog worker threads (one lock per registry;
increments are far off any per-row hot loop — per chunk, per rung, per
dispatch at most).
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL_METRIC"]


class Counter:
    """A monotonically increasing count."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)

    # counters and gauges share call sites via duck typing
    add = inc


class Gauge:
    """A point-in-time value (last write wins)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value = None
        self._lock = lock

    def set(self, v) -> None:
        with self._lock:
            self.value = v

    def max(self, v) -> None:
        """Keep the running maximum (peak-style gauges)."""
        with self._lock:
            if self.value is None or v > self.value:
                self.value = v


class Histogram:
    """Streaming summary of repeated observations (count/sum/min/max/last).

    Deliberately no buckets or reservoir: the consumers (manifest telemetry
    block, ``obs_report`` table) want one-line summaries, and a bounded
    ring of raw events already lives in the flight recorder.
    """

    __slots__ = ("name", "count", "total", "min", "max", "last", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.last = None
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.last = v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def summary(self) -> dict:
        with self._lock:
            if not self.count:
                return {"count": 0}
            return {
                "count": self.count,
                "sum": round(self.total, 6),
                "mean": round(self.total / self.count, 6),
                "min": round(self.min, 6),
                "max": round(self.max, 6),
                "last": round(self.last, 6),
            }


class _NullMetric:
    """The disabled path: every mutator is a bound no-op, one shared
    instance — ``obs.counter(...)`` costs a dict-free attribute call and
    allocates nothing when telemetry is off."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    add = inc

    def set(self, v) -> None:
        pass

    def max(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass


NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Name -> metric, created on first touch (Prometheus-style)."""

    # lock-discipline contract (tools/lint lock-map): any instrumented
    # thread (driver, committer, lanes, abandoned watchdog workers) may
    # create a metric; the name->metric maps mutate under _lock (the
    # racy pre-check read is a fast path — setdefault under the lock is
    # what actually inserts).
    _protected_by_ = {
        "_counters": "_lock",
        "_gauges": "_lock",
        "_histograms": "_lock",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name, self._lock))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, self._lock))
        return h

    def snapshot(self) -> dict:
        """JSON-ready dump of every metric (sorted for stable artifacts).

        The name->metric maps are copied UNDER the lock (an abandoned
        watchdog worker can still be creating metrics while the driver
        snapshots) and the values read outside it — ``Histogram.summary``
        takes the same lock, so reading inside would self-deadlock.
        """
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
        return {
            "counters": {k: v.value for k, v in counters},
            "gauges": {k: v.value for k, v in gauges},
            "histograms": {k: v.summary() for k, v in histograms},
        }
