"""Deterministic distributed-trace contexts for the fleet (ISSUE 18).

One request's life crosses processes: a client submit, a backoff sleep,
an endpoint rotation, a ``not_leader`` redirect, a SIGKILLed primary, a
standby's write-ahead recovery, a bitwise re-answer.  This module gives
every one of those hops a shared identity — a **trace context** — so the
merged fleet streams (``tools/obs_report.py --fleet``) can reassemble a
single causal timeline per request.

Unlike Dapper-style tracers, ids here are NEVER random: a ``uuid4``
trace id would differ per process and per retry, which is exactly wrong
for a system whose requests already have content-derived identities and
whose replicas must continue each other's work byte-for-byte.  Instead:

- ``trace_id  = sha256("ststpu-trace:" + request_id)[:16]`` — every
  process that knows the request id derives the SAME trace id, with no
  wire state needed.  A standby re-answering a write-ahead request
  after a failover CONTINUES the dead primary's trace by construction.
- ``span_id   = sha256(trace_id + ":" + site)[:16]`` — a site is a
  causal segment ("client", "server", "server.batch"); the same segment
  on two replicas shares one id, which is the point: the failover
  re-dispatch IS the same segment, resumed elsewhere.
- ``parent_id`` links a child segment to the segment that caused it
  (the wire carries the caller's span id; the callee derives its own).

The context rides a thread-local that composes with
``watchdog.current_request`` (the deadline worker re-establishes both),
and :mod:`..obs.core` stamps it onto every recorder event and span line
as a top-level ``trace`` object (recorder schema v2).

**Bitwise inertness**: the plane flag here is flipped only by
``obs.enable`` / ``obs.disable``.  While the obs plane is off every
derivation helper returns ``None`` — no hashing happens, no context is
ever current, no ``trace`` key reaches a wire header or a recorder
line, so a disabled run is structurally identical to pre-tracing code.

This module is import-leaf (stdlib only; never imports ``obs.core``) so
the facade can re-export it without cycles.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import NamedTuple, Optional

__all__ = [
    "TraceContext", "current", "derive_span_id", "derive_trace_id",
    "set_plane", "trace_for_request", "trace_from_wire", "trace_scope",
    "trace_to_wire",
]


class TraceContext(NamedTuple):
    """One causal segment of one request's fleet-wide timeline."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id is not None:
            d["parent_id"] = self.parent_id
        return d


# flipped by obs.core.enable/disable (under the obs state lock); a plain
# bool read is atomic under the GIL and the OFF value makes every helper
# below an early-return no-op
_PLANE_ENABLED = False

_TLS = threading.local()


def set_plane(enabled: bool) -> None:
    """Gate the trace plane (called by ``obs.enable``/``obs.disable``
    only — never by library code; the obs-inert lint enforces that)."""
    global _PLANE_ENABLED
    _PLANE_ENABLED = bool(enabled)
    if not enabled:
        _TLS.ctx = None


def plane_enabled() -> bool:
    return _PLANE_ENABLED


def derive_trace_id(request_id: str) -> str:
    """The request's fleet-wide trace id: pure function of the request
    id, so every process derives the same one with no coordination."""
    return hashlib.sha256(
        f"ststpu-trace:{request_id}".encode()).hexdigest()[:16]


def derive_span_id(trace_id: str, site: str) -> str:
    """A causal segment's id within a trace: pure function of (trace,
    site), so a failover re-dispatch resumes the SAME segment id."""
    return hashlib.sha256(f"{trace_id}:{site}".encode()).hexdigest()[:16]


def current() -> Optional[TraceContext]:
    """This thread's active trace context (None when no trace is open
    or the plane is disabled)."""
    return getattr(_TLS, "ctx", None)


@contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    """Make ``ctx`` the thread's active trace context for the block
    (restoring the prior one on exit).  ``trace_scope(None)`` is the
    documented cross-thread hop spelling: a worker re-establishing a
    caller that had no trace open simply clears its own."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


def trace_for_request(request_id: Optional[str], site: str = "client",
                      parent_id: Optional[str] = None
                      ) -> Optional[TraceContext]:
    """Open (derive) the trace context for one request at one causal
    site.  Returns ``None`` — no hashing, no context — while the obs
    plane is disabled, which is what keeps disabled runs bitwise
    identical to pre-tracing code."""
    if not _PLANE_ENABLED or not request_id:
        return None
    tid = derive_trace_id(str(request_id))
    return TraceContext(tid, derive_span_id(tid, site), parent_id)


def trace_to_wire(ctx: Optional[TraceContext]) -> Optional[dict]:
    """The header-dict spelling a trace context rides the wire in
    (``encode_msg`` canonicalizes the header, so this stays a plain
    sorted-key-safe dict)."""
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def trace_from_wire(header: dict, site: str = "server"
                    ) -> Optional[TraceContext]:
    """Continue a wire-carried trace on the callee side: the callee's
    segment id is derived from (trace, site) and the caller's span id
    becomes the parent link.  Absent/malformed trace headers — and a
    disabled plane — yield ``None`` (old clients keep working)."""
    if not _PLANE_ENABLED:
        return None
    w = header.get("trace") if isinstance(header, dict) else None
    if not isinstance(w, dict):
        return None
    tid, parent = w.get("trace_id"), w.get("span_id")
    if not isinstance(tid, str) or not tid:
        return None
    return TraceContext(tid, derive_span_id(tid, site),
                        parent if isinstance(parent, str) else None)
