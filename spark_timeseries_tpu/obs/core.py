"""Telemetry plane state: spans, the registry, the recorder, failure dumps.

One process-global plane, **off by default**: every entry point checks a
single boolean and returns a shared no-op object when disabled, so the
instrumented hot paths (chunk dispatch, ladder rungs, journal commits —
never per-row work) pay one attribute load and one truthiness test.  There
is deliberately no ambient "maybe enabled" middle state: ``enable()``
builds a fresh registry + recorder under a new run id, ``disable()`` emits
a final metrics snapshot and tears both down, and nothing instrumented can
alter what a fit computes — telemetry observes timings and counts, never
arrays (the bitwise-invariance contract ``tests/test_obs.py`` enforces).

Spans nest per thread (the watchdog dispatches fits on worker threads, and
a worker's spans must not splice into the driver thread's stack) and
measure wall clock plus process CPU time.  ``first_dispatch()`` lets the
chunk driver label the first dispatch of each (fit, shape) pair as
``compile+execute`` — in JAX the first call of a shape pays trace+compile,
steady-state calls pay execute only, and conflating the two is the classic
way to misread a cold chunk as a regression.

``profile=True`` additionally wraps every span in a
``jax.profiler.TraceAnnotation`` of the same name, so a
``jax.profiler.trace(...)`` capture shows the exact spans the JSONL
reports — one vocabulary across both tools.
"""

from __future__ import annotations

import functools
import os
import tempfile
import threading
import time
import uuid
from typing import Optional

from . import tracing
from .memory import peak_memory
from .metrics import NULL_METRIC, MetricsRegistry
from .recorder import SCHEMA_VERSION, FlightRecorder

__all__ = [
    "Span",
    "counter",
    "disable",
    "dump_failure",
    "dump_on_failure",
    "emit_metrics",
    "enable",
    "enable_from_env",
    "enabled",
    "event",
    "first_dispatch",
    "gauge",
    "histogram",
    "last_crash_dump",
    "snapshot",
    "span",
    "stream_path",
    "summary",
]


class _State:
    __slots__ = ("enabled", "run_id", "metrics", "recorder", "profile",
                 "crash_dump_dir", "seen_programs", "last_crash",
                 "crash_seq", "last_dumped_error")

    def __init__(self):
        self.enabled = False
        self.run_id = None
        self.metrics = MetricsRegistry()
        self.recorder: Optional[FlightRecorder] = None
        self.profile = False
        self.crash_dump_dir = None
        self.seen_programs = set()
        self.last_crash = None
        self.crash_seq = 0
        self.last_dumped_error = None


_STATE = _State()
_LOCK = threading.RLock()
_TLS = threading.local()


# -- lifecycle ---------------------------------------------------------------


def enabled() -> bool:
    return _STATE.enabled


def enable(jsonl_path: Optional[str] = None, *, ring_size: int = 4096,
           profile: bool = False, crash_dump_dir: Optional[str] = None) -> str:
    """Turn the telemetry plane on under a fresh run id (returned).

    ``jsonl_path``: tee every event to this JSONL file (appended, flushed
    per event) in addition to the in-memory ring; ``ring_size`` bounds the
    ring; ``profile=True`` mirrors spans into ``jax.profiler``
    annotations; ``crash_dump_dir`` overrides where failure dumps land
    (default: the JSONL's directory, else the system temp dir).  Calling
    while already enabled finalizes the previous run first — metrics never
    bleed across runs.
    """
    with _LOCK:
        if _STATE.enabled:
            disable()
        _STATE.run_id = uuid.uuid4().hex[:12]
        _STATE.metrics = MetricsRegistry()
        _STATE.recorder = FlightRecorder(_STATE.run_id, ring_size=ring_size,
                                         jsonl_path=jsonl_path)
        _STATE.profile = bool(profile)
        _STATE.crash_dump_dir = crash_dump_dir
        _STATE.seen_programs = set()
        _STATE.crash_seq = 0
        _STATE.last_crash = None
        _STATE.last_dumped_error = None
        _STATE.enabled = True
        tracing.set_plane(True)
        return _STATE.run_id


def disable() -> None:
    """Finalize the run: emit a closing metrics snapshot, close the
    stream, and return every entry point to its no-op fast path.
    Idempotent — disabling a disabled plane does nothing."""
    with _LOCK:
        if not _STATE.enabled:
            return
        rec = _STATE.recorder
        _STATE.enabled = False  # stop new events before the final snapshot
        tracing.set_plane(False)
        if rec is not None:
            rec.emit({"kind": "metrics", **_STATE.metrics.snapshot()})
            rec.close()
        _STATE.recorder = None
        _STATE.profile = False


def enable_from_env() -> None:
    """Honor ``STSTPU_OBS=1`` (+ ``STSTPU_OBS_JSONL=path``,
    ``STSTPU_OBS_PROFILE=1``) so bench/CI runs opt in without code.

    Runs at package import, so it must never raise: an unusable JSONL
    path (read-only dir, bad mount) degrades to a warning with telemetry
    off rather than breaking ``import spark_timeseries_tpu`` for a
    program that never touches the plane.
    """
    if os.environ.get("STSTPU_OBS", "").lower() not in ("1", "true", "on",
                                                        "yes"):
        return
    try:
        enable(os.environ.get("STSTPU_OBS_JSONL") or None,
               profile=os.environ.get("STSTPU_OBS_PROFILE", "") == "1")
    except Exception as e:  # noqa: BLE001 - telemetry must not break import
        import warnings

        _STATE.enabled = False
        tracing.set_plane(False)
        warnings.warn(f"STSTPU_OBS=1 but enabling telemetry failed "
                      f"({type(e).__name__}: {e}); continuing with the "
                      "plane disabled", stacklevel=2)


# -- metrics / events --------------------------------------------------------


def counter(name: str):
    st = _STATE
    return st.metrics.counter(name) if st.enabled else NULL_METRIC


def gauge(name: str):
    st = _STATE
    return st.metrics.gauge(name) if st.enabled else NULL_METRIC


def histogram(name: str):
    st = _STATE
    return st.metrics.histogram(name) if st.enabled else NULL_METRIC


def event(name: str, **attrs) -> None:
    """Record a point event in the ring (and JSONL stream when configured)."""
    st = _STATE
    rec = st.recorder  # local capture: a concurrent disable() nulls the
    if st.enabled and rec is not None:  # attribute between check and use
        ev = {"kind": "event", "name": name}
        if attrs:
            ev["attrs"] = attrs
        ctx = tracing.current()
        if ctx is not None:
            ev["trace"] = ctx.to_dict()
        rec.emit(ev)


def snapshot() -> Optional[dict]:
    """Current metrics snapshot, or None when disabled."""
    st = _STATE
    return st.metrics.snapshot() if st.enabled else None


def emit_metrics() -> None:
    """Append a metrics-snapshot line to the event stream (end of a fit)."""
    st = _STATE
    rec = st.recorder
    if st.enabled and rec is not None:
        rec.emit({"kind": "metrics", **st.metrics.snapshot()})


def stream_path() -> Optional[str]:
    """The enabled run's JSONL stream path (None when disabled or when
    the recorder is ring-only) — sidecar artifacts (the client's clock
    journal) land NEXT TO the stream, and this is how they find it."""
    st = _STATE
    rec = st.recorder  # local capture vs a concurrent disable()
    if not st.enabled or rec is None:
        return None
    return rec.jsonl_path


def first_dispatch(key) -> bool:
    """True exactly once per ``key`` per run — the chunk driver keys on
    (fit identity, chunk shape, dtype) to tag trace+compile dispatches."""
    st = _STATE
    if not st.enabled:
        return False
    with _LOCK:
        if key in st.seen_programs:
            return False
        st.seen_programs.add(key)
        return True


# -- spans -------------------------------------------------------------------


class Span:
    """A closed wall/process-time measurement, recorded at ``__exit__``.

    After the block, ``wall_s`` / ``process_s`` hold the measured times —
    instrumented drivers read them to embed per-chunk numbers in result
    metadata without re-measuring.
    """

    __slots__ = ("name", "attrs", "t0", "wall_s", "process_s", "depth",
                 "_p0", "_ts0", "_ann")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.wall_s = None
        self.process_s = None
        self.depth = 0
        self._ann = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self.depth = len(stack)
        stack.append(self)
        if _STATE.profile:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 - profiling is best-effort
                self._ann = None
        self._ts0 = time.time()
        self._p0 = time.process_time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.wall_s = time.perf_counter() - self.t0
        self.process_s = time.process_time() - self._p0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
        stack = getattr(_TLS, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:  # out-of-order exit: stay consistent
            stack.remove(self)
        st = _STATE
        rec = st.recorder  # local capture: disable() may null it between
        if st.enabled and rec is not None:  # the check and the emit
            ev = {"kind": "span", "name": self.name, "t0": self._ts0,
                  "wall_s": round(self.wall_s, 6),
                  "process_s": round(self.process_s, 6), "depth": self.depth}
            if self.attrs:
                ev["attrs"] = self.attrs
            if exc_type is not None:
                ev["error"] = exc_type.__name__
            ctx = tracing.current()
            if ctx is not None:
                ev["trace"] = ctx.to_dict()
            rec.emit(ev)
            st.metrics.histogram(f"span.{self.name}").observe(self.wall_s)
        return False


class _NullSpan:
    """Disabled-path span: one shared instance, every method a no-op."""

    __slots__ = ()
    wall_s = None
    process_s = None
    depth = 0

    def set(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def span(name: str, **attrs):
    """Open a nested timing span: ``with obs.span("chunk", lo=0): ...``.

    Disabled plane -> the shared no-op span (no allocation beyond the
    kwargs dict at the call site)."""
    if not _STATE.enabled:
        return NULL_SPAN
    return Span(name, attrs)


# -- run summary / failure dumps --------------------------------------------


def summary(counters_since: Optional[dict] = None, **extra) -> Optional[dict]:
    """The per-fit telemetry block embedded in journal manifests and
    ``ResilientFitResult.meta["telemetry"]``; None when disabled.

    Always carries a non-null ``peak_memory`` on any working interpreter
    (device HBM when the backend reports it, host peak RSS otherwise —
    ``obs.memory.peak_memory``), the metric snapshot, and whatever
    driver-level ``extra`` the instrumented caller adds (per-chunk span
    rows, resume accounting).  ``counters_since`` is a counter baseline
    (a prior snapshot's ``counters`` map): counters are then reported as
    DELTAS from it, so one ``enable()`` spanning several fits yields
    per-fit counts instead of attributing fit A's failures to fit B's
    manifest.  Gauges and histograms stay run-cumulative (a peak or a
    latency distribution has no meaningful subtraction).
    """
    st = _STATE
    if not st.enabled:
        return None
    pm = peak_memory()
    if pm.bytes is not None:
        st.metrics.gauge("memory.peak_bytes").max(pm.bytes)
        st.metrics.gauge("memory.source").set(pm.source)
    snap = st.metrics.snapshot()
    if counters_since:
        snap["counters"] = {k: v - counters_since.get(k, 0)
                            for k, v in snap["counters"].items()}
    rec = st.recorder  # local capture vs a concurrent disable()
    out = {
        "schema": SCHEMA_VERSION,
        "run_id": st.run_id,
        "jsonl_path": rec.jsonl_path if rec else None,
        "events_recorded": rec.events_emitted if rec else 0,
        "peak_memory": {"bytes": pm.bytes, "source": pm.source,
                        # present only when a host-resident walk staged
                        # through a pool — the disabled-path/no-pool
                        # summary stays byte-identical to pre-ISSUE-7
                        **({"staging_pool_bytes": pm.staging_pool_bytes}
                           if pm.staging_pool_bytes is not None else {})},
        **snap,
    }
    out.update(extra)
    return out


def dump_failure(context: str, error: Optional[BaseException] = None
                 ) -> Optional[str]:
    """Dump the flight-recorder tail for a failed fit; returns the path.

    Best-effort by contract: any internal failure is swallowed (the
    original fit exception must propagate undisturbed), and the same
    exception object is dumped at most once even when several instrumented
    layers (resilient_fit inside fit_chunked inside panel.fit) unwind
    through their own dump hooks.
    """
    st = _STATE
    rec = st.recorder  # local capture vs a concurrent disable()
    if not st.enabled or rec is None:
        return None
    try:
        with _LOCK:
            if error is not None and st.last_dumped_error is not None \
                    and st.last_dumped_error() is error:
                return st.last_crash
            st.crash_seq += 1
            seq = st.crash_seq
        d = st.crash_dump_dir
        if d is None and rec.jsonl_path:
            d = os.path.dirname(os.path.abspath(rec.jsonl_path))
        if d is None:
            d = tempfile.gettempdir()
        path = os.path.join(d, f"obs-crash-{st.run_id}-{seq:02d}.jsonl")
        closing = [
            {"kind": "event", "name": "fit.failure", "ts": time.time(),
             "attrs": {"context": context,
                       "error": (f"{type(error).__name__}: {error}"[:300]
                                 if error is not None else None)}},
            {"kind": "metrics", "ts": time.time(), **st.metrics.snapshot()},
        ]
        rec.emit(closing[0])
        rec.dump(path, extra_events=closing[1:])
        with _LOCK:
            st.last_crash = path
            if error is not None:
                import weakref

                try:
                    st.last_dumped_error = weakref.ref(error)
                except TypeError:  # some exceptions are not weakref-able
                    st.last_dumped_error = None
        return path
    except Exception:  # noqa: BLE001 - telemetry must never mask the fit error
        return None


def last_crash_dump() -> Optional[str]:
    """Path of the most recent failure dump this run, or None."""
    return _STATE.last_crash


def dump_on_failure(context: str, unless=None):
    """Decorator: dump the recorder tail when the wrapped fit raises.

    Zero-cost when disabled (the enabled check runs before any try frame
    matters); the exception always re-raises unchanged.  ``unless`` is a
    predicate on the exception that SKIPS the dump — a caller above may
    treat the error as recoverable (``resilient_fit`` passes the
    RESOURCE_EXHAUSTED check: the chunk driver's backoff handles those,
    and a successful run must not leave crash dumps behind).
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if not _STATE.enabled:
                return fn(*args, **kwargs)
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                if unless is None or not unless(e):
                    dump_failure(context, e)
                raise

        return wrapped

    return deco
