"""Peak-memory probe: device ``memory_stats()`` with a host-RSS fallback.

``BENCH_r05.json`` shipped ``northstar_1m.peak_hbm_bytes: null`` whenever
the run landed on a backend whose devices do not implement
``memory_stats()`` (CPU, some GPU builds) — the reading silently vanished
exactly where operators develop and CI runs.  This probe never returns
null on a working interpreter: it prefers the device allocator's
``peak_bytes_in_use`` (TPU HBM — the number capacity planning wants) and
falls back to the process's peak resident set via ``resource.getrusage``
(the closest host-side analog), always reporting WHICH source produced
the number so a dashboard cannot mistake host RSS for HBM.

Host-resident chunk walks (ISSUE 7) stage their H2D copies through
reusable staging-pool buffers (``reliability.source.StagingPool``); those
pools :func:`register_staging_pool` themselves here, and the probe
reports their combined peak host footprint as ``staging_pool_bytes`` next
to the device/RSS reading — an oversubscribed run's manifest then carries
both the device peak AND the staging RAM that made it possible, instead
of undercounting the job's real footprint.
"""

from __future__ import annotations

import sys
import weakref
from typing import NamedTuple, Optional

__all__ = ["PeakMemory", "peak_memory", "register_staging_pool"]

# staging pools currently alive in this process (weak: a pool's lifetime
# belongs to its ChunkSource, never to the probe).  The lock covers both
# registration and iteration: the probe runs on committer worker threads
# while another thread may be constructing a source, and an unguarded
# WeakSet walk would raise "set changed size during iteration" out of a
# diagnostics-only reading.
import threading as _threading

_staging_pools: "weakref.WeakSet" = weakref.WeakSet()
_staging_pools_mu = _threading.Lock()

# lock-discipline contract (tools/lint lock-map, module-level form):
# registration (source construction, any thread) vs iteration (the
# probe, committer workers) both hold the lock.
_PROTECTED_BY_ = {"_staging_pools": "_staging_pools_mu"}


def register_staging_pool(pool) -> None:
    """Track a staging pool so :func:`peak_memory` reports its bytes.

    ``pool`` must expose ``peak_host_bytes`` (an int attribute); the
    registry holds it weakly.
    """
    with _staging_pools_mu:
        _staging_pools.add(pool)


def _staging_pool_peak() -> Optional[int]:
    with _staging_pools_mu:
        pools = list(_staging_pools)
    total = 0
    for p in pools:
        try:
            total += int(p.peak_host_bytes)
        except Exception:  # noqa: BLE001 - diagnostics only
            continue
    return total or None


class PeakMemory(NamedTuple):
    """A peak-memory reading and the probe that produced it."""

    bytes: Optional[int]  # None only when every probe failed
    source: str  # "device" | "host_rss" | "unavailable"
    # combined peak host bytes of registered H2D staging pools (None when
    # no host-resident walk ran) — reported alongside, never folded into
    # ``bytes``: staging RAM is host memory regardless of ``source``
    staging_pool_bytes: Optional[int] = None


def _device_peak() -> Optional[int]:
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 - diagnostics only, never fail the fit
        return None
    peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
    return int(peak) if peak else None


def _host_peak_rss() -> Optional[int]:
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:  # noqa: BLE001 - e.g. no resource module (Windows)
        return None
    if not peak:
        return None
    # ru_maxrss is KiB on Linux, bytes on macOS
    return int(peak) if sys.platform == "darwin" else int(peak) * 1024


def peak_memory() -> PeakMemory:
    """Best available peak-memory reading (see module docstring).

    On backends with a real device allocator the reading is peak HBM; on
    CPU it degrades to host peak RSS rather than ``None`` — the source
    field says which, and consumers must label accordingly.
    """
    sp = _staging_pool_peak()
    b = _device_peak()
    if b is not None:
        return PeakMemory(b, "device", sp)
    b = _host_peak_rss()
    if b is not None:
        return PeakMemory(b, "host_rss", sp)
    return PeakMemory(None, "unavailable", sp)
