"""Peak-memory probe: device ``memory_stats()`` with a host-RSS fallback.

``BENCH_r05.json`` shipped ``northstar_1m.peak_hbm_bytes: null`` whenever
the run landed on a backend whose devices do not implement
``memory_stats()`` (CPU, some GPU builds) — the reading silently vanished
exactly where operators develop and CI runs.  This probe never returns
null on a working interpreter: it prefers the device allocator's
``peak_bytes_in_use`` (TPU HBM — the number capacity planning wants) and
falls back to the process's peak resident set via ``resource.getrusage``
(the closest host-side analog), always reporting WHICH source produced
the number so a dashboard cannot mistake host RSS for HBM.
"""

from __future__ import annotations

import sys
from typing import NamedTuple, Optional

__all__ = ["PeakMemory", "peak_memory"]


class PeakMemory(NamedTuple):
    """A peak-memory reading and the probe that produced it."""

    bytes: Optional[int]  # None only when every probe failed
    source: str  # "device" | "host_rss" | "unavailable"


def _device_peak() -> Optional[int]:
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 - diagnostics only, never fail the fit
        return None
    peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
    return int(peak) if peak else None


def _host_peak_rss() -> Optional[int]:
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:  # noqa: BLE001 - e.g. no resource module (Windows)
        return None
    if not peak:
        return None
    # ru_maxrss is KiB on Linux, bytes on macOS
    return int(peak) if sys.platform == "darwin" else int(peak) * 1024


def peak_memory() -> PeakMemory:
    """Best available peak-memory reading (see module docstring).

    On backends with a real device allocator the reading is peak HBM; on
    CPU it degrades to host peak RSS rather than ``None`` — the source
    field says which, and consumers must label accordingly.
    """
    b = _device_peak()
    if b is not None:
        return PeakMemory(b, "device")
    b = _host_peak_rss()
    if b is not None:
        return PeakMemory(b, "host_rss")
    return PeakMemory(None, "unavailable")
