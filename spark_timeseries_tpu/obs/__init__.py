"""Runtime telemetry plane (ISSUE 3): spans, metrics, flight recorder.

The reference got its observability for free from Spark — per-task
metrics, the event log, and a web UI made every job's stragglers, retries,
and memory pressure visible.  The TPU rebuild re-created Spark's
*resilience* (retry ladder, chunk journal, watchdog) but a million-series
fit was still a black box between "started" and the final status counts.
This package is the missing plane, zero-dependency and **off by default**
— when disabled every call returns a shared no-op object, adds no events,
and leaves fit results bitwise-identical to the uninstrumented code:

- :mod:`.core` — nested wall/process-time **spans**
  (``obs.span("chunk", lo=...)``), first-dispatch tagging that separates
  trace+compile time from steady-state execute time, run summaries, and
  failure dumps; ``profile=True`` mirrors spans into ``jax.profiler``
  annotations.
- :mod:`.metrics` — the **registry** of counters / gauges / histograms
  the instrumented paths feed: ladder-rung counts per ``FitStatus``,
  sanitizer actions, OOM backoff halvings, watchdog timeouts, journal
  commit latency, ``map_series`` compiled-kernel cache hits/misses,
  peak-memory gauges.
- :mod:`.recorder` — the bounded ring-buffer **flight recorder**: every
  span/event lands in a ring (and, when enabled with a path, a flushed
  JSONL stream ``tools/obs_report.py`` renders), and any fit failure
  dumps the tail for post-mortems.
- :mod:`.memory` — peak-memory probe: device ``memory_stats()`` with a
  host peak-RSS fallback, so the reading is never null on CPU.
- :mod:`.tracing` — fleet-wide distributed tracing (ISSUE 18): trace
  contexts derived DETERMINISTICALLY from content-derived request ids
  (never uuid4), carried on a thread-local, ridden across the wire in
  the serving header, and stamped onto every recorder line as a
  top-level ``trace`` object (schema v2) so
  ``tools/obs_report.py --fleet/--trace`` reassembles one causal
  timeline per request across replicas, retries, and failovers.
- :mod:`.promsink` — streaming Prometheus-textfile sink (ISSUE 12): the
  registry snapshot (+ caller gauges) rendered to the node-exporter
  textfile-collector format with atomic replace, so a RESIDENT serving
  process (``serving.FitServer(prom_path=...)``) is scrapeable mid-run;
  ``validate_textfile`` is the ``obs_report --check --prom`` gate that
  keeps renamed metrics from silently vanishing off dashboards.

Usage::

    from spark_timeseries_tpu import obs
    obs.enable("run.jsonl")           # or STSTPU_OBS=1 in the environment
    res = panel.fit("arima", order=(1, 1, 1), chunk_rows=131_072,
                    checkpoint_dir="/ckpt/job42")
    res.meta["telemetry"]             # per-chunk spans, counters, peak mem
    obs.disable()                     # final metrics snapshot -> JSONL

Instrumented surfaces: ``reliability.fit_chunked`` / ``resilient_fit`` /
``sanitize`` / ``journal`` / ``watchdog`` / the pipelined ``committer``
(queue-depth gauge, per-commit ``commit.overlap`` spans, hidden-commit
counter), ``TimeSeriesPanel.fit`` / ``map_series``, the compat
``fit_model`` wrappers, ``utils.optim``'s straggler-compaction stage, the
time-sharded ``ops.seqparallel`` ``sp_*_fit`` entry points (``sp_fit``
spans with compile/execute first-dispatch tagging), and
``parallel.mesh.shard_series``.

Elastic lane supervision (ISSUE 11, ``reliability.plan.LaneSupervisor``)
reports its whole lifecycle here: a per-lane health gauge
``lane.state.<shard>`` (``active`` / ``idle`` / ``retrying`` /
``quarantined`` / ``done`` / ``stopped``), counters ``lane.retry`` /
``lane.quarantine`` / ``lane.steal`` / ``lane.rebalance`` (spans moved
between lanes), and shard-tagged events ``lane.retry`` /
``lane.quarantine`` / ``lane.steal`` that ``tools/obs_report.py`` renders
inside each lane's timeline row (with a degraded-run total in the
header).
"""

from . import core, memory, metrics, promsink, recorder, tracing
from .core import (NULL_SPAN, Span, counter, disable, dump_failure,
                   dump_on_failure, emit_metrics, enable, enable_from_env,
                   enabled, event, first_dispatch, gauge, histogram,
                   last_crash_dump, snapshot, span, stream_path, summary)
from .memory import PeakMemory, peak_memory, register_staging_pool
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .promsink import PromTextfileSink
from .recorder import SCHEMA_VERSION, FlightRecorder
from .tracing import (TraceContext, trace_for_request, trace_from_wire,
                      trace_scope, trace_to_wire)
from .tracing import current as current_trace

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NULL_SPAN",
    "PeakMemory",
    "PromTextfileSink",
    "SCHEMA_VERSION",
    "Span",
    "TraceContext",
    "core",
    "counter",
    "current_trace",
    "disable",
    "dump_failure",
    "dump_on_failure",
    "emit_metrics",
    "enable",
    "enable_from_env",
    "enabled",
    "event",
    "first_dispatch",
    "gauge",
    "histogram",
    "last_crash_dump",
    "memory",
    "metrics",
    "peak_memory",
    "promsink",
    "recorder",
    "register_staging_pool",
    "snapshot",
    "span",
    "stream_path",
    "summary",
    "trace_for_request",
    "trace_from_wire",
    "trace_scope",
    "trace_to_wire",
    "tracing",
]

# bench / CI opt-in without code changes (no-op unless STSTPU_OBS=1)
enable_from_env()
