"""Streaming Prometheus-textfile sink for the obs plane (ISSUE 12).

The flight recorder's JSONL stream is a post-mortem artifact; a RESIDENT
server needs its metrics scrapeable WHILE it runs.  The standard
zero-dependency bridge is the node-exporter *textfile collector*: a
process writes ``<name>.prom`` files in the text exposition format, the
exporter scrapes the directory.  :class:`PromTextfileSink` renders the
obs registry snapshot (counters / gauges / histograms) plus any
caller-supplied gauge map into that format and replaces the target file
ATOMICALLY (tmp + ``os.replace``), so a scraper never reads a torn file
— the journal's manifest discipline applied to metrics.

Name mapping (the contract ``validate_textfile`` enforces so a renamed
counter cannot silently vanish from dashboards):

- every metric name is prefixed ``ststpu_`` and sanitized to the
  Prometheus grammar (``[a-zA-Z_:][a-zA-Z0-9_:]*``; dots and dashes
  become underscores);
- counters keep their name (``# TYPE ... counter``);
- numeric gauges keep their name (``# TYPE ... gauge``); string-valued
  gauges (e.g. ``memory.source``) become ``<name>_info{value="..."} 1``;
- histograms emit ``<name>_count`` / ``<name>_sum`` (counter-style) and
  ``<name>_min`` / ``<name>_max`` / ``<name>_last`` gauges.

``tools/obs_report.py --check --prom FILE`` runs :func:`validate_textfile`
against the event stream's final metrics snapshot: the file must parse,
every family must be well-formed, and every registry metric must be
present under its mapped name.
"""

from __future__ import annotations

import math
import os
import re
import threading
from typing import Dict, Iterable, Optional

__all__ = ["PromTextfileSink", "expected_families", "prom_name",
           "render_textfile", "validate_textfile"]

PREFIX = "ststpu"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>\S+)(\s+\d+)?$")
_LABELS = re.compile(r'^\{\s*([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
                     r'(\s*,\s*[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*'
                     r'\s*,?\s*)?\}$')


def prom_name(name: str, prefix: str = PREFIX) -> str:
    """Map an obs metric name onto the Prometheus grammar."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if prefix:
        out = f"{prefix}_{out}"
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def _fmt(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def expected_families(snapshot: Optional[dict],
                      extra: Optional[Dict[str, float]] = None,
                      prefix: str = PREFIX) -> Dict[str, str]:
    """``{family_name: type}`` the sink MUST emit for this registry
    snapshot (+ caller gauges) — the checkable contract between the
    registry and the dashboards."""
    fams: Dict[str, str] = {}
    snap = snapshot or {}
    for name in (snap.get("counters") or {}):
        fams[prom_name(name, prefix)] = "counter"
    for name, v in (snap.get("gauges") or {}).items():
        base = prom_name(name, prefix)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            fams[base] = "gauge"
        elif v is not None:
            fams[base + "_info"] = "gauge"
    for name in (snap.get("histograms") or {}):
        base = prom_name(name, prefix)
        # _min/_max/_last are deliberately NOT required: an empty
        # histogram (count 0) has no extrema to export
        fams[base + "_count"] = "counter"
        fams[base + "_sum"] = "counter"
    for name in (extra or {}):
        fams[prom_name(name, prefix)] = "gauge"
    return fams


def render_textfile(snapshot: Optional[dict],
                    extra: Optional[Dict[str, float]] = None,
                    prefix: str = PREFIX) -> str:
    """The exposition text for a registry snapshot (+ extra gauges)."""
    lines = []
    emitted: set = set()

    def family(name: str, kind: str, samples: Iterable[tuple]) -> None:
        # one declaration per family: a caller gauge that shadows a
        # registry metric of the same mapped name is skipped (the obs
        # plane is authoritative; the server refreshes its registry
        # gauges before each sink write)
        if name in emitted:
            return
        emitted.add(name)
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in samples:
            lines.append(f"{name}{labels} {value}")

    snap = snapshot or {}
    for name, v in sorted((snap.get("counters") or {}).items()):
        family(prom_name(name, prefix), "counter", [("", _fmt(v))])
    for name, v in sorted((snap.get("gauges") or {}).items()):
        base = prom_name(name, prefix)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            family(base, "gauge", [("", _fmt(v))])
        elif v is not None:
            family(base + "_info", "gauge",
                   [('{value="%s"}' % _esc(v), "1")])
    for name, h in sorted((snap.get("histograms") or {}).items()):
        base = prom_name(name, prefix)
        h = h or {}
        family(base + "_count", "counter", [("", _fmt(h.get("count", 0)))])
        family(base + "_sum", "counter", [("", _fmt(h.get("sum", 0.0)))])
        for suffix, key in (("_min", "min"), ("_max", "max"),
                            ("_last", "last")):
            if h.get(key) is not None:
                family(base + suffix, "gauge", [("", _fmt(h[key]))])
    for name, v in sorted((extra or {}).items()):
        family(prom_name(name, prefix), "gauge", [("", _fmt(v))])
    return "\n".join(lines) + ("\n" if lines else "")


class PromTextfileSink:
    """Write the current metrics to ``path`` atomically on every
    :meth:`write` — the resident server calls it after each batch (and on
    idle ticks), so the textfile always reflects a recent state and never
    a torn one."""

    # lock-discipline contract (tools/lint lock-map): the serve loop and
    # forced final writes (stop()) may overlap; one writer at a time.
    _protected_by_ = {"writes": "_lock"}

    def __init__(self, path: str, prefix: str = PREFIX):
        self.path = os.path.abspath(path)
        self.prefix = prefix
        self.writes = 0
        self._lock = threading.Lock()
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def write(self, snapshot: Optional[dict] = None,
              extra: Optional[Dict[str, float]] = None) -> str:
        """Render and atomically replace the textfile.  ``snapshot``
        defaults to the live obs registry (None when the plane is
        disabled — the caller's ``extra`` gauges still export, so a
        server without the obs plane on remains scrapeable)."""
        if snapshot is None:
            from . import core

            snapshot = core.snapshot()
        extra = dict(extra or {})
        with self._lock:
            self.writes += 1
            extra.setdefault("sink_writes_total", float(self.writes))
            text = render_textfile(snapshot, extra, self.prefix)
            tmp = f"{self.path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, self.path)
        return self.path


def validate_textfile(path: str, snapshot: Optional[dict] = None,
                      prefix: str = PREFIX) -> list:
    """Validate a sink textfile; returns a list of error strings (empty =
    valid).

    Checks (the ``obs_report --check --prom`` gate):

    - the file parses line-by-line as text exposition format (``# TYPE``
      headers, samples ``name{labels} value``, valid names/labels/values);
    - every sample belongs to a declared ``# TYPE`` family;
    - with ``snapshot`` (a registry dump — ``obs.snapshot()`` or the
      event stream's final ``metrics`` line): every registry metric's
      mapped family is PRESENT in the file, so a renamed or dropped
      counter fails the gate instead of silently vanishing from
      dashboards.
    """
    errors: list = []
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"cannot read {path}: {e}"]
    declared: Dict[str, str] = {}
    seen: set = set()
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4:
                    errors.append(f"line {i}: malformed TYPE line: {line!r}")
                    continue
                _, _, fam, kind = parts
                if not _NAME_OK.match(fam):
                    errors.append(f"line {i}: invalid family name {fam!r}")
                if kind not in ("counter", "gauge", "histogram", "summary",
                                "untyped"):
                    errors.append(f"line {i}: invalid family type {kind!r}")
                if fam in declared:
                    errors.append(f"line {i}: family {fam!r} declared twice")
                declared[fam] = kind
            continue  # HELP/comments pass through
        m = _SAMPLE.match(line.strip())
        if not m:
            errors.append(f"line {i}: not a valid sample: {line!r}")
            continue
        name, labels, value = (m.group("name"), m.group("labels"),
                               m.group("value"))
        if labels and not _LABELS.match(labels):
            errors.append(f"line {i}: malformed labels {labels!r}")
        try:
            float(value.replace("+Inf", "inf").replace("-Inf", "-inf"))
        except ValueError:
            errors.append(f"line {i}: non-numeric sample value {value!r}")
        fam = name
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[: -len(suffix)] in declared:
                fam = name[: -len(suffix)]
                break
        if fam not in declared:
            errors.append(f"line {i}: sample {name!r} has no TYPE "
                          "declaration")
        seen.add(name)
    if snapshot is not None:
        for fam, kind in expected_families(snapshot,
                                           prefix=prefix).items():
            if fam not in seen and fam not in declared:
                errors.append(
                    f"registry metric missing from textfile: {fam} "
                    f"({kind}) — a renamed/dropped metric would silently "
                    "vanish from dashboards")
            elif declared.get(fam) not in (kind, None):
                errors.append(f"family {fam}: textfile type "
                              f"{declared.get(fam)!r} != registry-derived "
                              f"type {kind!r}")
    return errors
