"""Flight recorder: bounded ring of structured events + JSONL stream.

The Spark event log (``spark.eventLog.enabled``) wrote every job/stage/task
transition to a file the history server replayed after a crash.  The
rebuild's analog is two-layered:

- a **ring buffer** of the last ``ring_size`` events always held in memory
  (cheap enough to leave on for long jobs — old events fall off the back),
  dumped to disk when a fit fails so the post-mortem starts with the tail
  of what the process was doing; and
- an optional **JSONL stream**: when the telemetry plane is enabled with a
  path, every event is also appended (and flushed — a SIGKILL loses at most
  the current line) to a file ``tools/obs_report.py`` renders.

One event = one flat JSON object.  Schema (``SCHEMA_VERSION``, v2):

- every line: ``ts`` (epoch seconds) and ``kind`` in
  ``meta | span | event | metrics``;
- ``meta``: first line of a stream — ``schema``, ``run_id``, ``pid``;
- ``span``: ``name``, ``t0``, ``wall_s``, ``process_s``, ``depth``,
  ``attrs`` (a closed span; emitted at exit);
- ``event``: ``name``, ``attrs`` (a point event: journal commit, OOM
  backoff, watchdog timeout, fit failure);
- ``metrics``: a full registry snapshot (``counters`` / ``gauges`` /
  ``histograms``), emitted at the end of an instrumented fit and on
  disable/dump.

Schema v2 (ISSUE 18) adds an OPTIONAL top-level ``trace`` object on
``span`` and ``event`` lines, stamped by :mod:`.core` whenever a
:mod:`.tracing` context is active on the emitting thread:

- ``trace.trace_id``: 16 lowercase hex chars —
  ``sha256("ststpu-trace:" + request_id)[:16]``, identical in every
  process that handles the request (derivation, not propagation);
- ``trace.span_id``: 16 lowercase hex chars —
  ``sha256(trace_id + ":" + site)[:16]`` for the causal segment
  ("client", "server", "server.batch", ...) the line belongs to;
- ``trace.parent_id`` (optional): the caller segment's ``span_id``
  (the wire header carried it across the hop).

v1 streams (no ``trace`` anywhere) remain readable by every consumer;
``tools/obs_report.py --check`` accepts an absent ``trace`` and FAILS a
malformed one (wrong type, bad id shape) instead of letting it vanish.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

__all__ = ["SCHEMA_VERSION", "FlightRecorder"]

SCHEMA_VERSION = 2


class FlightRecorder:
    """Bounded event ring, optionally teeing every event to a JSONL file."""

    # lock-discipline contract (tools/lint lock-map): every instrumented
    # thread emits; ring, counter, and the teed file handle mutate only
    # under _lock (emit downgrades _file to None on a broken stream).
    _protected_by_ = {
        "_ring": "_lock",
        "events_emitted": "_lock",
        "_file": "_lock",
    }

    def __init__(self, run_id: str, ring_size: int = 4096,
                 jsonl_path: Optional[str] = None):
        self.run_id = run_id
        self.jsonl_path = jsonl_path
        self._ring = collections.deque(maxlen=int(ring_size))
        self._lock = threading.Lock()
        self._file = None
        self.events_emitted = 0
        if jsonl_path:
            d = os.path.dirname(os.path.abspath(jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._file = open(jsonl_path, "a", encoding="utf-8")
        self.emit({"kind": "meta", "schema": SCHEMA_VERSION,
                   "run_id": run_id, "pid": os.getpid()})

    def emit(self, ev: dict) -> None:
        """Record one event (adds ``ts`` when absent; never raises — a
        telemetry write failure must not take down the fit it observes)."""
        ev.setdefault("ts", time.time())
        with self._lock:
            self._ring.append(ev)
            self.events_emitted += 1
            if self._file is not None:
                try:
                    self._file.write(json.dumps(ev, default=repr) + "\n")
                    self._file.flush()
                except (OSError, ValueError):
                    # stream broken (disk full, closed fd): keep the ring
                    self._file = None

    def tail(self, n: Optional[int] = None) -> list:
        with self._lock:
            evs = list(self._ring)
        return evs if n is None else evs[-n:]

    def dump(self, path: str, extra_events: Optional[list] = None) -> str:
        """Write the ring tail (plus any closing events) to ``path``."""
        evs = self.tail()
        if extra_events:
            evs = evs + list(extra_events)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for ev in evs:
                f.write(json.dumps(ev, default=repr) + "\n")
        return path

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
