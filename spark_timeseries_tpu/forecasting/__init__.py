"""Panel-scale forecasting (ROADMAP item 2): the forecast walk, rolling
origin backtest campaigns, and criterion-weighted ensembles.

Three layers over the durable chunk driver:

- :mod:`.walk` — ``forecast_chunked``: per-model forecast kernels run as
  a chunked walk on the ``ExecutionPlan`` via an AUGMENTED panel
  (``[y | params | status | row]``, :mod:`.augment`), so journaling,
  pipelining, prefetch, ``ChunkSource`` streaming, sharding, and elastic
  lanes compose for free and every composition is bitwise-identical to
  the serial in-memory walk.  Fitted params come from memory or straight
  from a fit journal (:mod:`.params` — fit once on disk, forecast many).
  ``intervals=True`` adds Monte-Carlo quantile bands under counter-based
  keys derived from the journal fingerprint (bitwise-reproducible).
- :mod:`.backtest` — ``run_backtest``: an expanding-window refit x
  horizon sweep as ONE journaled campaign, per-window walks warm-started
  from the previous window's journaled params, MAE/RMSE/MAPE/coverage
  into a durable ``backtest_manifest.json`` + metrics shards,
  SIGKILL-resumable to bitwise-identical metrics.
- :mod:`.ensemble` — ``ensemble_forecast``: softmax criterion weights
  over an auto-fit grid's ``[G, B]`` criteria matrix blend member
  forecasts (point + interval); ``temperature=0`` recovers the argmin
  winner bitwise.
"""

from .augment import ColumnBlockSource, augmented_panel
from .backtest import (BACKTEST_MANIFEST, BacktestResult,
                       StaleBacktestError, default_origins, run_backtest)
from .ensemble import (EnsembleForecast, criterion_weights,
                       ensemble_forecast)
from .params import load_auto_members, load_fit_result
from .walk import (ForecastResult, as_result, forecast_chunked,
                   forecast_fit, split_forecast, warmstart_fit)

__all__ = [
    "BACKTEST_MANIFEST",
    "BacktestResult",
    "ColumnBlockSource",
    "EnsembleForecast",
    "ForecastResult",
    "StaleBacktestError",
    "as_result",
    "augmented_panel",
    "criterion_weights",
    "default_origins",
    "ensemble_forecast",
    "forecast_chunked",
    "forecast_fit",
    "load_auto_members",
    "load_fit_result",
    "run_backtest",
    "split_forecast",
    "warmstart_fit",
]
