"""The chunked forecast walk: panel-scale forecasts on the fit driver.

The forecast of a panel is EMBARRASSINGLY parallel — every row's future
depends only on its own history and its own fitted params — so instead
of a new execution engine, the walk reuses ``reliability.fit_chunked``
wholesale: the per-row side data (params, status, row index) is packed
into extra panel columns (:mod:`.augment`), and :func:`forecast_fit` —
an ordinary chunk "fit function" returning a ``FitResult`` whose params
matrix IS the packed ``[point | lo | hi]`` forecast block — rides the
driver.  Journaling (SIGKILL-resume replaying only uncommitted chunks),
pipelined commits, dispatch-ahead prefetch, ``ChunkSource`` streaming,
mesh sharding, and elastic lanes therefore compose with the forecast
path for free, and the composed walks are bitwise-identical to the
serial in-memory walk ON THE SAME CHUNK GRID: the forecast kernels are
row-local vmapped programs with no cross-row coupling, staged chunks are
the same bytes in every residency, shard boundaries land on chunk
boundaries, and the interval sampling keys are counter-based on the
GLOBAL row index (``fold_in(base_key, row)``), never on chunk shape.
(Like the fits, low-order bits can follow the chunk SHAPE — XLA
reduction order inside a row's sigma estimate is batch-size-dependent —
so cross-grid comparisons are value-close, not bitwise; every driver
composition keeps the grid fixed.)

**Status propagation**: a row whose fit did not produce usable params
(status ``DIVERGED``/``EXCLUDED``/``TIMEOUT``, or non-finite params)
forecasts NaN — never garbage — and keeps its fit status in the result;
healthy rows (including ``SANITIZED``/``RETRIED``/``FALLBACK`` rescues)
forecast from their params and keep their provenance code.

**Reproducible intervals**: ``intervals=True`` adds Monte-Carlo
``level``-quantile bands from each model's forward simulation
(:mod:`.kernels`), under a base key derived deterministically from the
augmented panel's JOURNAL FINGERPRINT (or an explicit ``seed``) — the
same panel + params forecast the same bands on every run, resume, chunk
layout, and shard count, bitwise.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.base import FitResult, jit_program
from ..reliability import source as source_mod
from ..reliability.journal import panel_fingerprint
from ..reliability.runner import ResilientFitResult
from ..reliability.status import FitStatus, status_counts
from . import augment, kernels
from .params import load_fit_result

__all__ = ["ForecastResult", "forecast_chunked", "forecast_fit",
           "split_forecast", "warmstart_fit"]


class ForecastResult(NamedTuple):
    """Panel forecast output: rows align with the input panel.

    ``forecast`` is ``[B, horizon]`` point forecasts (NaN for rows whose
    fit was unusable); ``lo``/``hi`` the interval bands (None without
    ``intervals=True``); ``status`` the propagated per-row fit status;
    ``meta`` the walk accounting (``meta["forecast"]`` the forecast
    config, plus everything the chunk driver reports — journal, pipeline
    overlap, shards, source staging).
    """

    forecast: np.ndarray  # [B, horizon]
    lo: Optional[np.ndarray]  # [B, horizon] or None
    hi: Optional[np.ndarray]  # [B, horizon] or None
    status: np.ndarray  # [B] int8 FitStatus
    meta: dict


def split_forecast(pack: np.ndarray, horizon: int, intervals: bool):
    """Unpack a walk's params matrix ``[B, W]`` into (point, lo, hi).

    Tolerates the all-TIMEOUT degenerate pack (the driver synthesizes
    width-1 NaN params when no chunk ever finished)."""
    pack = np.asarray(pack)
    b = pack.shape[0]
    want = horizon * (3 if intervals else 1)
    if pack.shape[1] != want:
        nanmat = np.full((b, horizon), np.nan, pack.dtype)
        return (nanmat, nanmat.copy() if intervals else None,
                nanmat.copy() if intervals else None)
    point = np.array(pack[:, :horizon])
    if not intervals:
        return point, None, None
    return (point, np.array(pack[:, horizon:2 * horizon]),
            np.array(pack[:, 2 * horizon:3 * horizon]))


def forecast_fit(aug, *, forecast_model, horizon, n_time, k,
                 model_kwargs=(), intervals=False, level=0.9,
                 n_samples=256, base_seed=0):
    """The forecast walk's chunk fit function.

    ``aug`` is an augmented-panel chunk (``.augment`` layout); the
    statics select ONE compiled program per configuration
    (``forecast_model`` names the model family — spelled distinctly from
    the serving layer's ``model`` registry-name parameter so the config
    rides ``FitServer.submit`` untouched).  Returns a ``FitResult``
    whose ``params`` is the packed forecast block — which is exactly
    what the journal commits and a resume rehydrates.  Run it through
    ``fit_chunked(..., resilient=False)``: the resilient ladder must
    never "sanitize" a panel whose columns are fitted parameters.
    """
    mk = kernels.normalize_model_kwargs(str(forecast_model),
                                        dict(model_kwargs))
    return _forecast_chunk_program(
        str(forecast_model), mk, int(horizon), int(n_time), int(k),
        bool(intervals), float(level), int(n_samples), int(base_seed),
    )(jnp.asarray(aug))


@jit_program
def _forecast_chunk_program(model, mk, horizon, n_time, k, intervals,
                            level, n_samples, base_seed):
    cfg = dict(mk)
    want_k = kernels.param_width(model, cfg)
    if want_k != k:
        raise ValueError(
            f"model {model!r} with config {cfg} expects {want_k} params "
            f"per row, augmented panel carries {k}")
    point_f = kernels.point_fn(model, cfg, horizon)
    sim_f = (kernels.sim_fn(model, cfg, horizon, n_samples)
             if intervals else None)

    def run(aug):
        y = aug[:, :n_time]
        params = aug[:, n_time:n_time + k]
        status = aug[:, n_time + k].astype(jnp.int8)
        usable = (jnp.all(jnp.isfinite(params), axis=-1)
                  & (status < jnp.int8(FitStatus.DIVERGED)))
        point = jnp.where(usable[:, None], point_f(params, y), jnp.nan)
        blocks = [point]
        if intervals:
            rowidx = aug[:, n_time + k + 1].astype(jnp.int32)
            key0 = jax.random.PRNGKey(base_seed)
            keys = jax.vmap(lambda r: jax.random.fold_in(key0, r))(rowidx)
            paths = sim_f(params, y, keys)  # [B, S, H]
            ql = (1.0 - level) / 2.0
            lo = jnp.quantile(paths, ql, axis=1)
            hi = jnp.quantile(paths, 1.0 - ql, axis=1)
            blocks += [jnp.where(usable[:, None], lo, jnp.nan),
                       jnp.where(usable[:, None], hi, jnp.nan)]
        pack = jnp.concatenate(blocks, axis=1).astype(aug.dtype)
        nll = jnp.where(usable, 0.0, jnp.nan).astype(aug.dtype)
        return FitResult(pack, nll, usable,
                         jnp.zeros(aug.shape[0], jnp.int32), status)

    return run


def warmstart_fit(aug, *, model, n_time, k, model_kwargs=()):
    """Chunk fit function for a WARM-STARTED refit walk (the backtest
    campaign's expanding windows): the augmented panel carries
    ``[y (n_time) | init params (k)]`` and the model fits with
    ``init_params`` taken from the extra columns — per-chunk, so the
    warm start rides any chunking/sharding/streaming, exactly like the
    forecast pack.  Non-finite inits (a failed previous-window row) are
    zeroed, the model's own cold-ish default, mirroring the winners
    refit (``models.auto._refit_basin``).  Run with ``resilient=False``:
    the sanitizer must not touch param columns.
    """
    from ..models import arima as _arima

    cfg = dict(model_kwargs)
    aug = jnp.asarray(aug)
    y = aug[:, :int(n_time)]
    init = aug[:, int(n_time):int(n_time) + int(k)]
    init = jnp.where(jnp.isfinite(init), init, 0.0)
    if model != "arima":
        raise ValueError(
            f"warm-started refits need a fit with init_params= "
            f"(arima family); got {model!r}")
    order = tuple(cfg.pop("order"))
    return _arima.fit(y, order=order, init_params=init, **cfg)


def _derive_base_seed(fingerprint: str) -> int:
    digest = hashlib.sha256(
        ("ststpu-forecast:" + fingerprint).encode()).digest()
    return int.from_bytes(digest[:4], "big") & 0x7FFFFFFF


def forecast_chunked(
    model: str,
    fitted,
    y,
    horizon: int,
    *,
    model_kwargs: Optional[dict] = None,
    status=None,
    intervals: bool = False,
    level: float = 0.9,
    n_samples: int = 256,
    seed: Optional[int] = None,
    chunk_rows: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    shard: bool = False,
    mesh=None,
    sink=None,
    _journal_commit_hook=None,
) -> ForecastResult:
    """Forecast ``horizon`` steps for every row of ``y [B, T]``.

    ``fitted`` supplies the per-row parameters: an in-memory fit result
    (anything with ``params`` [+ ``status``] — ``FitResult``,
    ``ResilientFitResult``, ``TenantFitResult``), a raw ``[B, k]``
    params array, or a STRING path to a fit walk's journal directory
    (fit-once on disk -> forecast-many later: the journal is assembled
    host-side via :func:`.params.load_fit_result`, committed rows byte
    identical to the original walk's output).  ``status`` overrides the
    per-row fit status (default: taken from ``fitted``, or derived from
    params finiteness) and gates NaN propagation.

    ``y`` is a device/host array or any ``ChunkSource`` (the augmented
    panel then STREAMS — an oversubscribed panel forecasts at O(chunk)
    device footprint).  All the chunk driver's knobs ride through —
    ``checkpoint_dir`` journals the walk (forecast shards resume
    bitwise), ``shard=True`` runs one elastic lane per mesh device,
    pipeline/prefetch overlap staging and commits — and every
    composition is bitwise-identical to the serial in-memory walk.

    ``intervals=True`` adds ``level`` Monte-Carlo quantile bands
    (``n_samples`` forward simulations/row) under a base key derived
    from the augmented panel's journal fingerprint (``seed`` overrides),
    so bands are bitwise-reproducible across runs, resumes, shards, and
    residencies on the same chunk grid.
    """
    horizon = int(horizon)
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    mk = kernels.normalize_model_kwargs(model, model_kwargs or {})
    cfg = dict(mk)
    if isinstance(fitted, str):
        fitted = load_fit_result(fitted)
    if hasattr(fitted, "order_index"):
        # an auto-fit selection packs each ROW's params in its own
        # winning order's layout — reading them under one fixed order
        # would forecast finite garbage with status OK for every row
        # whose winner differs (the exact never-garbage violation this
        # walk exists to prevent)
        raise ValueError(
            "an auto-fit selection mixes parameter layouts per row "
            "(each row's winning order); forecast it with "
            "forecasting.ensemble_forecast(auto_root=..., "
            "temperature=0) — per-order walks + a per-row winner "
            "gather — not a single-order forecast")
    if hasattr(fitted, "params"):
        params = np.asarray(fitted.params)
        if status is None:
            status = getattr(fitted, "status", None)
    else:
        params = np.asarray(fitted)
    if params.ndim != 2:
        raise ValueError(f"params must be [rows, k], got {params.shape}")
    k = kernels.param_width(model, cfg)
    if params.shape[1] < k:
        raise ValueError(
            f"model {model!r} with config {cfg} needs {k} params per "
            f"row, fitted carries {params.shape[1]}")
    params = np.ascontiguousarray(params[:, :k])
    st = augment.derive_status(params, status)
    aug, n_time, k = augment.augmented_panel(y, params, st)

    base_seed = 0
    if intervals:
        if seed is not None:
            base_seed = int(seed)
        else:
            fp = (aug.fingerprint()
                  if isinstance(aug, source_mod.ChunkSource)
                  else panel_fingerprint(aug))
            base_seed = _derive_base_seed(fp)

    from ..reliability import fit_chunked

    journal_extra = {"forecast": {
        "model": model, "horizon": int(horizon),
        "n_time": int(n_time), "k": int(k),
        "model_kwargs": {key: (list(v) if isinstance(v, tuple) else v)
                         for key, v in cfg.items()},
        "intervals": bool(intervals),
        "level": float(level) if intervals else None,
        "n_samples": int(n_samples) if intervals else None,
        "base_seed": int(base_seed) if intervals else None,
    }}
    with obs.span("panel.forecast", model=model, horizon=int(horizon),
                  n_series=int(params.shape[0])):
        res = fit_chunked(
            forecast_fit, aug,
            chunk_rows=chunk_rows,
            resilient=False,
            checkpoint_dir=checkpoint_dir, resume=resume,
            chunk_budget_s=chunk_budget_s, job_budget_s=job_budget_s,
            pipeline=pipeline, pipeline_depth=pipeline_depth,
            prefetch_depth=prefetch_depth,
            shard=shard, mesh=mesh, sink=sink,
            journal_extra=journal_extra,
            _journal_commit_hook=_journal_commit_hook,
            # -- the forecast config (all hashed into the journal id) --
            forecast_model=model, horizon=int(horizon),
            n_time=int(n_time), k=int(k), model_kwargs=mk,
            intervals=bool(intervals), level=float(level),
            n_samples=int(n_samples), base_seed=int(base_seed),
        )
    if res.params is None:
        # write-back mode (ISSUE 20): the packed forecasts streamed out
        # as durable output shards under key "params"; read them back at
        # O(chunk) footprint with NpzShardSource(sink_dir, key="params")
        # and split_forecast.  meta["sink"] carries the accounting and
        # meta["status_counts"] the per-row outcome totals.
        meta = dict(res.meta)
        meta["forecast"] = {**journal_extra["forecast"],
                            "status_counts": res.meta["status_counts"]}
        obs.counter("forecast.walks").inc()
        return ForecastResult(None, None, None, None, meta)
    point, lo, hi = split_forecast(res.params, int(horizon),
                                   bool(intervals))
    out_status = np.asarray(res.status, np.int8)
    meta = dict(res.meta)
    meta["forecast"] = {**journal_extra["forecast"],
                        "status_counts": status_counts(out_status)}
    obs.counter("forecast.walks").inc()
    return ForecastResult(point, lo, hi, out_status, meta)


def as_result(res: ResilientFitResult, horizon: int,
              intervals: bool) -> ForecastResult:
    """Wrap a raw forecast-walk fit result (e.g. a serving demux slice)
    into a :class:`ForecastResult`."""
    point, lo, hi = split_forecast(res.params, int(horizon),
                                   bool(intervals))
    return ForecastResult(point, lo, hi,
                          np.asarray(res.status, np.int8),
                          dict(getattr(res, "meta", {}) or {}))
