"""Panel augmentation: per-row side data packed into extra COLUMNS.

The chunk driver (``reliability.fit_chunked``) slices exactly ONE array —
the panel — and hands each chunk to the fit function with no row
coordinates.  Everything a forecast (or warm-started refit) needs per row
beyond the observations therefore rides IN the panel: the augmented
layout is

    ``[ y (n_time) | fitted params (k) | fit status (1) | row index (1) ]``

so a chunk of the augmented panel is self-describing — the forecast
kernel splits it by static column offsets, the journal fingerprints it
(fitted params and statuses are part of the job identity: forecasting
from different params IS a different job), and every driver feature
(pipelining, prefetch, sharding, elastic lanes, ``ChunkSource``
streaming) composes with zero new driver code.

:class:`ColumnBlockSource` is the streaming spelling: a horizontal
composition of column blocks — a (possibly column-sliced) inner
``ChunkSource`` plus host arrays — that reads rows on demand, so an
oversubscribed panel is never materialized to build its augmented twin.
Its content fingerprint matches ``journal.panel_fingerprint`` of the
materialized augmented panel byte for byte, which is what makes
in-memory and source-streamed forecast journals cross-resume.

The row-index column drives the counter-based interval keys
(``jax.random.fold_in(base_key, row)``): a row's sampling key depends
only on its GLOBAL index and the base seed, never on chunk boundaries,
so probabilistic intervals are bitwise-reproducible across chunk sizes,
shards, and resumes.  Indices are stored in the panel dtype — exact up
to 2**24 rows at float32 (guarded loudly) and 2**53 at float64.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

from ..reliability import source as source_mod
from ..reliability.status import FitStatus

__all__ = ["ColumnBlockSource", "augmented_panel", "augmented_host",
           "derive_status", "EXTRA_COLS"]

# status + row-index columns appended after the params block
EXTRA_COLS = 2

# largest exactly-representable consecutive integer per float dtype
_MAX_EXACT_ROWS = {np.dtype(np.float32): 1 << 24,
                   np.dtype(np.float64): 1 << 53}


def derive_status(params: np.ndarray, status=None) -> np.ndarray:
    """Per-row ``FitStatus`` for a bare params matrix: rows with any
    non-finite parameter are DIVERGED (a forecast must never turn NaN
    params into plausible numbers), the rest OK.  An explicit ``status``
    array passes through (validated for length)."""
    b = int(np.asarray(params).shape[0])
    if status is not None:
        st = np.asarray(status, np.int8)
        if st.shape != (b,):
            raise ValueError(
                f"status must be [{b}], got shape {st.shape}")
        return st
    finite = np.isfinite(np.asarray(params)).all(axis=-1)
    return np.where(finite, np.int8(FitStatus.OK),
                    np.int8(FitStatus.DIVERGED)).astype(np.int8)


class ColumnBlockSource(source_mod.ChunkSource):
    """Horizontal composition of column blocks over one row axis.

    ``blocks`` is a sequence of either host ``np.ndarray [B, w]`` blocks
    or ``(source, col_lo, col_hi)`` column windows of an inner
    :class:`~..reliability.source.ChunkSource` (``col_lo``/``col_hi``
    default to the full width).  All blocks share the row count and
    dtype.  Rows are read block-by-block into the caller's buffer; inner
    sources are read through a transient full-width scratch (bounded by
    the chunk size), so disk-backed panels stream without ever
    materializing.
    """

    kind = "columns"

    def __init__(self, blocks: Sequence, *,
                 pool: Optional[source_mod.StagingPool] = None):
        norm = []
        b = None
        dtype = None
        inner_defaults = []
        for blk in blocks:
            if isinstance(blk, tuple):
                src, lo, hi = blk
                lo = 0 if lo is None else int(lo)
                hi = int(src.shape[1]) if hi is None else int(hi)
                if not (0 <= lo < hi <= int(src.shape[1])):
                    raise source_mod.SourceError(
                        f"column window [{lo}, {hi}) outside source width "
                        f"{src.shape[1]}")
                rows, d = int(src.shape[0]), src.dtype
                if src.default_chunk_rows:
                    inner_defaults.append(int(src.default_chunk_rows))
                norm.append(("source", src, lo, hi))
                w = hi - lo
            elif isinstance(blk, source_mod.ChunkSource):
                rows, d = int(blk.shape[0]), blk.dtype
                if blk.default_chunk_rows:
                    inner_defaults.append(int(blk.default_chunk_rows))
                norm.append(("source", blk, 0, int(blk.shape[1])))
                w = int(blk.shape[1])
            else:
                arr = np.ascontiguousarray(blk)
                if arr.ndim != 2:
                    raise source_mod.SourceError(
                        f"host block must be 2-D, got shape {arr.shape}")
                rows, d = arr.shape[0], arr.dtype
                norm.append(("host", arr, 0, arr.shape[1]))
                w = arr.shape[1]
            if b is None:
                b, dtype = rows, np.dtype(d)
            elif rows != b:
                raise source_mod.SourceError(
                    f"column blocks disagree on rows: {rows} != {b}")
            elif np.dtype(d) != dtype:
                raise source_mod.SourceError(
                    f"column blocks disagree on dtype: {d} != {dtype}")
            del w
        if not norm:
            raise source_mod.SourceError("no column blocks")
        total_w = sum(hi - lo for _, _, lo, hi in norm)
        self.blocks = tuple(norm)
        import threading

        self._scratch = threading.local()
        super().__init__((b, total_w), dtype, pool=pool)
        if inner_defaults:
            self.default_chunk_rows = max(1, min(inner_defaults))
        else:
            row_bytes = max(1, total_w * self.dtype.itemsize)
            self.default_chunk_rows = max(
                1, min(b, source_mod._DEFAULT_SLICE_BYTES // row_bytes))

    def _scratch_for(self, idx: int, rows: int, cols: int, dtype):
        """Per-thread reusable scratch for inner-source reads: the walk
        (and its prefetcher, and every sharded lane) calls read_rows per
        chunk, and a fresh full-width allocation per call is pure churn.
        Thread-local so concurrent lane/prefetcher reads never share a
        buffer; grown monotonically to the largest chunk seen."""
        store = getattr(self._scratch, "bufs", None)
        if store is None:
            store = self._scratch.bufs = {}
        buf = store.get(idx)
        if buf is None or buf.shape[0] < rows:
            buf = store[idx] = np.empty((rows, cols), dtype)
        return buf[:rows]

    def read_rows(self, lo, hi, out):
        lo, hi = int(lo), int(hi)
        c = 0
        for i, (kind, blk, blo, bhi) in enumerate(self.blocks):
            w = bhi - blo
            if kind == "host":
                np.copyto(out[:, c:c + w], blk[lo:hi, blo:bhi])
            else:
                # the ChunkSource read contract is full-width rows; a
                # narrow column window still reads the whole row and
                # slices (API limitation, not allocation churn)
                scratch = self._scratch_for(i, hi - lo,
                                            int(blk.shape[1]), blk.dtype)
                blk.read_rows(lo, hi, scratch)
                np.copyto(out[:, c:c + w], scratch[:, blo:bhi])
            c += w

    def _nan_probe(self):
        nan_any = False
        for kind, blk, blo, bhi in self.blocks:
            if kind == "host":
                if np.isnan(blk[:, blo:bhi]).any():
                    nan_any = True
                    break
            else:
                # the inner probe covers the FULL width — conservative
                # (a NaN outside the window still reads as "any"), which
                # can only weaken the mode toward the always-correct one
                if blk._nan_probe()[0]:
                    nan_any = True
                    break
        kind, blk, blo, bhi = self.blocks[-1]
        if kind == "host":
            nan_last = bool(np.isnan(blk[:, bhi - 1]).any())
        else:
            nan_last = True  # conservative: no cheap last-col read
        return nan_any, nan_last

    def fingerprint(self) -> str:
        """Byte-identical to ``journal.panel_fingerprint`` of the
        materialized composite: the strided sample rows are read through
        the blocks, so an in-memory augmented walk and this streamed one
        journal under the SAME panel identity and cross-resume."""
        with self._mu:
            if self._fingerprint is not None:
                return self._fingerprint
        b, t = self.shape
        max_side = 256
        sr = max(1, -(-b // max_side))
        sc = max(1, -(-t // max_side))
        rows = range(0, b, sr)
        sample = np.empty((len(rows), len(range(0, t, sc))), self.dtype)
        buf = np.empty((1, t), self.dtype)
        for i, r in enumerate(rows):
            self.read_rows(r, r + 1, buf)
            sample[i] = buf[0, ::sc]
        h = hashlib.sha256()
        h.update(f"{b}x{t}:{sample.dtype}".encode())
        h.update(np.ascontiguousarray(sample).tobytes())
        fp = h.hexdigest()[:16]
        with self._mu:
            self._fingerprint = fp
        return fp


def augmented_host(y: np.ndarray, params: np.ndarray, status: np.ndarray,
                   *, base_row: int = 0) -> np.ndarray:
    """Host-materialized augmented panel (the serving path: request
    panels are host arrays already).  ``base_row`` offsets the row-index
    column (a server request's rows are locally indexed)."""
    y = np.ascontiguousarray(y)
    dtype = y.dtype
    b = y.shape[0]
    _check_row_index(base_row + b, dtype)
    cols = [y,
            np.ascontiguousarray(np.asarray(params, dtype)),
            np.asarray(status, np.int8).astype(dtype)[:, None],
            (base_row + np.arange(b, dtype=np.int64)).astype(dtype)[:, None]]
    return np.concatenate(cols, axis=1)


def augmented_panel(y, params: np.ndarray, status: np.ndarray):
    """The augmented panel in the input's own residency.

    A device/host array ``y`` concatenates on device (the in-HBM walk);
    a ``ChunkSource`` composes into a :class:`ColumnBlockSource` that
    streams ``y`` and serves the side columns from host RAM — byte
    positions identical either way, so the two spellings journal under
    one panel identity.  Returns ``(panel_or_source, n_time, k)``.
    """
    params = np.asarray(params)
    if params.ndim != 2:
        raise ValueError(f"params must be [rows, k], got {params.shape}")
    status = np.asarray(status, np.int8)
    if isinstance(y, source_mod.ChunkSource):
        b, t = (int(y.shape[0]), int(y.shape[1]))
        dtype = np.dtype(y.dtype)
        if params.shape[0] != b:
            raise ValueError(
                f"params rows {params.shape[0]} != panel rows {b}")
        _check_row_index(b, dtype)
        side = np.concatenate(
            [np.ascontiguousarray(params.astype(dtype)),
             status.astype(dtype)[:, None],
             np.arange(b, dtype=np.int64).astype(dtype)[:, None]], axis=1)
        return (ColumnBlockSource([(y, 0, t), side]),
                t, int(params.shape[1]))
    import jax.numpy as jnp

    yb = jnp.asarray(y)
    if yb.ndim != 2:
        raise ValueError(f"expected [batch, time], got {yb.shape}")
    if params.shape[0] != yb.shape[0]:
        raise ValueError(
            f"params rows {params.shape[0]} != panel rows {yb.shape[0]}")
    dtype = np.dtype(str(yb.dtype))
    _check_row_index(int(yb.shape[0]), dtype)
    side = np.concatenate(
        [np.ascontiguousarray(params.astype(dtype)),
         status.astype(dtype)[:, None],
         np.arange(int(yb.shape[0]), dtype=np.int64).astype(dtype)[:, None]],
        axis=1)
    aug = jnp.concatenate([yb, jnp.asarray(side)], axis=1)
    return aug, int(yb.shape[1]), int(params.shape[1])


def _check_row_index(n_rows: int, dtype: np.dtype) -> None:
    limit = _MAX_EXACT_ROWS.get(np.dtype(dtype))
    if limit is None:
        raise ValueError(f"unsupported panel dtype {dtype} for forecasting")
    if n_rows > limit:
        raise ValueError(
            f"{n_rows} rows exceed the exactly-representable row-index "
            f"range of {dtype} ({limit}); use float64 panels beyond that")
