"""Rehydrate fitted parameters from durable journals.

Fit-once / forecast-many (the reference library's whole point): a fit
walk's write-ahead journal already holds every committed chunk's params,
statuses, and diagnostics as npz shards named by an atomically-updated
manifest — so a LATER process can forecast without refitting, and
without re-running the chunk driver at all: :func:`load_fit_result`
assembles the journal into the same host-side ``ResilientFitResult`` the
walk returned, byte for byte for every committed row.  Rows the job
never committed (TIMEOUT marks, uncommitted chunks of a killed run) come
back NaN with status ``TIMEOUT`` — the same synthesis the driver applies
to undispatched chunks, so a forecast over a partial journal degrades to
NaN rows, never to stale or fabricated numbers.

:func:`load_auto_members` does the same for an auto-fit search root
(``auto_manifest.json`` + per-group ``grid_*`` journals), demuxing fused
group packs back into per-order results — the input the
criterion-weighted ensemble blends.
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from ..reliability.journal import JournalError, TornManifestError
from ..reliability.runner import ResilientFitResult
from ..reliability.status import STATUS_DTYPE, FitStatus, status_counts

__all__ = ["load_fit_result", "load_auto_members"]


def _read_manifest(path: str) -> dict:
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise TornManifestError(
            f"{path} does not parse ({e}); a mid-commit crash tore the "
            "write — inspect/remove the journal explicitly.") from e


def load_fit_result(checkpoint_dir: str) -> ResilientFitResult:
    """Assemble a fit walk's journal into a ``ResilientFitResult``.

    Reads the job-level ``manifest.json`` (single-device or merged
    sharded — merged entries carry namespace-rooted shard paths) and
    loads every committed chunk's npz shard.  Committed rows are
    byte-identical to the walk's own output; everything else is NaN +
    ``TIMEOUT``.  A torn shard is skipped (its rows degrade to TIMEOUT)
    rather than poisoning the load — mirroring the driver's
    torn-shard-means-recompute contract, except a pure reader cannot
    recompute.
    """
    root = os.path.abspath(checkpoint_dir)
    mp = os.path.join(root, "manifest.json")
    if not os.path.exists(mp):
        raise JournalError(f"no manifest.json under {root}")
    m = _read_manifest(mp)
    n_rows = int(m["n_rows"])
    loaded: List[Tuple[int, int, dict]] = []
    k = 1
    dtype = np.dtype(np.float32)
    chunks_lost = 0
    for e in m.get("chunks", []):
        if e.get("status") != "committed":
            continue
        path = os.path.join(root, e["shard"])
        try:
            with np.load(path, allow_pickle=False) as z:
                arrs = {key: np.array(z[key]) for key in
                        ("params", "nll", "converged", "iters", "status")}
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            chunks_lost += 1
            continue
        lo, hi = int(e["lo"]), int(e["hi"])
        if arrs["params"].shape[0] != hi - lo:
            chunks_lost += 1
            continue
        k = max(k, int(arrs["params"].shape[1]))
        dtype = arrs["params"].dtype
        loaded.append((lo, hi, arrs))
    loaded.sort(key=lambda x: x[0])
    params = np.full((n_rows, k), np.nan, dtype)
    nll = np.full((n_rows,), np.nan, dtype)
    conv = np.zeros((n_rows,), bool)
    iters = np.zeros((n_rows,), np.int32)
    status = np.full((n_rows,), FitStatus.TIMEOUT, STATUS_DTYPE)
    covered = 0
    for lo, hi, arrs in loaded:
        w = arrs["params"].shape[1]
        params[lo:hi, :w] = arrs["params"]
        nll[lo:hi] = arrs["nll"]
        conv[lo:hi] = arrs["converged"]
        iters[lo:hi] = arrs["iters"]
        status[lo:hi] = arrs["status"]
        covered += hi - lo
    meta = {
        "journal": {
            "dir": root,
            "loaded_from_journal": True,
            "config_hash": m.get("config_hash"),
            "panel_fingerprint": m.get("panel_fingerprint"),
            "chunks_loaded": len(loaded),
            "chunks_lost": chunks_lost,
            "rows_covered": covered,
            "rows_missing": n_rows - covered,
        },
        "status_counts": status_counts(status),
    }
    return ResilientFitResult(params, nll, conv, iters, status, meta)


def load_auto_members(auto_root: str):
    """Per-order fit results of a durable auto-fit search.

    Reads ``auto_manifest.json`` for the grid (orders, fusion groups,
    journal dirs), loads each group's journal via
    :func:`load_fit_result`, and demuxes fused packs back into per-order
    results (``models.auto._demux_fused`` — the same unpacking the live
    search ran).  Returns ``(specs, include_intercept, results, meta)``
    where ``results`` is one host-side fit result per order in grid
    order — exactly what ``auto.select_orders`` /
    ``auto.criterion_matrix`` and the ensemble consume.
    """
    from ..models import auto as _auto

    root = os.path.abspath(auto_root)
    amp = os.path.join(root, "auto_manifest.json")
    if not os.path.exists(amp):
        raise JournalError(f"no auto_manifest.json under {root}")
    am = _read_manifest(amp)
    meta = am.get("auto_fit") or {}
    order_meta = meta.get("orders") or []
    if not order_meta:
        raise JournalError(f"{amp} records no orders")
    specs = _auto.normalize_orders([
        (tuple(o["order"]) if o.get("seasonal") is None
         else tuple(o["order"]) + (tuple(o["seasonal"]),))
        for o in sorted(order_meta, key=lambda o: o["grid_index"])])
    # include_intercept is recoverable from any order's recorded param
    # count: n_params(True) == n_params(False) + 1, always distinct
    o0 = sorted(order_meta, key=lambda o: o["grid_index"])[0]
    include_intercept = (
        int(o0["k"]) == specs[0].n_params(True))
    groups = meta.get("fusion_groups") or []
    if not groups:
        raise JournalError(f"{amp} records no fusion groups")
    results: List[Optional[object]] = [None] * len(specs)
    for grp in groups:
        gdir = os.path.join(root, grp["dir"])
        members = [int(g) for g in grp["orders"]]
        res = load_fit_result(gdir)
        if len(members) == 1:
            results[members[0]] = res
        else:
            per = _auto._demux_fused(
                res, [specs[g] for g in members], include_intercept)
            for j, g in enumerate(members):
                results[g] = per[j]
    missing = [g for g, r in enumerate(results) if r is None]
    if missing:
        raise JournalError(
            f"auto manifest {amp} fusion groups do not cover orders "
            f"{missing}")
    return specs, include_intercept, results, meta
