"""Per-model forecast kernels for the chunked forecast walk.

One vocabulary for every fit-capable model family: a **point kernel**
``(params [B, k], y [B, T]) -> [B, H]`` reusing each model module's own
jitted forecast program (nested jit inlines — one compiled program per
chunk shape), and a **simulation kernel**
``(params, y, keys [B]) -> paths [B, S, H]`` that runs the model's
forward recursion with Gaussian innovations whose scale is estimated
from the model's own in-sample one-step errors — the vmapped ``sample``
path bent forward from the end state instead of from zero.  Interval
quantiles over the ``S`` axis are per-row and per-horizon, so they
inherit the row-independence that makes the walk chunk-layout-invariant.

Everything here is TRACEABLE (not jitted): the walk's chunk program
(``forecasting.walk``) composes point + simulation + masking into ONE
compiled program per static configuration.

Alignment is handled per row ON DEVICE (``base.align_right`` /
``align_mode="general"``) — a forecast chunk never pays a host probe, so
the walk stays dispatch-ahead with zero per-chunk syncs.

Model configuration (``model_kwargs``) is normalized to a sorted tuple of
``(key, value)`` pairs with lists coerced to tuples
(:func:`normalize_model_kwargs`): the canonical form is what reaches the
compiled-program cache AND the journal config hash, so a live walk and a
JSON-round-tripped serving/recovery walk hash identically.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import arima as _arima
from ..models import base as _base
from ..models import ewma as _ewma
from ..models import garch as _garch
from ..models import holtwinters as _hw

__all__ = ["MODELS", "normalize_model_kwargs", "param_width",
           "point_fn", "sim_fn"]

# model name -> allowed config keys (with defaults applied at normalize)
MODELS = {
    "arima": {"order": None, "include_intercept": True},
    "autoregression": {"max_lag": 1},
    "ewma": {},
    "holtwinters": {"period": None, "model_type": "additive"},
    "garch": {},
}


def _norm_val(v):
    if isinstance(v, (list, tuple)):
        return tuple(_norm_val(x) for x in v)
    if isinstance(v, bool):
        return v
    if isinstance(v, float) and float(v).is_integer():
        return int(v)  # JSON round trips can float-ify ints
    return v


def normalize_model_kwargs(model: str, kwargs) -> Tuple:
    """Validated canonical config tuple for ``model`` (see module doc)."""
    if model not in MODELS:
        raise ValueError(
            f"unknown forecast model {model!r} (one of {sorted(MODELS)})")
    allowed = MODELS[model]
    kw = dict(kwargs or ())
    bad = sorted(set(kw) - set(allowed))
    if bad:
        raise ValueError(
            f"forecast model {model!r} does not accept {bad} "
            f"(allowed: {sorted(allowed)})")
    cfg = {}
    for key, default in allowed.items():
        v = _norm_val(kw.get(key, default))
        if v is None:
            raise ValueError(f"forecast model {model!r} requires {key}=")
        cfg[key] = v
    if model == "arima":
        order = tuple(cfg["order"])
        if len(order) == 4:
            raise ValueError(
                "seasonal ARIMA forecasting is not supported yet "
                "(ROADMAP follow-on); pass a plain (p, d, q) order")
        if len(order) != 3:
            raise ValueError(f"bad ARIMA order {cfg['order']!r}")
        order = tuple(int(x) for x in order)
        if min(order) < 0:
            raise ValueError(f"bad ARIMA order {cfg['order']!r}")
        cfg["order"] = order
        cfg["include_intercept"] = bool(cfg["include_intercept"])
    elif model == "autoregression":
        cfg["max_lag"] = int(cfg["max_lag"])
        if cfg["max_lag"] < 1:
            raise ValueError("max_lag must be >= 1")
    elif model == "holtwinters":
        cfg["period"] = int(cfg["period"])
        if cfg["period"] < 2:
            raise ValueError("period must be >= 2")
        if cfg["model_type"] not in ("additive", "multiplicative"):
            raise ValueError(
                f"bad model_type {cfg['model_type']!r}")
    return tuple(sorted(cfg.items()))


def param_width(model: str, cfg: dict) -> int:
    """The params-block width the augmented panel must carry."""
    if model == "arima":
        return _arima._n_params(cfg["order"], cfg["include_intercept"])
    if model == "autoregression":
        return cfg["max_lag"] + 1  # [c, phi_1..phi_p], c = 0 if no intercept
    if model in ("ewma",):
        return 1
    if model in ("holtwinters", "garch"):
        return 3
    raise ValueError(f"unknown forecast model {model!r}")


# ---------------------------------------------------------------------------
# point kernels — each model module's own jitted forecast program
# ---------------------------------------------------------------------------


def point_fn(model: str, cfg: dict, horizon: int):
    """Traceable ``(pb, yb) -> [B, horizon]`` point forecasts."""
    if model == "arima":
        prog = _arima._forecast_program(
            cfg["order"], horizon, cfg["include_intercept"], "scan",
            "general")
        return lambda pb, yb: prog(pb, yb)
    if model == "autoregression":
        prog = _arima._forecast_program(
            (cfg["max_lag"], 0, 0), horizon, True, "scan", "general")
        return lambda pb, yb: prog(pb, yb)
    if model == "ewma":
        prog = _ewma._forecast_program(horizon)
        return lambda pb, yb: prog(pb, yb)
    if model == "holtwinters":
        prog = _hw._forecast_program(
            cfg["period"], cfg["model_type"] == "multiplicative", horizon)
        return lambda pb, yb: prog(pb, yb)
    if model == "garch":
        prog = _garch._forecast_program(horizon)
        return lambda pb, yb: prog(pb, yb)
    raise ValueError(f"unknown forecast model {model!r}")


# ---------------------------------------------------------------------------
# simulation kernels — forward recursions with Gaussian innovations
# ---------------------------------------------------------------------------


def sim_fn(model: str, cfg: dict, horizon: int, n_samples: int):
    """Traceable ``(pb, yb, keys [B]) -> paths [B, S, horizon]``.

    Paths simulate the FUTURE OBSERVATIONS under the fitted model with
    innovations of the in-sample one-step error scale — except GARCH,
    whose point forecast is the variance path and whose paths simulate
    future RETURNS (the quantity its interval bands bound).
    """
    if model == "arima":
        return _arima_sim(cfg["order"], cfg["include_intercept"],
                          horizon, n_samples)
    if model == "autoregression":
        return _arima_sim((cfg["max_lag"], 0, 0), True, horizon, n_samples)
    if model == "ewma":
        return _ewma_sim(horizon, n_samples)
    if model == "holtwinters":
        return _hw_sim(cfg["period"],
                       cfg["model_type"] == "multiplicative",
                       horizon, n_samples)
    if model == "garch":
        return _garch_sim(horizon, n_samples)
    raise ValueError(f"unknown forecast model {model!r}")


def _arima_sim(order, include_intercept: bool, horizon: int, n_samples: int):
    p, d, q = order
    i0 = int(include_intercept)

    def f(pb, yb, keys):
        def one(pr, yv, key):
            ya, nv0 = _base.align_right(yv)
            yd = ya
            for _ in range(d):
                yd = yd[1:] - yd[:-1]
            nvd = nv0 - d
            n = yd.shape[0]
            start = (n - nvd).astype(yd.dtype)
            t_idx = jnp.arange(n, dtype=yd.dtype)
            ydz = jnp.where(t_idx >= start, yd, 0.0)
            e = _arima._css_errors(pr, ydz, order, include_intercept,
                                   condition=False, n_valid=nvd)
            n_eff = jnp.maximum(nvd - p, 1).astype(yv.dtype)
            sigma = jnp.sqrt(jnp.sum(e * e) / n_eff)
            elast = e[::-1][:q] if q else jnp.zeros((0,), yv.dtype)
            ydlast = ydz[::-1][:p] if p else jnp.zeros((0,), yv.dtype)
            c = pr[0] if include_intercept else jnp.zeros((), yv.dtype)
            phi = pr[i0:i0 + p]
            theta = pr[i0 + p:i0 + p + q]
            levels = []
            lv = ya
            for _ in range(d):
                levels.append(lv[-1])
                lv = lv[1:] - lv[:-1]
            lvl0 = (jnp.stack(levels) if d
                    else jnp.zeros((0,), yv.dtype))
            S = n_samples
            eps = sigma * jax.random.normal(key, (horizon, S), yv.dtype)
            init = (jnp.broadcast_to(ydlast, (S, p)),
                    jnp.broadcast_to(elast, (S, q)),
                    jnp.broadcast_to(lvl0, (S, d)))

            def step(carry, et):
                ydl, el, lvl = carry
                pred = c
                if p:
                    pred = pred + ydl @ phi
                if q:
                    pred = pred + el @ theta
                ynew = pred + et  # the innovation IS the error at t
                new_ydl = (jnp.concatenate(
                    [ynew[:, None], ydl[:, :-1]], axis=1) if p else ydl)
                new_el = (jnp.concatenate(
                    [et[:, None], el[:, :-1]], axis=1) if q else el)
                acc = ynew
                new_lvl = lvl
                for i in reversed(range(d)):
                    acc = lvl[:, i] + acc
                    new_lvl = new_lvl.at[:, i].set(acc)
                out = acc if d else ynew
                return (new_ydl, new_el, new_lvl), out

            _, paths = lax.scan(step, init, eps)  # [H, S]
            return paths.T  # [S, H]

        return jax.vmap(one)(pb, yb, keys)

    return f


def _ewma_sim(horizon: int, n_samples: int):
    def f(pb, yb, keys):
        def one(pr, yv, key):
            a = pr[0]
            ya, nv = _base.align_right(yv)
            s = _ewma.smooth(a, ya, nv)
            t_len = ya.shape[0]
            start = t_len - nv
            err = ya[1:] - s[:-1]
            err = jnp.where(jnp.arange(1, t_len) > start, err, 0.0)
            n_eff = jnp.maximum(nv - 1, 1).astype(yv.dtype)
            sigma = jnp.sqrt(jnp.sum(err * err) / n_eff)
            S = n_samples
            eps = sigma * jax.random.normal(key, (horizon, S), yv.dtype)
            s0 = jnp.broadcast_to(s[-1], (S,))

            def step(sp, et):
                x = sp + et
                return a * x + (1.0 - a) * sp, x

            _, paths = lax.scan(step, s0, eps)
            out = paths.T
            return jnp.where(nv >= 2, out, jnp.nan)

        return jax.vmap(one)(pb, yb, keys)

    return f


def _hw_sim(period: int, multiplicative: bool, horizon: int,
            n_samples: int):
    def f(pb, yb, keys):
        def one(pr, yv, key):
            ya, nv = _base.align_right(yv)
            preds, (level, trend, seasonal) = _hw._run(
                pr, ya, period, multiplicative, nv)
            t_len = ya.shape[0]
            start = t_len - nv
            err = ya - preds
            err = jnp.where(
                jnp.arange(t_len) >= start + period, err, 0.0)
            n_eff = jnp.maximum(nv - period, 1).astype(yv.dtype)
            sigma = jnp.sqrt(jnp.sum(err * err) / n_eff)
            alpha, beta, gamma = pr[0], pr[1], pr[2]
            S = n_samples
            eps = sigma * jax.random.normal(key, (horizon, S), yv.dtype)
            init = (jnp.broadcast_to(level, (S,)),
                    jnp.broadcast_to(trend, (S,)),
                    jnp.broadcast_to(seasonal, (S, period)))

            def step(carry, et):
                lv, tr, seas = carry
                s0 = seas[:, 0]
                if multiplicative:
                    pred = (lv + tr) * s0
                    yt = pred + et
                    nl = (alpha * yt / jnp.maximum(s0, 1e-12)
                          + (1 - alpha) * (lv + tr))
                    ns = (gamma * yt / jnp.maximum(nl, 1e-12)
                          + (1 - gamma) * s0)
                else:
                    pred = lv + tr + s0
                    yt = pred + et
                    nl = alpha * (yt - s0) + (1 - alpha) * (lv + tr)
                    ns = gamma * (yt - nl) + (1 - gamma) * s0
                nt = beta * (nl - lv) + (1 - beta) * tr
                nseas = jnp.concatenate([seas[:, 1:], ns[:, None]], axis=1)
                return (nl, nt, nseas), yt

            _, paths = lax.scan(step, init, eps)
            out = paths.T
            # same structural gate as the point forecast: seeding needs
            # two full seasons
            return jnp.where(nv >= 2 * period, out, jnp.nan)

        return jax.vmap(one)(pb, yb, keys)

    return f


def _garch_sim(horizon: int, n_samples: int):
    def f(pb, yb, keys):
        def one(pr, rv, key):
            ra, nv = _base.align_right(rv)
            h = _garch.variances(pr, ra, nv)
            omega, alpha, beta = pr[0], pr[1], pr[2]
            S = n_samples
            eps = jax.random.normal(key, (horizon, S), rv.dtype)
            init = (jnp.broadcast_to(h[-1], (S,)),
                    jnp.broadcast_to(ra[-1], (S,)))

            def step(carry, et):
                hp, rp = carry
                hn = omega + alpha * rp ** 2 + beta * hp
                r = jnp.sqrt(jnp.maximum(hn, 1e-12)) * et
                return (hn, r), r

            _, paths = lax.scan(step, init, eps)
            out = paths.T
            return jnp.where(nv >= 2, out, jnp.nan)

        return jax.vmap(one)(pb, yb, keys)

    return f
