"""Criterion-weighted forecast ensembles over an auto-fit order grid.

A hard argmin throws away everything the losing candidates learned; the
standard repair is Akaike weighting — per row, each candidate order gets
``w_g ∝ exp(-Δ_g / (2 T))`` where ``Δ_g`` is its criterion excess over
the row's best and ``T`` the temperature — and the ensemble forecast is
the weight-blended member forecast.  ``auto_fit(return_criteria=True)``
already surfaces the ``[G, B]`` criteria matrix; this module turns it
into weights (:func:`criterion_weights`), runs one chunked forecast walk
per member order (journaled under ``<root>/forecast_%05d`` — every walk
composes with the driver exactly like a single-model forecast), and
blends points and interval bands.

At ``temperature=0`` selection degenerates BITWISE to the argmin winner:
the blend is not a weighted sum with a one-hot weight (``0 * NaN`` and
``x + 0.0`` both break bit identity) but a literal per-row gather of the
winning member's forecast — ties to the earlier grid entry, the same
contract as ``auto._select_program``.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Sequence

import numpy as np

from .. import obs
from ..models import auto as _auto
from ..reliability.status import FitStatus
from . import walk as walk_mod
from .params import load_auto_members

__all__ = ["EnsembleForecast", "criterion_weights", "ensemble_forecast"]


class EnsembleForecast(NamedTuple):
    """Blended panel forecast plus the selection record.

    ``weights`` is the ``[G, B]`` member weight matrix (columns sum to 1
    where any member is eligible, all-zero where none is);
    ``order_index`` the per-row argmin winner (``-1``: none eligible);
    ``member_forecasts`` the stacked ``[G, B, H]`` member points (kept so
    callers can audit the blend).
    """

    forecast: np.ndarray  # [B, H]
    lo: Optional[np.ndarray]
    hi: Optional[np.ndarray]
    weights: np.ndarray  # [G, B]
    order_index: np.ndarray  # [B] int32
    status: np.ndarray  # [B] int8
    orders: tuple
    member_forecasts: np.ndarray  # [G, B, H]
    meta: dict


def criterion_weights(criteria, temperature: float = 1.0) -> np.ndarray:
    """Softmax Akaike-style weights from a ``[G, B]`` criteria matrix.

    ``w_g = exp(-(c_g - min_g c) / (2 * temperature))`` normalized per
    row; non-finite criteria get weight 0 (an ineligible candidate can
    never contribute), rows with no finite candidate are all-zero.
    ``temperature=0`` returns the exact one-hot argmin (ties to the
    earlier grid entry); weights are float64 regardless of panel dtype —
    they are selection metadata, not panel bytes.
    """
    c = np.asarray(criteria, np.float64)
    if c.ndim != 2:
        raise ValueError(f"criteria must be [G, B], got {c.shape}")
    temperature = float(temperature)
    if temperature < 0:
        raise ValueError("temperature must be >= 0")
    finite = np.isfinite(c)
    any_f = finite.any(axis=0)
    cz = np.where(finite, c, np.inf)
    if temperature == 0.0:
        best = np.argmin(cz, axis=0)  # first-min ties, like argmin select
        w = np.zeros(c.shape, np.float64)
        w[best[any_f], np.nonzero(any_f)[0]] = 1.0
        return w
    cmin = np.min(cz, axis=0)
    with np.errstate(invalid="ignore", over="ignore"):
        w = np.where(finite & any_f[None, :],
                     np.exp(-(cz - np.where(any_f, cmin, 0.0)[None, :])
                            / (2.0 * temperature)), 0.0)
    s = w.sum(axis=0)
    with np.errstate(invalid="ignore", divide="ignore"):
        w = np.where(s[None, :] > 0, w / np.maximum(s[None, :], 1e-300),
                     0.0)
    return w


def ensemble_forecast(
    y,
    horizon: int,
    *,
    orders: Optional[Sequence] = None,
    criterion: str = "aicc",
    temperature: float = 1.0,
    include_intercept: bool = True,
    auto_root: Optional[str] = None,
    members: Optional[Sequence] = None,
    intervals: bool = False,
    level: float = 0.9,
    n_samples: int = 256,
    seed: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    chunk_rows: Optional[int] = None,
    fit_kwargs: Optional[dict] = None,
    **walk_kwargs,
) -> EnsembleForecast:
    """Blend per-order forecasts with softmax criterion weights.

    Member fits come from ONE of: ``auto_root`` (a durable
    ``auto_fit(checkpoint_dir=...)`` search root — fit once on disk,
    ensemble-forecast many times later; orders and intercept convention
    are read from its manifest), ``members`` (pre-fit per-order results
    in ``orders`` grid order), or — neither given — fresh per-order fit
    walks run here (journaled under ``<root>/grid_%05d`` when
    ``checkpoint_dir`` is set, ``fit_kwargs`` forwarded).  Seasonal
    candidates are rejected (seasonal forecasting is a ROADMAP
    follow-on).  Criteria are recomputed on device from the member nlls
    (``auto.criterion_matrix``), weights via :func:`criterion_weights`;
    each member order forecasts the whole panel through a chunked
    forecast walk (journaled under ``<root>/forecast_%05d``), and the
    blend renormalizes per row over members whose forecast is usable.
    ``temperature=0`` recovers the argmin winner bitwise.
    """
    if auto_root is not None:
        specs, include_intercept, results, _am = load_auto_members(
            auto_root)
        if orders is not None:
            want = _auto.normalize_orders(orders)
            if want != specs:
                raise ValueError(
                    "orders= disagrees with the auto root's grid; omit "
                    "orders or pass the same grid")
    else:
        specs = _auto.normalize_orders(orders)
        results = list(members) if members is not None else None
        if results is not None and len(results) != len(specs):
            raise ValueError(
                f"{len(specs)} orders but {len(results)} member results")
    if any(s.seasonal is not None for s in specs):
        raise ValueError(
            "seasonal orders cannot be ensemble-forecast yet (seasonal "
            "forecasting is a ROADMAP follow-on)")
    g_total = len(specs)

    if auto_root is None and results is None:
        import functools

        from ..models import arima as _arima
        from ..reliability import fit_chunked

        results = []
        for g, spec in enumerate(specs):
            fit_fn = functools.partial(
                _arima.fit, order=spec.order,
                include_intercept=include_intercept,
                **dict(fit_kwargs or {}))
            ckpt = (os.path.join(checkpoint_dir, f"grid_{g:05d}")
                    if checkpoint_dir is not None else None)
            results.append(fit_chunked(
                fit_fn, y, resilient=False, chunk_rows=chunk_rows,
                checkpoint_dir=ckpt, grid=(g, g_total), **walk_kwargs))

    nv0 = _auto.panel_n_valid(y)
    nll_stack = np.stack([np.asarray(r.neg_log_likelihood)
                          for r in results])
    criteria = np.asarray(_auto.criterion_matrix(
        specs, nll_stack, nv0, criterion=criterion,
        include_intercept=include_intercept))
    weights = criterion_weights(criteria, temperature)

    member_fc = []
    for g, spec in enumerate(specs):
        ckpt = (os.path.join(checkpoint_dir, f"forecast_{g:05d}")
                if checkpoint_dir is not None else None)
        fc = walk_mod.forecast_chunked(
            "arima", results[g], y, horizon,
            model_kwargs={"order": spec.order,
                          "include_intercept": include_intercept},
            intervals=intervals, level=level, n_samples=n_samples,
            seed=(None if seed is None else int(seed) + g),
            chunk_rows=chunk_rows, checkpoint_dir=ckpt, **walk_kwargs)
        member_fc.append(fc)
    points = np.stack([fc.forecast for fc in member_fc])  # [G, B, H]
    los = (np.stack([fc.lo for fc in member_fc]) if intervals else None)
    his = (np.stack([fc.hi for fc in member_fc]) if intervals else None)
    statuses = np.stack([np.asarray(fc.status, np.int8)
                         for fc in member_fc])

    b = points.shape[1]
    finite_c = np.isfinite(criteria)
    any_f = finite_c.any(axis=0)
    cz = np.where(finite_c, criteria, np.inf)
    order_index = np.where(any_f, np.argmin(cz, axis=0),
                           -1).astype(np.int32)

    if float(temperature) == 0.0:
        # literal winner gather: bitwise the argmin member's forecast
        rows = np.arange(b)
        idx = np.where(any_f, order_index, 0)
        point = np.where(any_f[:, None], points[idx, rows], np.nan)
        lo = (np.where(any_f[:, None], los[idx, rows], np.nan)
              if intervals else None)
        hi = (np.where(any_f[:, None], his[idx, rows], np.nan)
              if intervals else None)
        status = np.where(any_f, statuses[idx, rows],
                          np.int8(FitStatus.DIVERGED)).astype(np.int8)
    else:
        usable = np.isfinite(points).all(axis=2)  # [G, B]
        eff = weights * usable
        s = eff.sum(axis=0)
        with np.errstate(invalid="ignore", divide="ignore"):
            effn = np.where(s[None, :] > 0,
                            eff / np.maximum(s[None, :], 1e-300), 0.0)
        point = np.einsum("gb,gbh->bh",
                          effn, np.nan_to_num(points, nan=0.0))
        point = np.where(s > 0, point.T, np.nan).T.astype(points.dtype)
        if intervals:
            lo = np.einsum("gb,gbh->bh", effn,
                           np.nan_to_num(los, nan=0.0))
            lo = np.where(s > 0, lo.T, np.nan).T.astype(points.dtype)
            hi = np.einsum("gb,gbh->bh", effn,
                           np.nan_to_num(his, nan=0.0))
            hi = np.where(s > 0, hi.T, np.nan).T.astype(points.dtype)
        else:
            lo = hi = None
        contrib = eff > 0
        status = np.where(
            contrib.any(axis=0),
            np.min(np.where(contrib, statuses,
                            np.int8(FitStatus.TIMEOUT)), axis=0),
            np.int8(FitStatus.DIVERGED)).astype(np.int8)

    meta = {
        "ensemble": {
            "criterion": criterion,
            "temperature": float(temperature),
            "orders": [s.label for s in specs],
            "include_intercept": bool(include_intercept),
            "auto_root": auto_root,
            "horizon": int(horizon),
            "intervals": bool(intervals),
            "rows_none_eligible": int((~any_f).sum()),
        },
        "criteria_matrix": criteria,
    }
    obs.counter("forecast.ensembles").inc()
    return EnsembleForecast(point, lo, hi, weights, order_index, status,
                            specs, points, meta)
