"""Rolling-origin backtest campaigns: expanding-window refit x horizon.

The production question a forecast answers is "how wrong will we be?" —
and the standard answer is rolling-origin evaluation: refit on
``y[:, :origin]``, forecast ``horizon`` steps, score against the held
out actuals, slide the origin forward, repeat.  At panel scale every
window is a full fit walk, so the campaign is expressed as ONE journaled
job:

- each window's refit is an ordinary ``fit_chunked`` walk journaled
  under ``<root>/window_00000/…``, WARM-STARTED from the previous
  window's journaled params (packed into augmented columns —
  ``walk.warmstart_fit`` — exactly like PR 9's warm-started basin
  refits) when the model takes ``init_params``;
- the window's forecast is recomputed deterministically from the fit
  result (same kernels, same layout — no second journal needed);
- per-row and per-horizon error metrics (MAE / RMSE / MAPE / interval
  coverage) are written as an npz metrics shard plus a durable
  ``backtest_manifest.json`` entry, both atomic, after EVERY window.

SIGKILL anywhere — mid-chunk, mid-window, between windows — and a rerun
with the same panel/config resumes: committed windows load their metrics
shards (digest-verified), the in-flight window's fit walk replays only
its uncommitted chunks, and the completed campaign's metrics are
BITWISE-identical to an uninterrupted run.  A manifest written under a
different panel or campaign config is rejected loudly
(:class:`StaleBacktestError`), mirroring the chunk journal's contract.

A campaign is also the serving layer's natural stress client: pass
``server=`` to route every window's forecast through a resident
``FitServer``'s micro-batching (the fits stay journaled walks — the
server serves the forecast-many half of fit-once/forecast-many).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
import time
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from .. import obs
from ..reliability import journal as journal_mod
from ..reliability import source as source_mod
from . import augment, kernels, walk as walk_mod
from .params import load_fit_result

__all__ = ["BacktestResult", "StaleBacktestError", "default_origins",
           "run_backtest", "BACKTEST_MANIFEST"]

BACKTEST_MANIFEST = "backtest_manifest.json"
BACKTEST_VERSION = 1


class StaleBacktestError(RuntimeError):
    """The backtest manifest belongs to a different panel or campaign."""


class BacktestResult(NamedTuple):
    """Campaign output: per-window records + campaign-level aggregates.

    ``windows`` is one dict per origin (metrics aggregates + artifact
    paths); ``metrics`` the campaign-level per-horizon aggregates
    (row-count-weighted across windows); ``manifest_path`` the durable
    record (None unjournaled); ``meta`` the campaign accounting.
    """

    windows: List[dict]
    metrics: dict
    manifest_path: Optional[str]
    meta: dict


def default_origins(n_time: int, horizon: int, n_windows: int,
                    min_train: Optional[int] = None) -> List[int]:
    """Evenly spaced expanding-window origins: the first leaves
    ``min_train`` (default: half the panel) observations to fit on, the
    last leaves exactly ``horizon`` actuals to score against."""
    horizon = int(horizon)
    last = int(n_time) - horizon
    lo = int(min_train) if min_train is not None else max(8, n_time // 2)
    if last < lo:
        raise ValueError(
            f"panel of {n_time} obs cannot hold a {horizon}-step "
            f"backtest with min_train={lo}")
    n_windows = int(n_windows)
    if n_windows < 1:
        raise ValueError("n_windows must be >= 1")
    if n_windows == 1 or last == lo:
        return [last]
    step = max(1, (last - lo) // (n_windows - 1))
    origins = [lo + i * step for i in range(n_windows - 1)]
    origins.append(last)
    return sorted(set(origins))


def _norm_kwargs(kwargs: Optional[dict]):
    def norm(v):
        if isinstance(v, (list, tuple)):
            return tuple(norm(x) for x in v)
        return v

    return tuple(sorted((k, norm(v)) for k, v in (kwargs or {}).items()))


def _actuals(y, origin: int, horizon: int) -> np.ndarray:
    """Held-out actuals ``y[:, origin:origin+horizon]`` on the host."""
    if isinstance(y, source_mod.ChunkSource):
        b, t = int(y.shape[0]), int(y.shape[1])
        out = np.empty((b, horizon), y.dtype)
        step = max(1, int(y.default_chunk_rows or 4096))
        buf = np.empty((step, t), y.dtype)
        for lo in range(0, b, step):
            hi = min(lo + step, b)
            y.read_rows(lo, hi, buf[: hi - lo])
            out[lo:hi] = buf[: hi - lo, origin:origin + horizon]
        return out
    return np.array(np.asarray(y)[:, origin:origin + horizon])


def _window_panel(y, origin: int):
    """The window's training panel ``y[:, :origin]`` in the input's own
    residency (sources stay streamed via a column window)."""
    if isinstance(y, source_mod.ChunkSource):
        return augment.ColumnBlockSource([(y, 0, origin)])
    import jax.numpy as jnp

    return jnp.asarray(y)[:, :origin]


def _window_metrics(point, lo, hi, actual, level) -> dict:
    """Per-horizon + per-row error metrics (float64 host reductions —
    fixed iteration order, deterministic bytes)."""
    point = np.asarray(point, np.float64)
    actual = np.asarray(actual, np.float64)
    err = point - actual
    mask = np.isfinite(point) & np.isfinite(actual)
    errz = np.where(mask, err, 0.0)
    n_h = mask.sum(axis=0)
    n_r = mask.sum(axis=1)
    with np.errstate(invalid="ignore", divide="ignore"):
        mae_h = np.where(n_h > 0, np.abs(errz).sum(0) / np.maximum(n_h, 1),
                         np.nan)
        rmse_h = np.where(n_h > 0,
                          np.sqrt((errz ** 2).sum(0) / np.maximum(n_h, 1)),
                          np.nan)
        denom_ok = mask & (np.abs(actual) > 1e-8)
        ape = np.where(denom_ok, np.abs(err) / np.maximum(
            np.abs(actual), 1e-8), 0.0)
        nd = denom_ok.sum(axis=0)
        mape_h = np.where(nd > 0, ape.sum(0) / np.maximum(nd, 1), np.nan)
        mae_row = np.where(n_r > 0, np.abs(errz).sum(1)
                           / np.maximum(n_r, 1), np.nan)
        rmse_row = np.where(n_r > 0,
                            np.sqrt((errz ** 2).sum(1)
                                    / np.maximum(n_r, 1)), np.nan)
    out = {
        "n_h": n_h.astype(np.int64), "mae_h": mae_h, "rmse_h": rmse_h,
        "mape_h": mape_h, "mae_row": mae_row, "rmse_row": rmse_row,
        "n_row": n_r.astype(np.int64),
    }
    if lo is not None and hi is not None:
        lo = np.asarray(lo, np.float64)
        hi = np.asarray(hi, np.float64)
        cmask = mask & np.isfinite(lo) & np.isfinite(hi)
        inside = cmask & (actual >= lo) & (actual <= hi)
        nc = cmask.sum(axis=0)
        out["coverage_h"] = np.where(
            nc > 0, inside.sum(0) / np.maximum(nc, 1), np.nan)
        out["coverage_n_h"] = nc.astype(np.int64)
        out["coverage_level"] = np.float64(level)
    return out


def _metrics_digest(arrays: dict) -> str:
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(f"{name}:{a.shape}:{a.dtype}".encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def _panel_prefix_digest(y, t_cols: int) -> str:
    """Residency-independent content digest of ``y[:, :t_cols]``.

    Streams sources chunk-by-chunk, so the same bytes hash identically
    whether the panel lives in RAM, on device, or in npz/parquet shards.
    This is what lets a grown campaign prove its prefix IS the prior
    campaign's panel (``delta=True`` window adoption): the prior
    manifest records the digest of its full panel, and the grown run
    recomputes the digest of its first ``t_prior`` columns.
    """
    h = hashlib.sha256()
    t_cols = int(t_cols)
    if isinstance(y, source_mod.ChunkSource):
        b, t = int(y.shape[0]), int(y.shape[1])
        h.update(f"panel:{b}:{t_cols}:{np.dtype(y.dtype)}".encode())
        step = max(1, int(y.default_chunk_rows or 4096))
        buf = np.empty((step, t), y.dtype)
        for lo in range(0, b, step):
            hi = min(lo + step, b)
            y.read_rows(lo, hi, buf[: hi - lo])
            h.update(np.ascontiguousarray(
                buf[: hi - lo, :t_cols]).tobytes())
    else:
        a = np.asarray(y)
        h.update(f"panel:{a.shape[0]}:{t_cols}:{a.dtype}".encode())
        h.update(np.ascontiguousarray(a[:, :t_cols]).tobytes())
    return h.hexdigest()[:16]


_WINDOW_DIR_RE = re.compile(r"^window_(\d{5})$")
_METRICS_FILE_RE = re.compile(r"^metrics_(\d{5})\.npz$")


def _adopt_prior_campaign(prior: dict, *, mp: str, root: str, y,
                          n_rows: int, n_time: int, horizon: int,
                          origins: Sequence[int],
                          window_config_hash: str):
    """Adopt a grown campaign's committed windows from a prior manifest.

    A committed window is adopted verbatim (zero fit compute) when the
    new campaign would reproduce it byte-for-byte: same window identity
    (``window_config_hash`` — everything but the origin grid), same row
    count, the new panel's first ``t_prior`` columns bitwise-equal to
    the prior panel, and the window placed at the SAME (index, origin)
    so its training prefix, held-out actuals, and forecast seed are all
    unchanged.  Every prior origin satisfied ``origin + horizon <=
    t_prior``, so a matching (index, origin) is always fully scoreable
    against the unchanged prefix.

    Non-adopted indices get their prior window dirs / metrics shards
    removed: those fit journals were written under a different training
    prefix and would be rejected as stale by the chunk journal anyway.

    Returns ``(adopted_windows, delta_info)`` or raises
    :class:`StaleBacktestError` when the prior campaign is ineligible.
    """

    def _reject(why: str):
        raise StaleBacktestError(
            f"{mp} cannot seed a delta campaign: {why}. Use a fresh "
            "directory or remove the stale manifest explicitly.")

    if prior.get("window_config_hash") != window_config_hash:
        _reject("window_config_hash mismatch — the per-window config "
                "(model/knobs/horizon/chunk grid) changed, so no prior "
                "window is reproducible")
    if int(prior.get("n_rows", -1)) != n_rows:
        _reject(f"row count changed ({prior.get('n_rows')} != {n_rows})")
    t_prior = int(prior.get("n_time", -1))
    if not 0 < t_prior <= n_time:
        _reject(f"prior n_time {t_prior} is not a prefix of {n_time}")
    prior_digest = prior.get("panel_digest")
    if prior_digest is None:
        _reject("prior manifest has no panel_digest (written before "
                "delta-eligible campaigns)")
    got = _panel_prefix_digest(y, t_prior)
    if got != prior_digest:
        _reject(f"the new panel's first {t_prior} columns differ from "
                f"the prior panel (digest {got} != {prior_digest}) — "
                "history was revised, not appended")

    adopted: List[dict] = []
    keep = set()
    for w in prior.get("windows", []):
        i, origin = int(w.get("index", -1)), int(w.get("origin", -1))
        if (w.get("status") == "committed" and 0 <= i < len(origins)
                and int(origins[i]) == origin
                and origin + horizon <= t_prior):
            entry = dict(w)
            entry["window_class"] = "adopted"
            adopted.append(entry)
            keep.add(i)
    adopted.sort(key=lambda w: int(w["index"]))
    # sweep artifacts of non-adopted indices: their journals belong to
    # the superseded origin grid and would be rejected as stale
    for name in sorted(os.listdir(root)):
        m = _WINDOW_DIR_RE.match(name) or _METRICS_FILE_RE.match(name)
        if m is None or int(m.group(1)) in keep:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except OSError:
                pass
    delta_info = {
        "prior_campaign_hash": prior.get("campaign_hash"),
        "prior_n_time": t_prior,
        "adopted": len(adopted),
        "recomputed": len(origins) - len(adopted),
    }
    obs.event("backtest.delta_adopted", adopted=len(adopted),
              recomputed=len(origins) - len(adopted), prior_n_time=t_prior)
    return adopted, delta_info


def _write_metrics_npz(path: str, arrays: dict) -> None:
    """Atomic npz write of one window's metrics shard (tmp -> fsync ->
    replace, the journal's own durability primitive)."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=".npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_backtest_manifest(root: str, manifest: dict) -> None:
    """Atomic rewrite of the campaign manifest — the single writer is
    the campaign driver, after each window commits."""
    manifest["updated_at"] = time.time()  # lint: nondet(manifest wall-clock metadata; never in metric bytes)
    journal_mod._atomic_write_bytes(
        os.path.join(root, BACKTEST_MANIFEST),
        (json.dumps(manifest, indent=1, sort_keys=True) + "\n").encode())


def _round_list(a, nd: int = 6) -> list:
    return [None if not np.isfinite(v) else round(float(v), nd)
            for v in np.asarray(a, np.float64)]


def run_backtest(
    y,
    model: str,
    horizon: int,
    *,
    origins: Optional[Sequence[int]] = None,
    n_windows: int = 4,
    min_train: Optional[int] = None,
    model_kwargs: Optional[dict] = None,
    fit_kwargs: Optional[dict] = None,
    warm_start: bool = True,
    intervals: bool = False,
    level: float = 0.9,
    n_samples: int = 256,
    seed: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    resume: str = "auto",
    delta: bool = False,
    chunk_rows: Optional[int] = None,
    pipeline: bool = True,
    pipeline_depth: int = 2,
    prefetch_depth: int = 1,
    shard: bool = False,
    mesh=None,
    chunk_budget_s: Optional[float] = None,
    job_budget_s: Optional[float] = None,
    server=None,
    _journal_commit_hook=None,
) -> BacktestResult:
    """Run a rolling-origin backtest campaign over ``y [B, T]``.

    ``model`` is a forecast-capable model name (``forecasting.kernels``);
    ``model_kwargs`` its structural config (e.g. ``order=(1, 1, 1)``),
    ``fit_kwargs`` extra per-window fit knobs (``max_iters``, ``tol``,
    ...).  Windows are ``origins`` (explicit time positions) or
    :func:`default_origins`.  Every window's refit rides the durable
    chunk driver under ``checkpoint_dir/window_%05d``; warm starts pack
    the previous window's journaled params into augmented columns
    (models without ``init_params`` refit cold — recorded per window).
    The campaign's own durable state is ``backtest_manifest.json`` plus
    one metrics npz per window, each committed atomically after the
    window scores — a SIGKILLed campaign resumes to bitwise-identical
    metrics.  ``job_budget_s`` bounds the WHOLE campaign (remaining
    windows are skipped with status ``"timeout"``; a resume retries
    them).  ``server=`` routes each window's forecast through a resident
    ``FitServer`` (micro-batched, journaled under the server's root) —
    the backtest doubling as the serving layer's stress client.

    ``delta=True`` makes a GROWN panel adopt the prior campaign in the
    same ``checkpoint_dir``: when the new panel's first ``t_prior``
    columns are bitwise the prior panel (``panel_digest``) and the
    per-window config matches (``window_config_hash``), every committed
    window that lands at the same (index, origin) is adopted verbatim —
    zero fit compute — and only windows whose origins moved or whose
    actuals extend into the appended ticks are refit (warm-started as
    usual).  The completed campaign is bitwise-identical to a fresh
    run on the grown panel; per-class window counts and walls are
    reported in ``meta["window_classes"]``.  ``delta`` changes WHICH
    work is redone, never the bytes, so it is excluded from the
    campaign identity.
    """
    horizon = int(horizon)
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    mk = kernels.normalize_model_kwargs(model, model_kwargs or {})
    cfg = dict(mk)
    k = kernels.param_width(model, cfg)
    fkw = _norm_kwargs(fit_kwargs)
    if isinstance(y, source_mod.ChunkSource):
        b, t = int(y.shape[0]), int(y.shape[1])
    else:
        y = np.asarray(y) if isinstance(y, np.ndarray) else y
        import jax.numpy as jnp

        y = jnp.asarray(y)
        if y.ndim != 2:
            raise ValueError(f"expected [batch, time], got {y.shape}")
        b, t = int(y.shape[0]), int(y.shape[1])
    origins = (sorted(int(o) for o in origins) if origins is not None
               else default_origins(t, horizon, n_windows, min_train))
    if origins[0] < 3 or origins[-1] + horizon > t:
        raise ValueError(
            f"origins {origins} do not fit a {horizon}-step horizon in "
            f"{t} observations")

    fit_fn_cold = _model_fit_fn(model, cfg, dict(fkw))
    warm_capable = warm_start and _supports_init(model)
    campaign_hash = journal_mod.config_hash(
        fit_fn_cold, {"fit_kwargs": fkw},
        extra={"backtest_version": BACKTEST_VERSION, "model": model,
               "model_kwargs": repr(mk), "horizon": horizon,
               "origins": tuple(origins), "warm_start": bool(warm_capable),
               "intervals": bool(intervals),
               "level": float(level) if intervals else None,
               "n_samples": int(n_samples) if intervals else None,
               "seed": seed, "chunk_rows": chunk_rows})
    # window-level identity: everything that pins ONE window's bytes
    # except the origin grid — two campaigns sharing it produce
    # bitwise-identical windows wherever their (index, origin) pairs
    # coincide, which is exactly what ``delta=True`` adoption relies on
    window_config_hash = journal_mod.config_hash(
        fit_fn_cold, {"fit_kwargs": fkw},
        extra={"backtest_version": BACKTEST_VERSION, "model": model,
               "model_kwargs": repr(mk), "horizon": horizon,
               "warm_start": bool(warm_capable),
               "intervals": bool(intervals),
               "level": float(level) if intervals else None,
               "n_samples": int(n_samples) if intervals else None,
               "seed": seed, "chunk_rows": chunk_rows})
    fp = (y.fingerprint() if isinstance(y, source_mod.ChunkSource)
          else journal_mod.panel_fingerprint(y))

    root = None
    manifest = None
    delta_info = None
    if checkpoint_dir is not None:
        root = os.path.abspath(checkpoint_dir)
        os.makedirs(root, exist_ok=True)
        mp = os.path.join(root, BACKTEST_MANIFEST)
        adopted_windows: List[dict] = []
        if os.path.exists(mp):
            try:
                with open(mp, "rb") as f:
                    prior = json.loads(f.read().decode())
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                raise StaleBacktestError(
                    f"{mp} does not parse ({e}); a crash tore the write "
                    "— inspect/remove the campaign directory explicitly."
                ) from e
            mismatches = []
            if prior.get("campaign_hash") != campaign_hash:
                mismatches.append("campaign_hash")
            if prior.get("panel_fingerprint") != fp:
                mismatches.append("panel_fingerprint")
            if int(prior.get("n_rows", -1)) != b:
                mismatches.append("n_rows")
            if mismatches and delta:
                adopted_windows, delta_info = _adopt_prior_campaign(
                    prior, mp=mp, root=root, y=y, n_rows=b, n_time=t,
                    horizon=horizon, origins=origins,
                    window_config_hash=window_config_hash)
            elif mismatches:
                raise StaleBacktestError(
                    f"{mp} was written by a different campaign "
                    f"({', '.join(mismatches)} mismatch); resuming would "
                    "splice foreign metrics — use a fresh directory, "
                    "remove the stale one explicitly, or pass delta=True "
                    "to adopt a prior campaign's windows on a grown "
                    "panel.")
            else:
                manifest = prior
        if manifest is None:
            manifest = {
                "kind": "backtest",
                "backtest_version": BACKTEST_VERSION,
                "created_at": time.time(),  # lint: nondet(manifest wall-clock metadata; never in metric bytes)
                "campaign_hash": campaign_hash,
                "window_config_hash": window_config_hash,
                "panel_fingerprint": fp,
                "panel_digest": _panel_prefix_digest(y, t),
                "n_rows": b,
                "n_time": t,
                "model": model,
                "model_kwargs": {key: (list(v) if isinstance(v, tuple)
                                       else v) for key, v in cfg.items()},
                "horizon": horizon,
                "origins": list(origins),
                "warm_start": bool(warm_capable),
                "intervals": bool(intervals),
                "level": float(level) if intervals else None,
                "n_samples": int(n_samples) if intervals else None,
                "windows": adopted_windows,
                **({"delta": delta_info} if delta_info else {}),
            }
            _write_backtest_manifest(root, manifest)

    by_index = {int(w["index"]): w
                for w in (manifest or {}).get("windows", [])}
    walk_knobs = dict(chunk_rows=chunk_rows, resume=resume,
                      pipeline=pipeline, pipeline_depth=pipeline_depth,
                      prefetch_depth=prefetch_depth, shard=shard,
                      mesh=mesh, chunk_budget_s=chunk_budget_s,
                      _journal_commit_hook=_journal_commit_hook)
    t0 = time.perf_counter()

    def _budget_left() -> Optional[float]:
        if job_budget_s is None:
            return None
        return job_budget_s - (time.perf_counter() - t0)

    windows_out: List[dict] = []
    metric_arrays: List[dict] = []
    class_counts = {"adopted": 0, "warm": 0, "cold": 0}
    class_wall_s = {"adopted": 0.0, "warm": 0.0, "cold": 0.0}
    prev_res = None  # previous window's fit result (warm-start source)
    for i, origin in enumerate(origins):
        fit_dir = (os.path.join(root, f"window_{i:05d}")
                   if root is not None else None)
        metrics_name = f"metrics_{i:05d}.npz"
        committed = by_index.get(i)
        if committed is not None and committed.get("status") == "committed":
            t_skip = time.perf_counter()
            mpath = os.path.join(root, metrics_name)
            try:
                with np.load(mpath, allow_pickle=False) as z:
                    arrays = {key: np.array(z[key]) for key in z.files}
            except (OSError, ValueError, KeyError):
                arrays = None
            if arrays is not None and \
                    _metrics_digest(arrays) == committed.get("digest"):
                cls = committed.get("window_class") or (
                    "warm" if committed.get("warm_start") else "cold")
                entry = dict(committed)
                entry["window_class"] = cls
                class_counts[cls] = class_counts.get(cls, 0) + 1
                class_wall_s[cls] = (class_wall_s.get(cls, 0.0)
                                     + time.perf_counter() - t_skip)
                metric_arrays.append(arrays)
                windows_out.append(entry)
                prev_res = None  # reload lazily only if a later window fits
                obs.event("backtest.window_skipped", window=i,
                          origin=origin, window_class=cls)
                continue
            # torn/missing metrics shard: recompute the window (the fit
            # journal makes that cheap — committed chunks replay)
        left = _budget_left()
        if left is not None and left <= 0:
            entry = {"index": i, "origin": int(origin),
                     "status": "timeout"}
            windows_out.append(entry)
            obs.event("backtest.window_timeout", window=i, origin=origin)
            continue
        with obs.span("backtest.window", window=i, origin=int(origin)):
            t_w = time.perf_counter()
            y_win = _window_panel(y, origin)
            warm = warm_capable and i > 0
            if warm and prev_res is None and root is not None:
                prev_dir = os.path.join(root, f"window_{i - 1:05d}")
                if os.path.exists(os.path.join(prev_dir, "manifest.json")):
                    prev_res = load_fit_result(prev_dir)
            warm = warm and prev_res is not None \
                and np.asarray(prev_res.params).shape == (b, k)
            from ..reliability import fit_chunked

            if warm:
                init = np.asarray(prev_res.params)[:, :k]
                st = augment.derive_status(init, prev_res.status)
                aug, nt_w, _ = augment.augmented_panel(y_win, init, st)
                fit_res = fit_chunked(
                    walk_mod.warmstart_fit, aug, resilient=False,
                    checkpoint_dir=fit_dir,
                    job_budget_s=_budget_left(),
                    journal_extra={"backtest": {
                        "window": i, "origin": int(origin),
                        "warm_start": True}},
                    model=model, n_time=nt_w, k=k,
                    model_kwargs=mk + fkw, **walk_knobs)
            else:
                fit_res = fit_chunked(
                    fit_fn_cold, y_win, resilient=False,
                    checkpoint_dir=fit_dir,
                    job_budget_s=_budget_left(),
                    journal_extra={"backtest": {
                        "window": i, "origin": int(origin),
                        "warm_start": False}},
                    **walk_knobs)
            fc = _window_forecast(
                model, cfg, fit_res, y_win, horizon,
                intervals=intervals, level=level, n_samples=n_samples,
                seed=(None if seed is None else int(seed) + i),
                server=server)
            actual = _actuals(y, origin, horizon)
            arrays = _window_metrics(fc.forecast, fc.lo, fc.hi, actual,
                                     level)
            arrays["origin"] = np.int64(origin)
            arrays["window"] = np.int64(i)
            wall = time.perf_counter() - t_w
        digest = _metrics_digest(arrays)
        cls = "warm" if warm else "cold"
        class_counts[cls] += 1
        class_wall_s[cls] += wall
        entry = {
            "index": i, "origin": int(origin), "status": "committed",
            "rows": b, "horizon": horizon,
            "warm_start": bool(warm),
            "window_class": cls,
            "fit_dir": (f"window_{i:05d}" if root is not None else None),
            "metrics_file": metrics_name if root is not None else None,
            "digest": digest,
            "wall_s": round(wall, 4),
            "fit_status_counts": fit_res.meta.get("status_counts"),
            "mae": _round_list(arrays["mae_h"]),
            "rmse": _round_list(arrays["rmse_h"]),
            "mape": _round_list(arrays["mape_h"]),
            **({"coverage": _round_list(arrays["coverage_h"])}
               if "coverage_h" in arrays else {}),
        }
        if root is not None:
            _write_metrics_npz(os.path.join(root, metrics_name), arrays)
            manifest["windows"] = [w for w in manifest["windows"]
                                   if int(w["index"]) != i]
            manifest["windows"].append(entry)
            manifest["windows"].sort(key=lambda w: int(w["index"]))
            _write_backtest_manifest(root, manifest)
        metric_arrays.append(arrays)
        windows_out.append(entry)
        prev_res = fit_res
        obs.counter("backtest.windows").inc()
        obs.event("backtest.window_committed", window=i,
                  origin=int(origin), wall_s=round(wall, 4))

    agg = _aggregate(metric_arrays, horizon, intervals)
    meta = {
        "model": model, "horizon": horizon, "origins": list(origins),
        "campaign_hash": campaign_hash, "panel_fingerprint": fp,
        "n_rows": b, "warm_start": bool(warm_capable),
        "windows_committed": sum(1 for w in windows_out
                                 if w.get("status") == "committed"),
        "windows_timeout": sum(1 for w in windows_out
                               if w.get("status") == "timeout"),
        "window_classes": {
            "counts": class_counts,
            "wall_s": {key: round(v, 4)
                       for key, v in class_wall_s.items()},
        },
        "wall_s": round(time.perf_counter() - t0, 4),
        **({"delta": delta_info} if delta_info else {}),
    }
    return BacktestResult(windows_out, agg,
                          (os.path.join(root, BACKTEST_MANIFEST)
                           if root is not None else None), meta)


def _aggregate(metric_arrays: List[dict], horizon: int,
               intervals: bool) -> dict:
    """Campaign-level per-horizon aggregates, row-count-weighted across
    windows (deterministic fixed-order float64 sums)."""
    if not metric_arrays:
        return {"windows": 0}
    n = np.zeros(horizon, np.float64)
    mae = np.zeros(horizon, np.float64)
    rmse2 = np.zeros(horizon, np.float64)
    mape = np.zeros(horizon, np.float64)
    cov = np.zeros(horizon, np.float64)
    ncov = np.zeros(horizon, np.float64)
    for a in metric_arrays:
        w = a["n_h"].astype(np.float64)
        m = np.nan_to_num(a["mae_h"], nan=0.0)
        r = np.nan_to_num(a["rmse_h"], nan=0.0)
        p = np.nan_to_num(a["mape_h"], nan=0.0)
        n += w
        mae += m * w
        rmse2 += (r ** 2) * w
        mape += p * w
        if "coverage_h" in a:
            cw = a["coverage_n_h"].astype(np.float64)
            cov += np.nan_to_num(a["coverage_h"], nan=0.0) * cw
            ncov += cw
    with np.errstate(invalid="ignore", divide="ignore"):
        out = {
            "windows": len(metric_arrays),
            "n_h": n.astype(np.int64).tolist(),
            "mae_h": _round_list(np.where(n > 0, mae / np.maximum(n, 1),
                                          np.nan)),
            "rmse_h": _round_list(np.where(
                n > 0, np.sqrt(rmse2 / np.maximum(n, 1)), np.nan)),
            "mape_h": _round_list(np.where(n > 0, mape / np.maximum(n, 1),
                                           np.nan)),
        }
        if intervals and ncov.any():
            out["coverage_h"] = _round_list(
                np.where(ncov > 0, cov / np.maximum(ncov, 1), np.nan))
    return out


def _model_fit_fn(model: str, cfg: dict, fit_kwargs: dict):
    """The cold per-window fit partial (keyword-bound so the journal's
    config hash covers the model structure and every fit knob)."""
    import functools

    from .. import models as _models

    mod = getattr(_models, model, None)
    if mod is None or not hasattr(mod, "fit"):
        raise ValueError(f"unknown model {model!r}")
    kw = dict(fit_kwargs)
    if model == "arima":
        kw["order"] = tuple(cfg["order"])
        kw["include_intercept"] = cfg["include_intercept"]
    elif model == "autoregression":
        kw["max_lag"] = cfg["max_lag"]
    elif model == "holtwinters":
        kw["period"] = cfg["period"]
        kw["model_type"] = cfg["model_type"]
    return functools.partial(mod.fit, **kw)


def _supports_init(model: str) -> bool:
    import inspect

    from .. import models as _models

    mod = getattr(_models, model, None)
    fit = getattr(mod, "fit", None)
    if fit is None:
        return False
    try:
        return "init_params" in inspect.signature(fit).parameters
    except (TypeError, ValueError):
        return False


def _window_forecast(model, cfg, fit_res, y_win, horizon, *, intervals,
                     level, n_samples, seed, server):
    """One window's forecast: the local serial walk, or — the stress
    client — the resident ``FitServer``'s micro-batched forecast path."""
    if server is None:
        return walk_mod.forecast_chunked(
            model, fit_res, y_win, horizon, model_kwargs=cfg,
            intervals=intervals, level=level, n_samples=n_samples,
            seed=seed)
    values = (np.asarray(y_win) if not isinstance(
        y_win, source_mod.ChunkSource) else _materialize(y_win))
    ticket = server.submit_forecast(
        "backtest", values, fit_res, model=model, horizon=horizon,
        model_kwargs=cfg, intervals=intervals, level=level,
        n_samples=n_samples, seed=seed)
    return walk_mod.as_result(ticket.result(), horizon, intervals)


def _materialize(src) -> np.ndarray:
    out = np.empty(tuple(int(s) for s in src.shape), src.dtype)
    step = max(1, int(src.default_chunk_rows or 4096))
    for lo in range(0, out.shape[0], step):
        hi = min(lo + step, out.shape[0])
        src.read_rows(lo, hi, out[lo:hi])
    return out
