"""Durable per-tenant auto-fit profiles (ISSUE 19).

ROADMAP item 1's warm half: the fleet's shared checkpoint root is where
per-TENANT state becomes fleet-wide instead of per-process, and the
:class:`TenantProfileStore` is that state — one npz per tenant under
``<root>/profiles/`` recording the tenant's last winning orders, fitted
params, panel fingerprint, and a stability counter.  A repeat auto-fit
submit classifies against its profile:

- **stable** — the panel's prefix fingerprint, row count, and fit config
  all match: stage 1 is skipped entirely (a warm-started refit of each
  row's known winning order, ``reliability.delta.WarmstartFit``).
- **drifted** — same shape/config but the content moved: a stepwise
  search seeded from the profile's distinct winners.
- **new** — no profile, or the shape/config changed: the full stepwise
  search (or the exhaustive grid in exact mode).

Writes go through ``journal.durable_replace`` (tmp + fsync + replace —
whole file or previous content, never torn) and are lease-FENCED like
every primary write on a fleet root: the store's ``fence`` callable runs
before bytes land, so a zombie primary dies loudly in ``FencedError``
instead of clobbering the survivor's warm state.  Standbys (and tools)
read profiles without any lease — reads are just npz loads, cached per
``(mtime, size)`` so a takeover sees the dead primary's last durable
update by construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..reliability import journal as journal_mod

__all__ = ["TenantProfileStore", "config_key"]

# profile schema version: bump when the npz field layout changes so an
# old profile degrades to a "new" classification, never a misread
PROFILE_VERSION = 1

_ARRAY_FIELDS = ("params", "order_index", "criterion", "status", "orders")


def config_key(fit_kwargs: dict) -> str:
    """Digest of the fit configuration a profile's params were won under.

    Everything that changes the fit OUTPUT must count (criterion,
    intercept, iteration budget, backend, the candidate grid, ...) —
    routing knobs that only change HOW the search runs (``warm_routing``
    itself) are excluded by the caller.  Sorted-JSON over the kwargs, so
    the key is stable across submit spellings and the wire round-trip.
    """
    payload = json.dumps(fit_kwargs, sort_keys=True, default=repr)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _safe_name(tenant: str) -> str:
    """Collision-safe filename for a tenant id: a sanitized prefix for
    humans plus a content digest for uniqueness."""
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", str(tenant))[:48]
    digest = hashlib.sha256(str(tenant).encode()).hexdigest()[:10]
    return f"{safe}-{digest}"


class TenantProfileStore:
    """Durable tenant profiles on a (possibly fleet-shared) root.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): the read cache
        mutates only under its lock — the serve loop updates profiles
        while caller threads classify repeat submits, and tools/standby
        readers may share an instance.

    ``fence`` is the write-side fencing hook: when set (the fleet's
    primary sets it to ``Lease.check``), it runs before EVERY profile
    write and must raise to refuse the write — profile updates obey the
    same zombie-writer discipline as result stores and journal commits.
    Plain (non-fleet) servers leave it ``None``.

    ``max_age_s`` / ``max_profiles`` bound the store (ISSUE 20 residue
    of ISSUE 19): profiles older than ``max_age_s`` since their last
    update, and the oldest profiles beyond ``max_profiles``, are
    evicted — a dormant tenant's warm state must not hold the shared
    root's disk forever.  Eviction runs after every fenced
    :meth:`update` and on demand via :meth:`evict`; deletes are fenced
    exactly like writes (a zombie primary must not reap the survivor's
    profiles).  ``clock`` is injectable for tests; wall-clock here is
    metadata-only and never feeds fitted bytes.
    """

    _protected_by_ = {"_cache": "_lock"}

    def __init__(self, root: str, *, fence: Optional[Callable] = None,
                 max_age_s: Optional[float] = None,
                 max_profiles: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        self.root = os.path.abspath(root)
        self.fence = fence
        self.max_age_s = (float(max_age_s) if max_age_s is not None
                          else None)
        self.max_profiles = (int(max_profiles) if max_profiles is not None
                             else None)
        self._clock = clock
        self._lock = threading.Lock()
        self._cache: Dict[str, tuple] = {}

    def path(self, tenant: str) -> str:
        return os.path.join(self.root, f"{_safe_name(tenant)}.npz")

    # -- reads (unfenced: standbys and tools read freely) --------------------

    def load(self, tenant: str) -> Optional[dict]:
        """The tenant's profile dict, or ``None`` (absent/torn/stale
        version).  Cached per ``(mtime_ns, size)``: a fresh write — ours
        or a peer primary's on the shared root — invalidates by
        construction."""
        path = self.path(tenant)
        try:
            st = os.stat(path)
        except OSError:
            return None
        key = (st.st_mtime_ns, st.st_size)
        with self._lock:
            ent = self._cache.get(tenant)
            if ent is not None and ent[0] == key:
                return ent[1]
        prof = self._read(path)
        with self._lock:
            self._cache[tenant] = (key, prof)
        return prof

    def _read(self, path: str) -> Optional[dict]:
        try:
            with np.load(path) as z:
                meta = json.loads(bytes(z["meta"].tobytes()).decode())
                prof = {f: np.array(z[f]) for f in _ARRAY_FIELDS}
        except Exception:  # noqa: BLE001 - torn/foreign bytes, not a bug
            return None
        if meta.get("version") != PROFILE_VERSION:
            return None
        prof.update(meta)
        return prof

    def tenants(self) -> list:
        """Sorted tenant ids with a readable profile on this root (the
        budget advisor's iteration surface)."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".npz") or fn.startswith(".tmp-"):
                continue
            prof = self._read(os.path.join(self.root, fn))
            if prof is not None:
                out.append(prof["tenant"])
        return sorted(out)

    # -- classification ------------------------------------------------------

    def classify(self, tenant: str, values: np.ndarray,
                 cfg_key: str) -> tuple:
        """``(route, profile)`` for a repeat submit: ``"stable"`` when the
        panel's first ``prefix_cols`` columns fingerprint-match the
        profile (an exact repeat AND an appended-ticks panel both
        qualify — the profile's params warm-start the longer panel),
        ``"drifted"`` when the shape/config match but the content moved,
        ``"new"`` otherwise."""
        prof = self.load(tenant)
        if prof is None:
            return "new", None
        values = np.asarray(values)
        if (prof.get("config_key") != cfg_key
                or int(prof.get("rows", -1)) != int(values.shape[0])
                or int(values.shape[1]) < int(prof.get("prefix_cols", 0))):
            return "new", prof
        pc = int(prof["prefix_cols"])
        fp = journal_mod.panel_fingerprint(values[:, :pc])
        if fp == prof.get("fingerprint"):
            return "stable", prof
        return "drifted", prof

    # -- writes (fenced, durable) --------------------------------------------

    def update(self, tenant: str, *, values: np.ndarray, orders,
               order_index, params, criterion, status, cfg_key: str,
               criterion_name: str, include_intercept: bool,
               route: str) -> dict:
        """Record one completed auto-fit pass for ``tenant`` — fenced,
        then durable via ``journal.durable_replace``.

        The stability counter compares each row's winning ORDER (not its
        grid index — stepwise grids grow between passes) against the
        previous profile: an unchanged winner map increments it, any
        movement resets it to 0.  Returns the profile as written.
        """
        values = np.asarray(values)
        order_index = np.asarray(order_index, np.int32)
        orders = np.asarray(orders, np.int32).reshape(-1, 3)
        prev = self.load(tenant)
        stability = 0
        if prev is not None and prev.get("config_key") == cfg_key and \
                int(prev["rows"]) == int(values.shape[0]):
            if np.array_equal(_winner_orders(prev["orders"],
                                             prev["order_index"]),
                              _winner_orders(orders, order_index)):
                stability = int(prev.get("stability", 0)) + 1
        meta = {
            "version": PROFILE_VERSION,
            "updated_at": float(self._clock()),
            "tenant": str(tenant),
            "fingerprint": journal_mod.panel_fingerprint(values),
            "prefix_cols": int(values.shape[1]),
            "n_time": int(values.shape[1]),
            "rows": int(values.shape[0]),
            "stability": stability,
            "passes": (int(prev.get("passes", 0)) + 1
                       if prev is not None else 1),
            "config_key": str(cfg_key),
            "criterion_name": str(criterion_name),
            "include_intercept": bool(include_intercept),
            "route": str(route),
        }
        arrays = {
            "params": np.asarray(params),
            "order_index": order_index,
            "criterion": np.asarray(criterion),
            "status": np.asarray(status, np.int8),
            "orders": orders,
        }

        def _write(f):
            np.savez(f, meta=np.frombuffer(json.dumps(meta).encode(),
                                           dtype=np.uint8), **arrays)

        if self.fence is not None:
            # the fencing contract: the token check precedes the bytes —
            # a zombie primary raises FencedError HERE, before the
            # survivor's warm state can be clobbered
            self.fence()
        os.makedirs(self.root, exist_ok=True)
        journal_mod.durable_replace(self.path(tenant), _write,
                                    fault_kind="profile")
        with self._lock:
            self._cache.pop(tenant, None)
        if self.max_age_s is not None or self.max_profiles is not None:
            self.evict()
        prof = dict(meta)
        prof.update(arrays)
        return prof

    def evict(self, now: Optional[float] = None) -> List[str]:
        """Reap expired and over-count profiles; returns evicted tenants.

        Age expiry first (``updated_at`` older than ``max_age_s``; a
        profile without the stamp — written before eviction existed —
        counts as oldest), then the count bound keeps the
        ``max_profiles`` NEWEST by ``updated_at``.  Each unlink is
        fenced like a write: on a fleet root only the leaseholder may
        reap, and a zombie dies in ``FencedError`` before the first
        delete.
        """
        now = float(self._clock()) if now is None else float(now)
        profs = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        for fn in names:
            if not fn.endswith(".npz") or fn.startswith(".tmp-"):
                continue
            path = os.path.join(self.root, fn)
            prof = self._read(path)
            if prof is None:
                continue
            profs.append((float(prof.get("updated_at", -1.0)),
                          str(prof["tenant"]), path))
        doomed = []
        if self.max_age_s is not None:
            doomed = [p for p in profs if now - p[0] > self.max_age_s]
            profs = [p for p in profs if now - p[0] <= self.max_age_s]
        if self.max_profiles is not None and len(profs) > self.max_profiles:
            profs.sort(key=lambda p: (p[0], p[1]))
            cut = len(profs) - self.max_profiles
            doomed.extend(profs[:cut])
        if not doomed:
            return []
        if self.fence is not None:
            # deletes obey the same zombie-writer discipline as writes
            self.fence()
        evicted = []
        for _, tenant, path in doomed:
            try:
                os.unlink(path)
            except OSError:
                continue
            evicted.append(tenant)
            with self._lock:
                self._cache.pop(tenant, None)
        return sorted(evicted)


def _winner_orders(orders: np.ndarray, order_index: np.ndarray) -> np.ndarray:
    """Per-row winning order TUPLES (``[B, 3]``; ``-1`` rows map to
    ``(-1, -1, -1)``) — the grid-independent spelling of a selection, so
    stability survives stepwise grids that grow between passes."""
    orders = np.asarray(orders, np.int64).reshape(-1, 3)
    idx = np.asarray(order_index, np.int64)
    out = np.full((idx.shape[0], 3), -1, np.int64)
    ok = idx >= 0
    out[ok] = orders[idx[ok]]
    return out
