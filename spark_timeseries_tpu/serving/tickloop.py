"""Tick-to-forecast streaming loop (ISSUE 20 / ROADMAP item 2).

Everything below this module already knows how to do its step of the
market-data story durably: shard dirs append new time columns
idempotently (``write_npz_shards(append_time=..., expect_time=...)``),
``fit_chunked(delta_from=...)`` warm-refits a grown panel from the
previous fit's journal, and ``forecast_chunked(sink=...)`` streams the
packed forecasts straight into durable output shards without ever
holding the panel's results in RAM.  :class:`TickLoop` is the daemon
that strings them into ONE journaled cycle::

    tick batch -> record -> append -> delta-warm refit -> forecast
               -> publish (write-back sink)

Each cycle lives under ``<root>/cycle_%05d/`` with a durable
``tick_manifest.json`` recording the stage progression
(``ticked -> appended -> fitted -> published``), per-stage walls, and
the delta adoption counts.  The tick batch itself is recorded durably
(``ticks.npz``) BEFORE anything mutates the data dir, so a SIGKILL at
ANY point — mid-append (some shards grown, some not), mid-fit,
mid-publish — resumes from the recorded ticks and finishes the cycle
bitwise-identical to an uninterrupted run: the append is
width-gated idempotent, the fit and forecast walks replay their chunk
journals, and the write-back sink re-emits committed spans through
``durable_replace`` with the same bytes.

The loop is the serving layer's ingestion twin: ``FitServer`` answers
"fit this panel now"; ``TickLoop`` answers "the panel grew again" —
forever, at O(chunk) incremental cost per cycle.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import List, NamedTuple, Optional

import numpy as np

from .. import obs
from ..forecasting import walk as walk_mod
from ..reliability import journal as journal_mod
from ..reliability import sink as sink_mod
from ..reliability import source as source_mod

__all__ = ["TickLoop", "TickLoopError", "CycleResult",
           "TICKLOOP_MANIFEST", "CYCLE_MANIFEST", "TICKLOOP_VERSION"]

TICKLOOP_MANIFEST = "tickloop.json"
CYCLE_MANIFEST = "tick_manifest.json"
TICKLOOP_VERSION = 1

_CYCLE_DIR_RE = re.compile(r"^cycle_(\d{5})$")


class TickLoopError(RuntimeError):
    """The tick-loop root is torn, stale, or fed inconsistent ticks."""


class CycleResult(NamedTuple):
    """One completed cycle: where its forecasts landed + accounting."""

    cycle: int
    published_dir: str
    manifest_path: str
    meta: dict


def _write_json_atomic(path: str, payload: dict) -> None:
    journal_mod._atomic_write_bytes(
        path, (json.dumps(payload, indent=1, sort_keys=True)
               + "\n").encode())


def _load_json(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise TickLoopError(
            f"{path} does not parse ({e}); a crash tore the write — "
            "inspect/remove it explicitly.") from e


class TickLoop:
    """Durable append -> delta-refit -> forecast -> publish cycles.

    ``data_dir`` is an npz or parquet shard directory holding the
    panel; it is the ONLY mutable input state, and only grows (columns
    appended, never revised).  ``root`` holds the loop's own durable
    record: ``tickloop.json`` (loop identity — reopened loops must
    match it) and one ``cycle_%05d/`` per tick batch.

    Each :meth:`run_cycle` call first finishes any incomplete prior
    cycle from its recorded ticks (:meth:`resume`), then runs the new
    batch end to end.  Publishing streams through a write-back sink:
    the packed forecasts land as durable ``out_*.npz`` shards under
    ``cycle_%05d/published`` and are readable back with
    ``NpzShardSource(published_dir, key="params")`` — the loop never
    materializes a full forecast panel on the host.
    """

    def __init__(self, root: str, data_dir: str, *,
                 model: str = "arima",
                 model_kwargs: Optional[dict] = None,
                 fit_kwargs: Optional[dict] = None,
                 horizon: int = 8,
                 intervals: bool = False,
                 level: float = 0.9,
                 n_samples: int = 256,
                 seed: Optional[int] = None,
                 chunk_rows: Optional[int] = None,
                 pipeline: bool = True,
                 delta: bool = True):
        from ..forecasting import backtest as backtest_mod
        from ..forecasting import kernels

        self.root = os.path.abspath(root)
        self.data_dir = os.path.abspath(data_dir)
        os.makedirs(self.root, exist_ok=True)
        src = source_mod.as_source(self.data_dir)
        b, t0 = int(src.shape[0]), int(src.shape[1])
        self._layout = ("parquet" if src.kind.startswith("parquet")
                        else "npz")
        cfg = dict(kernels.normalize_model_kwargs(model,
                                                  model_kwargs or {}))
        self.model = model
        self.model_kwargs = dict(cfg)
        self.fit_kwargs = dict(fit_kwargs or {})
        self.horizon = int(horizon)
        self.intervals = bool(intervals)
        self.level = float(level)
        self.n_samples = int(n_samples)
        self.seed = seed
        self.chunk_rows = chunk_rows
        self.pipeline = bool(pipeline)
        self.delta = bool(delta)
        self._fit_fn = backtest_mod._model_fit_fn(model, cfg,
                                                  dict(self.fit_kwargs))
        config = {
            "model": model, "model_kwargs": repr(sorted(cfg.items())),
            "fit_kwargs": repr(sorted(self.fit_kwargs.items())),
            "horizon": self.horizon, "intervals": self.intervals,
            "level": self.level if self.intervals else None,
            "n_samples": self.n_samples if self.intervals else None,
            "seed": seed,
            "chunk_rows": (int(chunk_rows) if chunk_rows else None),
        }
        mp = os.path.join(self.root, TICKLOOP_MANIFEST)
        prior = _load_json(mp)
        if prior is not None:
            bad = []
            if prior.get("kind") != "tickloop":
                bad.append("kind")
            if int(prior.get("n_rows", -1)) != b:
                bad.append("n_rows")
            if prior.get("config") != config:
                bad.append("config")
            if bad:
                raise TickLoopError(
                    f"{mp} was written by a different loop "
                    f"({', '.join(bad)} mismatch); resuming would splice "
                    "foreign cycles — use a fresh root or remove the "
                    "stale one explicitly.")
            self._manifest = prior
        else:
            self._manifest = {
                "kind": "tickloop",
                "tickloop_version": TICKLOOP_VERSION,
                "created_at": time.time(),
                "data_dir": self.data_dir,
                "layout": self._layout,
                "n_rows": b,
                "n_time0": t0,
                "config": config,
            }
            _write_json_atomic(mp, self._manifest)

    # -- cycle bookkeeping ---------------------------------------------------

    def _cycles(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            m = _CYCLE_DIR_RE.match(name)
            if m is not None:
                out.append(int(m.group(1)))
        return sorted(out)

    def _cycle_dir(self, i: int) -> str:
        return os.path.join(self.root, f"cycle_{i:05d}")

    def _cycle_manifest(self, i: int) -> Optional[dict]:
        return _load_json(os.path.join(self._cycle_dir(i), CYCLE_MANIFEST))

    def _t_before(self, i: int) -> int:
        """Panel width when cycle ``i`` started: the initial width plus
        every earlier cycle's recorded tick count — derived from the
        durable chain, never from the (possibly torn mid-append) data
        dir."""
        t = int(self._manifest["n_time0"])
        for j in self._cycles():
            if j >= i:
                break
            m = self._cycle_manifest(j)
            if m is None:
                raise TickLoopError(
                    f"cycle {j} has no {CYCLE_MANIFEST}; the cycle chain "
                    "is torn — resume cycles in order.")
            t += int(m["n_ticks"])
        return t

    # -- the cycle ----------------------------------------------------------

    def resume(self) -> Optional[CycleResult]:
        """Finish the last cycle if a crash left it incomplete.

        A cycle dir without a durable ``ticks.npz`` recorded nothing —
        the batch never happened, the dir is swept, and the feed's
        redelivery becomes a fresh cycle.  With the record present, the
        cycle re-executes from the recorded ticks; every stage is
        idempotent, so the result is bitwise what an uninterrupted run
        would have published.
        """
        cycles = self._cycles()
        if not cycles:
            return None
        i = cycles[-1]
        tick_path = os.path.join(self._cycle_dir(i), "ticks.npz")
        if not os.path.exists(tick_path):
            shutil.rmtree(self._cycle_dir(i), ignore_errors=True)
            return None
        m = self._cycle_manifest(i)
        if m is not None and m.get("stage") == "published":
            return None
        with np.load(tick_path, allow_pickle=False) as z:
            ticks = np.array(z["ticks"])
        obs.event("tickloop.resume", cycle=i)
        return self._execute(i, ticks)

    def run_cycle(self, ticks) -> CycleResult:
        """Ingest one tick batch ``[B, n_ticks]`` end to end."""
        self.resume()
        ticks = np.asarray(ticks)
        if ticks.ndim != 2 or ticks.shape[0] != int(
                self._manifest["n_rows"]):
            raise TickLoopError(
                f"tick batch must be [n_rows={self._manifest['n_rows']}, "
                f"n_ticks], got {ticks.shape}")
        cycles = self._cycles()
        i = (cycles[-1] + 1) if cycles else 0
        return self._execute(i, ticks)

    def serve(self, feed, max_cycles: Optional[int] = None
              ) -> List[CycleResult]:
        """Drain an iterable of tick batches through :meth:`run_cycle`."""
        out = []
        for ticks in feed:
            out.append(self.run_cycle(ticks))
            if max_cycles is not None and len(out) >= max_cycles:
                break
        return out

    def _execute(self, i: int, ticks: np.ndarray) -> CycleResult:
        from ..reliability import fit_chunked

        cdir = self._cycle_dir(i)
        os.makedirs(cdir, exist_ok=True)
        mp = os.path.join(cdir, CYCLE_MANIFEST)
        t_before = self._t_before(i)
        digest = journal_mod.panel_fingerprint(ticks)
        manifest = self._cycle_manifest(i)

        # stage 1 — record the batch durably BEFORE touching the data
        # dir: the recorded ticks are what every later stage (and every
        # resume) consumes, so the cycle's bytes are pinned here
        tick_path = os.path.join(cdir, "ticks.npz")
        if not os.path.exists(tick_path):
            self._write_ticks(tick_path, ticks)
        if manifest is None:
            manifest = {
                "kind": "tickloop_cycle",
                "tickloop_version": TICKLOOP_VERSION,
                "cycle": i,
                "t_before": t_before,
                "n_ticks": int(ticks.shape[1]),
                "ticks_digest": digest,
                "stage": "ticked",
                "walls": {},
            }
            _write_json_atomic(mp, manifest)
        elif manifest.get("ticks_digest") != digest:
            raise TickLoopError(
                f"cycle {i} already recorded a different tick batch "
                f"({manifest.get('ticks_digest')} != {digest}); a feed "
                "must redeliver the SAME batch to an incomplete cycle.")

        # stage 2 — width-gated idempotent append: shards already at
        # t_before + n_ticks are skipped, shards still at t_before are
        # grown, anything else (a foreign writer) is rejected
        if manifest.get("stage") == "ticked":
            t0 = time.perf_counter()
            writer = (source_mod.write_parquet_shards
                      if self._layout == "parquet"
                      else source_mod.write_npz_shards)
            writer(self.data_dir, ticks, append_time=True,
                   expect_time=t_before)
            manifest["stage"] = "appended"
            manifest["walls"]["append_s"] = round(
                time.perf_counter() - t0, 4)
            _write_json_atomic(mp, manifest)

        # stage 3 — delta-warm refit of the grown panel: every chunk's
        # content changed (new columns), so the previous cycle's journal
        # warm-starts all of them; the fit's own chunk journal makes
        # this stage resumable mid-walk
        src = source_mod.as_source(self.data_dir)
        fit_dir = os.path.join(cdir, "fit")
        if manifest.get("stage") in ("appended", "ticked"):
            t0 = time.perf_counter()
            prev_fit = (os.path.join(self._cycle_dir(i - 1), "fit")
                        if i > 0 else None)
            delta_from = (prev_fit if self.delta and prev_fit
                          and os.path.exists(
                              os.path.join(prev_fit, "manifest.json"))
                          else None)
            fit_res = fit_chunked(
                self._fit_fn, src, resilient=False,
                checkpoint_dir=fit_dir, delta_from=delta_from,
                chunk_rows=self.chunk_rows, pipeline=self.pipeline,
                journal_extra={"tickloop": {"cycle": i,
                                            "t_before": t_before}})
            manifest["stage"] = "fitted"
            manifest["walls"]["fit_s"] = round(
                time.perf_counter() - t0, 4)
            if "delta" in fit_res.meta:
                manifest["delta_counts"] = fit_res.meta["delta"]["counts"]
            manifest["fit_status_counts"] = fit_res.meta.get(
                "status_counts")
            _write_json_atomic(mp, manifest)

        # stage 4 — forecast the grown panel and publish through the
        # write-back sink: packed forecasts stream to durable out_*.npz
        # shards, O(chunk) host footprint, torn writes invisible
        pub_dir = os.path.join(cdir, "published")
        if manifest.get("stage") == "fitted":
            t0 = time.perf_counter()
            fres = walk_mod.forecast_chunked(
                self.model, fit_dir, src, self.horizon,
                model_kwargs=self.model_kwargs,
                intervals=self.intervals, level=self.level,
                n_samples=self.n_samples, seed=self.seed,
                chunk_rows=self.chunk_rows,
                checkpoint_dir=os.path.join(cdir, "forecast"),
                pipeline=self.pipeline,
                sink=sink_mod.WritableChunkSource(pub_dir))
            manifest["stage"] = "published"
            manifest["walls"]["publish_s"] = round(
                time.perf_counter() - t0, 4)
            manifest["published"] = {
                "rows": int(self._manifest["n_rows"]),
                "pack_width": self.horizon * (3 if self.intervals
                                              else 1),
                "status_counts": fres.meta["forecast"]["status_counts"],
                "sink": {key: fres.meta["sink"][key]
                         for key in ("writes", "spans", "bytes_written",
                                     "peak_in_flight_bytes")},
            }
            _write_json_atomic(mp, manifest)
            obs.counter("tickloop.cycles").inc()
            obs.event("tickloop.published", cycle=i,
                      n_ticks=int(ticks.shape[1]),
                      t_after=t_before + int(ticks.shape[1]))
        return CycleResult(i, pub_dir, mp, dict(manifest))

    # -- reads ---------------------------------------------------------------

    def published_forecast(self, cycle: Optional[int] = None):
        """Load one cycle's published forecasts: ``(point, lo, hi)``.

        Reads the sink's output shards back through the ordinary source
        layer — the published artifact is just another shard dir."""
        if cycle is None:
            done = [j for j in self._cycles()
                    if (self._cycle_manifest(j) or {}).get("stage")
                    == "published"]
            if not done:
                raise TickLoopError("no published cycle yet")
            cycle = done[-1]
        src = source_mod.NpzShardSource(
            os.path.join(self._cycle_dir(cycle), "published"),
            key="params")
        b, w = int(src.shape[0]), int(src.shape[1])
        pack = np.empty((b, w), src.dtype)
        step = max(1, int(src.default_chunk_rows or 4096))
        for lo in range(0, b, step):
            hi = min(lo + step, b)
            src.read_rows(lo, hi, pack[lo:hi])
        return walk_mod.split_forecast(pack, self.horizon, self.intervals)

    def _write_ticks(self, path: str, ticks: np.ndarray) -> None:
        import tempfile

        d = os.path.dirname(path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=".npz")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, ticks=np.ascontiguousarray(ticks))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
