"""Socket transport for the fit server: length-prefixed frames over TCP.

ROADMAP item 1's last clause — "millions of users arrive over sockets" —
lands here (ISSUE 16).  Until this PR every :class:`~.server.FitServer`
caller was a thread in the server's own process; this module puts the
EXISTING request vocabulary on a wire without inventing a second
serialization:

- **Frames**: ``b"STSF" | u32 payload_len | u32 crc32(payload) |
  payload`` (big-endian).  The CRC is what turns a half-written frame
  (a peer killed mid-``send``, a torn proxy buffer) into a loud
  :class:`FrameError` instead of a silently corrupted request; a
  connection that produces one is poisoned and closed — the client
  reconnects and idempotently retries.
- **Messages**: one frame per message; the payload is
  ``u32 header_len | canonical-JSON header | blob``.  The blob for
  ``submit`` is the durable request record's npz bytes VERBATIM
  (``values`` array + ``meta`` uint8 JSON — exactly what
  :meth:`~.session.FitRequest.save` writes under ``requests/``), so the
  wire format and the crash-recovery format cannot drift apart.
- **Ops**: ``submit`` / ``submit_forecast`` (ack after durable
  admission), ``result`` (poll: done / pending / unknown),
  ``health``, ``ping``.  Every reply echoes the request's ``msg_id`` so
  a duplicated frame (fault injection, a retrying middlebox) can never
  pair a stale reply with the wrong call.

The server side (:class:`TransportServer`) is a thin adapter over any
backend exposing the FitServer surface (``submit`` / ``submit_forecast``
/ ``result_for`` / ``request_pending`` / ``health``) — a bare
:class:`~.server.FitServer` or a :class:`~.fleet.FleetReplica` (which
answers :class:`NotLeaderError` while standby).  Admission stays the
backend's job: the transport never queues, so overload surfaces as the
same :class:`~.session.RejectedError` backpressure callers see
in-process, serialized as ``{"error": "rejected", "retry_after_s": ...}``.
"""

from __future__ import annotations

import hashlib
import hmac
import io
import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .. import obs
from ..reliability.journal import FencedError
from .session import (RejectedError, ServerClosedError, StorageError,
                      TenantFitResult)

__all__ = [
    "FrameDecoder",
    "FrameError",
    "NotLeaderError",
    "ReadOnlyError",
    "TransportError",
    "TransportServer",
    "WireAuthError",
    "decode_msg",
    "decode_request_blob",
    "encode_frame",
    "encode_msg",
    "encode_request_blob",
    "encode_result_blob",
    "decode_result_blob",
    "recv_msg",
    "resolve_wire_secret",
    "send_msg",
]

MAGIC = b"STSF"
_FRAME_HDR = struct.Struct(">4sII")  # magic | payload_len | crc32
_U32 = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024  # a request panel, with headroom

WIRE_SECRET_ENV = "STSTPU_WIRE_SECRET"
WIRE_SECRET_FILE_ENV = "STSTPU_WIRE_SECRET_FILE"
_TAG_LEN = hashlib.sha256().digest_size  # HMAC-SHA256 tag prefix


class TransportError(RuntimeError):
    """Base class for wire-protocol failures (connection-scoped)."""


class FrameError(TransportError):
    """A frame failed validation (bad magic, CRC mismatch, oversized,
    or truncated mid-frame) — the connection is poisoned; reconnect."""


class WireAuthError(RuntimeError):
    """A message failed HMAC verification (or the peer rejected ours).
    Deliberately NOT a :class:`TransportError`: a CRC failure means a
    flaky wire and retrying is right; an auth failure means the two
    sides disagree on the shared secret and retrying can never help —
    it is terminal, a configuration problem for the operator."""


class NotLeaderError(RuntimeError):
    """The replica answering this connection does not hold the fleet
    lease — resubmit to (or wait for) the current primary."""


class ReadOnlyError(RuntimeError):
    """The fleet is in a leaderless window (no replica holds the lease)
    — reads over durable state still work, but a write has nowhere safe
    to land.  Distinct from :class:`NotLeaderError` ("retry ELSEWHERE:
    a primary exists, it just is not me"): this says "retry LATER — an
    election is in flight"."""

    def __init__(self, message: str, retry_after_s: float = 0.5):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def resolve_wire_secret(secret=None) -> Optional[bytes]:
    """The shared wire-auth secret, or None (auth disarmed).

    Explicit ``secret`` (str/bytes) wins; else ``STSTPU_WIRE_SECRET``
    (utf-8), else ``STSTPU_WIRE_SECRET_FILE`` (file bytes, stripped).
    Server and every client must resolve the SAME bytes or every frame
    between them dies with :class:`WireAuthError`."""
    if secret is not None:
        return secret.encode() if isinstance(secret, str) else bytes(secret)
    env = os.environ.get(WIRE_SECRET_ENV)
    if env:
        return env.encode()
    path = os.environ.get(WIRE_SECRET_FILE_ENV)
    if path:
        with open(path, "rb") as f:
            return f.read().strip()
    return None


# ---------------------------------------------------------------------------
# frame codec (pure bytes -> bytes; the seeded fault tests drive these)
# ---------------------------------------------------------------------------


def encode_frame(payload: bytes) -> bytes:
    """One wire frame around ``payload`` (magic, length, CRC)."""
    if len(payload) > MAX_FRAME:
        raise FrameError(f"payload of {len(payload)} bytes exceeds the "
                         f"{MAX_FRAME}-byte frame bound")
    return _FRAME_HDR.pack(MAGIC, len(payload),
                           zlib.crc32(payload) & 0xFFFFFFFF) + payload


class FrameDecoder:
    """Incremental frame parser: ``feed(chunk)`` returns the payloads of
    every frame completed by that chunk, raising :class:`FrameError` on
    corruption.  ``pending`` reports buffered-but-incomplete bytes so a
    closed connection can distinguish a clean EOF from a half-written
    frame."""

    def __init__(self, max_frame: int = MAX_FRAME):
        self._buf = bytearray()
        self._max = int(max_frame)

    @property
    def pending(self) -> int:
        return len(self._buf)

    def requeue(self, payload: bytes) -> None:
        """Push an already-validated payload back to the buffer's front
        (duplicated-frame faults can complete several frames in one
        ``recv``; the extras re-enter FIFO)."""
        self._buf[:0] = encode_frame(payload)

    def feed(self, chunk: bytes) -> list:
        self._buf.extend(chunk)
        out = []
        while len(self._buf) >= _FRAME_HDR.size:
            magic, length, crc = _FRAME_HDR.unpack_from(self._buf)
            if magic != MAGIC:
                raise FrameError(f"bad frame magic {bytes(magic)!r}")
            if length > self._max:
                raise FrameError(f"frame of {length} bytes exceeds the "
                                 f"{self._max}-byte bound")
            end = _FRAME_HDR.size + length
            if len(self._buf) < end:
                break
            payload = bytes(self._buf[_FRAME_HDR.size:end])
            del self._buf[:end]
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise FrameError("frame CRC mismatch (half-written or "
                                 "corrupted frame)")
            out.append(payload)
        return out


def encode_msg(header: dict, blob: bytes = b"",
               secret: Optional[bytes] = None) -> bytes:
    """A full message frame: canonical-JSON header + optional blob.

    With a ``secret`` armed the payload is prefixed by a 32-byte
    HMAC-SHA256 tag over the rest (header length + header + blob), so
    every frame on the wire is authenticated — the CRC catches
    accidents, the tag catches peers without the secret."""
    hdr = json.dumps(header, sort_keys=True).encode()
    body = _U32.pack(len(hdr)) + hdr + blob
    if secret is not None:
        body = hmac.new(secret, body, hashlib.sha256).digest() + body
    return encode_frame(body)


def decode_msg(payload: bytes,
               secret: Optional[bytes] = None) -> Tuple[dict, bytes]:
    if secret is not None:
        if len(payload) < _TAG_LEN:
            raise WireAuthError(
                "frame too short to carry an auth tag — peer is not "
                "speaking the authenticated protocol")
        tag, payload = payload[:_TAG_LEN], payload[_TAG_LEN:]
        want = hmac.new(secret, payload, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):  # constant-time
            raise WireAuthError(
                "frame HMAC verification failed — shared-secret mismatch")
    if len(payload) < _U32.size:
        raise FrameError("message payload shorter than its header length")
    (hlen,) = _U32.unpack_from(payload)
    if _U32.size + hlen > len(payload):
        raise FrameError("message header overruns its payload")
    try:
        header = json.loads(payload[_U32.size:_U32.size + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"unparseable message header: {e}") from None
    return header, payload[_U32.size + hlen:]


def send_msg(sock, header: dict, blob: bytes = b"",
             secret: Optional[bytes] = None) -> None:
    """One message = one ``sendall`` — the unit the fault-injection
    wrappers (``reliability.faultinject``) drop/duplicate/tear."""
    sock.sendall(encode_msg(header, blob, secret))


def recv_msg(sock, decoder: FrameDecoder, bufsize: int = 1 << 16,
             secret: Optional[bytes] = None
             ) -> Optional[Tuple[dict, bytes]]:
    """Block for the next whole message on ``sock`` (None on clean EOF;
    :class:`FrameError` on EOF inside a frame)."""
    frames: list = []
    while not frames:
        chunk = sock.recv(bufsize)
        if not chunk:
            if decoder.pending:
                raise FrameError(
                    f"connection closed mid-frame ({decoder.pending} "
                    "buffered bytes) — half-written frame dropped")
            return None
        frames.extend(decoder.feed(chunk))
    first = frames[0]
    for extra in reversed(frames[1:]):
        decoder.requeue(extra)
    return decode_msg(first, secret)


# ---------------------------------------------------------------------------
# request / result blobs (the existing npz+JSON spelling, verbatim)
# ---------------------------------------------------------------------------


def encode_request_blob(values: np.ndarray, meta: dict) -> bytes:
    """The durable request record's npz bytes (``FitRequest.save``'s
    spelling: ``values`` + ``meta`` as uint8 canonical JSON)."""
    buf = io.BytesIO()
    np.savez(buf, values=np.ascontiguousarray(values),
             meta=np.frombuffer(
                 json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8))
    return buf.getvalue()


def decode_request_blob(blob: bytes) -> Tuple[np.ndarray, dict]:
    with np.load(io.BytesIO(blob)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        values = np.array(z["values"])
    return values, meta


def encode_result_blob(res: TenantFitResult) -> bytes:
    """A stored result's npz bytes (``FitServer._store_result``'s
    spelling), so polls ship exactly what recovery re-answers."""
    buf = io.BytesIO()
    np.savez(buf, params=res.params, nll=res.neg_log_likelihood,
             converged=res.converged, iters=res.iters, status=res.status,
             meta=np.frombuffer(
                 json.dumps(res.meta, default=repr).encode(),
                 dtype=np.uint8))
    return buf.getvalue()


def decode_result_blob(blob: bytes) -> TenantFitResult:
    with np.load(io.BytesIO(blob)) as z:
        return TenantFitResult(
            params=np.array(z["params"]),
            neg_log_likelihood=np.array(z["nll"]),
            converged=np.array(z["converged"]),
            iters=np.array(z["iters"]),
            status=np.array(z["status"]),
            meta=json.loads(bytes(z["meta"].tobytes()).decode()))


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class TransportServer:
    """Listener + per-connection handler threads over a serving backend.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): the accept
        thread registers connections while ``stop()`` (any thread)
        closes them — the connection registry mutates only under its
        lock.

    The backend is duck-typed: a :class:`~.server.FitServer` (submit /
    submit_forecast / result_for / request_pending / health) or a
    :class:`~.fleet.FleetReplica` delegating to its leased server.
    Backend exceptions map to typed error replies; everything else is
    ``{"error": "internal"}`` — a handler never kills the listener.
    """

    _protected_by_ = {"_conns": "_conns_lock"}

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 *, max_frame: int = MAX_FRAME, secret=None):
        self.backend = backend
        self._host = host
        self._port = int(port)
        self._max_frame = int(max_frame)
        self._secret = resolve_wire_secret(secret)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: Dict[int, socket.socket] = {}
        self._conns_lock = threading.Lock()
        self._conn_seq = 0
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TransportServer":
        if self._sock is not None:
            raise RuntimeError("TransportServer.start() called twice")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(64)
        # bounded accept wait: close() alone does NOT wake a thread
        # blocked in accept() on Linux, so the loop re-checks _stopped
        s.settimeout(0.25)
        self._sock = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="transport-accept")
        self._accept_thread.start()
        obs.event("transport.listening", address=list(self.address))
        return self

    @property
    def address(self) -> Tuple[str, int]:
        """The bound (host, port) — with ``port=0`` the kernel picked."""
        if self._sock is None:
            raise RuntimeError("TransportServer not started")
        addr = self._sock.getsockname()
        return (addr[0], int(addr[1]))

    def stop(self) -> None:
        self._stopped.set()
        if self._sock is not None:
            try:  # wakes a blocked accept() immediately (EINVAL)
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        t = self._accept_thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def __enter__(self) -> "TransportServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- connection handling -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue  # bounded wait: re-check _stopped
            except OSError:
                return  # listener closed by stop()
            conn.settimeout(None)  # handlers block; only accept is bounded
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conn_seq += 1
                cid = self._conn_seq
                self._conns[cid] = conn
            threading.Thread(target=self._handle_conn, args=(cid, conn),
                             daemon=True,
                             name=f"transport-conn-{cid}").start()

    def _handle_conn(self, cid: int, conn: socket.socket) -> None:
        decoder = FrameDecoder(self._max_frame)
        try:
            while not self._stopped.is_set():
                try:
                    msg = recv_msg(conn, decoder, secret=self._secret)
                except WireAuthError as e:
                    # an unauthenticated peer: one typed refusal (so an
                    # honest-but-misconfigured client fails LOUDLY, not
                    # by timeout), then close — never dispatch the frame
                    obs.event("transport.auth_failed", conn=cid,
                              error=repr(e)[:200])
                    try:
                        send_msg(conn, {"error": "auth_failed",
                                        "message": str(e)},
                                 secret=self._secret)
                    except OSError:
                        pass
                    return
                except (FrameError, OSError) as e:
                    obs.event("transport.conn_poisoned", conn=cid,
                              error=repr(e)[:200])
                    return  # poisoned/reset connection: drop it
                if msg is None:
                    return  # clean EOF
                header, blob = msg
                reply_hdr, reply_blob = self._dispatch(header, blob)
                if "msg_id" in header:
                    reply_hdr["msg_id"] = header["msg_id"]
                if "trace" in header:
                    # a tracing client gets this replica's monotonic
                    # clock on every reply — the raw material for the
                    # client's per-endpoint offset estimates (ISSUE 18);
                    # non-tracing requests get byte-identical replies
                    reply_hdr["ts_mono"] = time.monotonic()
                try:
                    send_msg(conn, reply_hdr, reply_blob,
                             secret=self._secret)
                except OSError:
                    return  # peer went away mid-reply; it will retry
        finally:
            with self._conns_lock:
                self._conns.pop(cid, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, header: dict, blob: bytes) -> Tuple[dict, bytes]:
        # continue the wire-carried trace (ISSUE 18): every event/span
        # the backend emits while handling this frame — admission, batch
        # membership, result store — lands in THIS replica's stream
        # stamped with the request's fleet-wide trace id
        with obs.trace_scope(obs.trace_from_wire(header, site="server")):
            return self._dispatch_traced(header, blob)

    def _dispatch_traced(self, header: dict,
                         blob: bytes) -> Tuple[dict, bytes]:
        op = header.get("op")
        try:
            if op == "ping":
                return {"ok": True}, b""
            if op == "health":
                h = self.backend.health()
                return {"ok": True,
                        "health": json.loads(
                            json.dumps(h, default=repr))}, b""
            if op == "submit":
                return self._op_submit(blob)
            if op == "submit_forecast":
                return self._op_submit_forecast(header, blob)
            if op == "result":
                return self._op_result(header)
            return {"error": "bad_request",
                    "message": f"unknown op {op!r}"}, b""
        except NotLeaderError as e:
            return {"error": "not_leader", "message": str(e)}, b""
        except ReadOnlyError as e:
            return {"error": "read_only", "message": str(e),
                    "retry_after_s": e.retry_after_s}, b""
        except FencedError as e:
            return {"error": "fenced", "message": str(e)}, b""
        except StorageError as e:
            # before RejectedError (its base): storage refusals carry a
            # distinct kind so clients prefer OTHER replicas
            return {"error": "storage_degraded", "message": str(e),
                    "retry_after_s": e.retry_after_s}, b""
        except RejectedError as e:
            return {"error": "rejected", "message": str(e),
                    "retry_after_s": e.retry_after_s,
                    "shed": e.shed}, b""
        except ServerClosedError as e:
            return {"error": "closed", "message": str(e)}, b""
        except (ValueError, TypeError, KeyError, FrameError) as e:
            return {"error": "bad_request",
                    "message": f"{type(e).__name__}: {e}"}, b""
        except Exception as e:  # noqa: BLE001 - handler never kills listener
            obs.event("transport.internal_error", op=op,
                      error=repr(e)[:300])
            return {"error": "internal",
                    "message": f"{type(e).__name__}: {e}"}, b""

    def _op_submit(self, blob: bytes) -> Tuple[dict, bytes]:
        values, meta = decode_request_blob(blob)
        req_id = meta.get("req_id")
        if req_id and self.backend.request_pending(req_id):
            # idempotent resubmit of an in-flight id: already durable,
            # the serve loop will answer it — ack instead of re-admitting
            return {"ok": True, "req_id": req_id, "pending": True}, b""
        try:
            ticket = self.backend.submit(
                meta["tenant"], values, meta.get("model", "arima"),
                priority=int(meta.get("priority") or 0),
                deadline_s=meta.get("deadline_s"),
                request_id=req_id,
                **(meta.get("fit_kwargs") or {}))
        except RejectedError:
            # raced another resubmit of the same id into admission: the
            # winner's record is durable, which is all the ack promises
            if req_id and self.backend.request_pending(req_id):
                return {"ok": True, "req_id": req_id, "pending": True}, b""
            raise
        return {"ok": True, "req_id": ticket.req_id}, b""

    def _op_submit_forecast(self, header: dict,
                            blob: bytes) -> Tuple[dict, bytes]:
        values, meta = decode_request_blob(blob)
        with np.load(io.BytesIO(blob)) as z:
            fitted = np.array(z["fitted"])
            status = np.array(z["status"]) if "status" in z else None
        req_id = meta.get("req_id")
        if req_id and self.backend.request_pending(req_id):
            return {"ok": True, "req_id": req_id, "pending": True}, b""
        fc = meta.get("forecast") or {}
        try:
            ticket = self.backend.submit_forecast(
                meta["tenant"], values, fitted,
                model=fc.get("model", "arima"),
                horizon=int(fc.get("horizon") or 1),
                model_kwargs=fc.get("model_kwargs") or {},
                status=status,
                intervals=bool(fc.get("intervals")),
                level=float(fc.get("level") or 0.9),
                n_samples=int(fc.get("n_samples") or 256),
                seed=fc.get("seed"),
                priority=int(meta.get("priority") or 0),
                deadline_s=meta.get("deadline_s"),
                request_id=req_id)
        except RejectedError:
            if req_id and self.backend.request_pending(req_id):
                return {"ok": True, "req_id": req_id, "pending": True}, b""
            raise
        return {"ok": True, "req_id": ticket.req_id}, b""

    def _op_result(self, header: dict) -> Tuple[dict, bytes]:
        req_id = header.get("req_id")
        if not req_id:
            return {"error": "bad_request",
                    "message": "result op needs req_id"}, b""
        try:
            res = self.backend.result_for(req_id)
        except KeyError:
            if self.backend.request_pending(req_id):
                return {"ok": True, "done": False, "req_id": req_id}, b""
            return {"error": "unknown_request", "req_id": req_id,
                    "message": f"request {req_id!r} has no stored result "
                               "and is not in flight — resubmit it "
                               "(idempotent by request id)"}, b""
        return ({"ok": True, "done": True, "req_id": req_id},
                encode_result_blob(res))
