"""FitClient: the kill-tolerant caller side of the fleet wire protocol.

The serving contract callers rely on (ISSUE 16) is *at-least-once
delivery, exactly-once answering*: a request id is idempotent end to end
(the durable record, the batch digest, the stored result), so the client
is free to retry aggressively — a resubmit of an admitted id is acked,
a resubmit of a completed id returns the stored bytes, and a resubmit
after the admitting replica was SIGKILLed lands on the surviving peer.
:class:`FitClient` packages that into a synchronous facade shaped like
:class:`~.server.FitServer` itself (``submit`` / ``submit_forecast``
returning tickets), so ``run_backtest(server=client)`` storms a fleet
exactly the way it storms an in-process server:

- **idempotent resubmit**: the client names every request
  (``request_id`` or a generated ``c-<hex>`` id) and keeps the encoded
  submit bytes; any ambiguity (reset mid-ack, ``unknown_request`` from a
  peer that never saw the dead replica's un-journaled admission) is
  resolved by resubmitting the same id.
- **bounded retries, deterministic backoff**: transport faults and
  ``rejected``/``not_leader`` replies retry up to ``retries`` times with
  exponential backoff whose jitter derives from ``sha256(seed, attempt)``
  — the same seed replays the same schedule (:func:`backoff_schedule`),
  so backpressure behavior is testable byte-for-byte.
- **per-call deadlines**: every blocking call (submit, poll, result)
  runs under a wall-clock budget and raises the *typed*
  :class:`ClientDeadlineError` when it expires — a dead fleet can cost a
  caller its deadline, never a hang.
- **reconnect-safe polling**: results are polled by id over whatever
  connection currently works; a ticket survives any number of
  connection resets and replica failovers because the id, not the
  socket, is the request's identity.
- **health-aware routing** (ISSUE 17): endpoint choice goes through an
  :class:`~.health.EndpointHealthCache` — writes prefer the believed
  primary, reads fan out to whatever is healthy (standbys serve reads),
  a repeatedly-failing endpoint's circuit opens for a seeded
  deterministic cooldown, and a ``not_leader`` redirect steers writes
  away for a lease-TTL-ish memo window.  Slow result polls optionally
  HEDGE to a second healthy endpoint (``hedge_after_s``): results are
  durable bytes, identical from every replica, so hedging is
  bitwise-neutral — first answer wins.
- **typed degradation**: ``read_only`` (leaderless window — retry
  later) and ``storage_degraded`` (this replica's disk refuses writes —
  retry ELSEWHERE) replies are retried with their own policies;
  ``auth_failed`` (shared-secret mismatch) is terminal
  :class:`~.transport.WireAuthError` — retrying cannot help.
"""

from __future__ import annotations

import hashlib
import io
import json
import socket
import threading
import time
import uuid
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from .. import obs
from . import transport
from .health import EndpointHealthCache
from .session import (RejectedError, ServerClosedError, StorageError,
                      TenantFitResult)

__all__ = [
    "ClientDeadlineError",
    "FitClient",
    "RemoteTicket",
    "backoff_schedule",
]


class ClientDeadlineError(RuntimeError):
    """A client call's wall-clock budget expired before the fleet
    answered.  The request itself may still be in flight server-side
    (durable by id); re-poll or resubmit with the same ``request_id``."""

    def __init__(self, what: str, deadline_s: float):
        super().__init__(
            f"{what} exceeded its {deadline_s:.2f}s deadline; the request "
            "id stays idempotent — poll or resubmit it")
        self.deadline_s = float(deadline_s)


def backoff_schedule(seed: int, attempts: int, *,
                     base_s: float = 0.05,
                     max_s: float = 2.0) -> List[float]:
    """The client's deterministic backoff schedule: exponential growth
    with multiplicative jitter in ``[0.5, 1.0)`` derived from
    ``sha256(seed, attempt)`` — same seed, same schedule, every process,
    every run (the property the retry tests assert)."""
    out = []
    for attempt in range(int(attempts)):
        cap = min(float(max_s), float(base_s) * (2.0 ** attempt))
        digest = hashlib.sha256(
            f"backoff:{int(seed)}:{attempt}".encode()).digest()
        frac = 0.5 + (int.from_bytes(digest[:8], "big") / 2.0 ** 64) * 0.5
        out.append(cap * frac)
    return out


class _ConnDropped(transport.TransportError):
    """Internal: the current connection died mid-call; rotate + retry."""


class RemoteTicket:
    """The caller's handle for one fleet request: resolves by POLLING
    the durable result by id, so it survives connection resets, replica
    SIGKILLs, and failovers (``FitTicket`` semantics, minus the process
    locality)."""

    def __init__(self, client: "FitClient", req_id: str,
                 resubmit: Tuple[dict, bytes]):
        self.req_id = req_id
        self._client = client
        self._resubmit = resubmit  # (header, blob): idempotent re-offer
        self._result: Optional[TenantFitResult] = None

    def done(self) -> bool:
        if self._result is not None:
            return True
        try:
            self._result = self._client._poll_once(self.req_id,
                                                   self._resubmit)
        except transport.TransportError:
            return False
        return self._result is not None

    def result(self, timeout: Optional[float] = None) -> TenantFitResult:
        """Block for the result (``ClientDeadlineError`` on expiry).
        ``timeout=None`` uses the client's default call deadline."""
        if self._result is not None:
            return self._result
        self._result = self._client._poll_result(self.req_id,
                                                 self._resubmit, timeout)
        return self._result


class FitClient:
    """Socket client over one or more fleet endpoints (see module doc).

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): tickets may be
        polled from many caller threads (``run_backtest`` worker pools);
        the connection, endpoint cursor, and message sequence mutate
        only under the I/O lock — one request/reply round trip at a
        time per client.

    ``endpoints`` is a list of ``(host, port)`` tuples or
    ``"host:port"`` strings; the client rotates through them on
    connection failure and ``not_leader`` replies, which is the whole
    failover story — the lease decides who answers, the client just
    keeps knocking.
    """

    _protected_by_ = {
        "_sock": "_io_lock",
        "_decoder": "_io_lock",
        "_cur_ep": "_io_lock",
        "_msg_seq": "_io_lock",
        "_clock": "_io_lock",
    }

    def __init__(self, endpoints: Sequence[Union[str, Tuple[str, int]]], *,
                 retries: int = 16,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 seed: int = 0,
                 deadline_s: Optional[float] = 300.0,
                 poll_interval_s: float = 0.05,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 60.0,
                 failure_threshold: int = 3,
                 hedge_after_s: Optional[float] = None,
                 secret=None,
                 _wire_wrap: Optional[Callable] = None):
        eps = []
        for ep in endpoints:
            if isinstance(ep, str):
                host, _, port = ep.rpartition(":")
                eps.append((host or "127.0.0.1", int(port)))
            else:
                eps.append((str(ep[0]), int(ep[1])))
        if not eps:
            raise ValueError("FitClient needs at least one endpoint")
        self.endpoints = eps
        self.retries = int(retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.seed = int(seed)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.poll_interval_s = float(poll_interval_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        # hedged result polls: after this many seconds of pending, every
        # poll ALSO asks the next-best healthy endpoint (None = off)
        self.hedge_after_s = (None if hedge_after_s is None
                              else float(hedge_after_s))
        self._secret = transport.resolve_wire_secret(secret)
        self.endpoint_health = EndpointHealthCache(
            eps, seed=seed, failure_threshold=failure_threshold)
        # fault-injection seam: wraps each fresh connection in a lossy
        # wire (reliability.faultinject.FaultyWire) — tests only
        self._wire_wrap = _wire_wrap
        self._io_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._decoder = transport.FrameDecoder()
        self._cur_ep: Optional[Tuple[str, int]] = None
        self._msg_seq = 0
        # per-endpoint monotonic-clock offset estimates (ISSUE 18): when
        # tracing is on, each reply carries the replica's time.monotonic
        # and the midpoint estimate with the SMALLEST observed rtt wins —
        # journaled next to the obs stream at close() so merged fleet
        # timelines are orderable without trusting wall clocks
        self._clock: dict = {}

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._write_clock_journal()
        with self._io_lock:
            self._close_locked()

    def _write_clock_journal(self) -> None:
        """Journal the per-endpoint clock-offset estimates as a sidecar
        JSON next to the obs JSONL stream (``<stream>.clock.json``) —
        the artifact ``obs_report --fleet`` reads to order cross-process
        timelines.  Structurally a no-op unless the obs plane is on
        with a stream AND at least one estimate exists, so a disabled
        run writes nothing (bitwise-inert contract)."""
        path = obs.stream_path()
        with self._io_lock:
            clock = dict(self._clock)
        if path is None or not clock:
            return
        record = {
            "kind": "clock_offsets",
            "endpoints": {f"{h}:{p}": est for (h, p), est in
                          sorted(clock.items())},
        }
        try:
            with open(path + ".clock.json", "w", encoding="utf-8") as f:
                f.write(json.dumps(record, indent=1, sort_keys=True))
        except OSError:
            pass  # telemetry sidecar: never let it break close()

    def __enter__(self) -> "FitClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._cur_ep = None
        self._decoder = transport.FrameDecoder()

    def _connect_locked(self, write: bool = False) -> None:
        if write and self._sock is not None:
            # a write on a read connection: if the cache believes the
            # primary is elsewhere, move there instead of bouncing off
            # a standby's not_leader
            want = self.endpoint_health.believed_primary()
            if want is not None and self._cur_ep != want:
                self._close_locked()
        if self._sock is not None:
            return
        host, port = self.endpoint_health.order(write=write)[0]
        try:
            s = socket.create_connection((host, port),
                                         timeout=self.connect_timeout_s)
        except OSError as e:
            self.endpoint_health.record_failure((host, port))
            raise _ConnDropped(
                f"connect to {host}:{port} failed: {e}") from None
        s.settimeout(self.io_timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._wire_wrap is not None:
            s = self._wire_wrap(s)
        self._sock = s
        self._cur_ep = (host, port)
        self._decoder = transport.FrameDecoder()

    def _rotate_locked(self) -> None:
        # the health cache decides where the NEXT connect lands; the
        # failure/redirect records made this endpoint sort later
        if self._cur_ep is not None:
            obs.event("client.rotate", endpoint=list(self._cur_ep))
        self._close_locked()

    # -- one round trip ------------------------------------------------------

    def _call_once(self, header: dict, blob: bytes = b"",
                   write: bool = False) -> Tuple[dict, bytes]:
        """One request/reply round trip on the current connection
        (raises :class:`_ConnDropped` on any transport-level failure,
        leaving the connection closed).  Health recording happens here,
        where the endpoint is known: any reply is a liveness success,
        ``not_leader`` memos the redirect, ``storage_degraded`` counts
        as a failure (prefer other replicas), a clean write ack marks
        the believed primary."""
        with self._io_lock:
            self._connect_locked(write=write)
            ep = self._cur_ep
            self._msg_seq += 1
            msg_id = f"m{self._msg_seq}"
            hdr = {**header, "msg_id": msg_id}
            tctx = obs.current_trace()
            if tctx is not None:  # trace rides the wire (ISSUE 18)
                hdr["trace"] = obs.trace_to_wire(tctx)
            t0 = time.monotonic()
            try:
                transport.send_msg(self._sock, hdr, blob, self._secret)
                while True:
                    msg = transport.recv_msg(self._sock, self._decoder,
                                             secret=self._secret)
                    if msg is None:
                        raise transport.FrameError(
                            "connection closed before the reply")
                    reply, rblob = msg
                    # duplicated-frame faults can surface stale replies;
                    # the msg_id echo pairs replies with calls exactly
                    if reply.get("error") == "auth_failed":
                        # terminal: the server refused OUR bytes — a
                        # shared-secret mismatch no retry can fix
                        raise transport.WireAuthError(
                            reply.get("message", "auth_failed"))
                    if reply.get("msg_id") in (None, msg_id):
                        err = reply.get("error")
                        if err == "storage_degraded":
                            self.endpoint_health.record_failure(ep)
                        elif err == "not_leader":
                            self.endpoint_health.record_redirect(ep)
                        else:
                            t1 = time.monotonic()
                            self.endpoint_health.record_success(ep, t1 - t0)
                            if write and err is None:
                                self.endpoint_health.set_primary(ep)
                            self._update_clock_locked(ep, reply, t0, t1)
                        return reply, rblob
            except transport.WireAuthError:
                self._close_locked()
                raise
            except (transport.TransportError, OSError) as e:
                self.endpoint_health.record_failure(ep)
                self._rotate_locked()
                raise _ConnDropped(f"call failed mid-flight: {e}") from None

    def _update_clock_locked(self, ep, reply: dict, t0: float,
                             t1: float) -> None:
        """Fold a reply's replica-monotonic timestamp into this
        endpoint's clock-offset estimate (caller holds ``_io_lock``).
        NTP-style midpoint: ``offset = ts_mono - (t0 + t1) / 2``; the
        estimate with the smallest round trip wins (least midpoint
        slack).  Replies without ``ts_mono`` — tracing off, old servers
        — leave the table untouched."""
        ts_mono = reply.get("ts_mono")
        if not isinstance(ts_mono, (int, float)) or ep is None:
            return
        rtt = t1 - t0
        offset = float(ts_mono) - (t0 + t1) / 2.0
        prev = self._clock.get(ep)
        if prev is None or rtt < prev["rtt_s"]:
            self._clock[ep] = {"offset_s": round(offset, 6),
                               "rtt_s": round(rtt, 6)}
            obs.event("client.clock_offset", endpoint=list(ep),
                      offset_s=round(offset, 6), rtt_s=round(rtt, 6))

    def _call(self, header: dict, blob: bytes = b"", *,
              what: str, deadline_s: Optional[float] = None,
              resubmit_ok: bool = True,
              write: bool = False) -> Tuple[dict, bytes]:
        """A round trip under the retry/backoff/deadline policy.

        Retryable outcomes — dropped connections, ``not_leader`` (a
        standby answered; the new primary needs a lease TTL to take
        over), ``read_only`` (leaderless window: retry LATER),
        ``storage_degraded`` (this replica's disk refuses writes: retry
        ELSEWHERE), ``closed`` (a draining replica), ``rejected``
        (backpressure: honors ``retry_after_s``) — burn one bounded
        retry each, sleeping the deterministic backoff schedule between
        attempts.  Typed terminal outcomes raise: bad requests
        (``ValueError``), auth failures
        (:class:`~.transport.WireAuthError` from the round trip),
        deadline expiry (:class:`ClientDeadlineError`), retries
        exhausted (the last error)."""
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        t0 = time.monotonic()
        schedule = backoff_schedule(self.seed, self.retries + 1,
                                    base_s=self.backoff_base_s,
                                    max_s=self.backoff_max_s)
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            if budget is not None and time.monotonic() - t0 >= budget:
                raise ClientDeadlineError(what, budget)
            try:
                reply, rblob = self._call_once(header, blob, write=write)
            except _ConnDropped as e:
                last = e
                self._sleep_backoff(schedule[attempt], t0, budget, what)
                continue
            err = reply.get("error")
            if err is None:
                return reply, rblob
            if err == "rejected":
                last = RejectedError(
                    reply.get("message", "rejected"),
                    retry_after_s=float(reply.get("retry_after_s") or 1.0),
                    shed=bool(reply.get("shed")))
                if not resubmit_ok:
                    raise last
                self._sleep_backoff(
                    max(schedule[attempt], last.retry_after_s),
                    t0, budget, what)
                continue
            if err == "storage_degraded":
                # _call_once already dinged the endpoint's health; the
                # next connect prefers a replica whose disk works
                last = StorageError(
                    reply.get("message", "storage degraded"),
                    retry_after_s=float(reply.get("retry_after_s") or 5.0))
                if not resubmit_ok:
                    raise last
                with self._io_lock:
                    self._rotate_locked()
                self._sleep_backoff(schedule[attempt], t0, budget, what)
                continue
            if err == "read_only":
                # leaderless window: nobody can admit writes anywhere —
                # wait out the election rather than hammering peers
                last = ServerClosedError(reply.get("message", err))
                with self._io_lock:
                    self._rotate_locked()
                self._sleep_backoff(
                    max(schedule[attempt],
                        float(reply.get("retry_after_s") or 0.5)),
                    t0, budget, what)
                continue
            if err in ("not_leader", "closed", "fenced"):
                # the lease is (re)electing; knock on the next replica
                last = ServerClosedError(reply.get("message", err))
                with self._io_lock:
                    self._rotate_locked()
                self._sleep_backoff(schedule[attempt], t0, budget, what)
                continue
            if err == "unknown_request":
                raise KeyError(reply.get("message", "unknown request"))
            if err == "bad_request":
                raise ValueError(reply.get("message", "bad request"))
            raise RuntimeError(
                f"fleet internal error: {reply.get('message')}")
        raise (last if last is not None else
               transport.TransportError(f"{what}: retries exhausted"))

    def _sleep_backoff(self, delay: float, t0: float,
                       budget: Optional[float], what: str) -> None:
        if budget is not None:
            remaining = budget - (time.monotonic() - t0)
            if remaining <= 0:
                raise ClientDeadlineError(what, budget)
            delay = min(delay, remaining)
        if delay > 0:
            obs.event("client.backoff", what=what, delay_s=round(delay, 6))
            time.sleep(delay)

    # -- public API ----------------------------------------------------------

    def ping(self) -> bool:
        reply, _ = self._call({"op": "ping"}, what="ping")
        return bool(reply.get("ok"))

    def health(self) -> dict:
        reply, _ = self._call({"op": "health"}, what="health")
        return reply.get("health") or {}

    def submit(self, tenant: str, values, model: str = "arima", *,
               priority: int = 0, deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               call_deadline_s: Optional[float] = None,
               **fit_kwargs) -> RemoteTicket:
        """Admit one panel fit over the wire; returns a
        :class:`RemoteTicket`.  ``deadline_s`` is the SERVER-side request
        deadline (watchdog contract); ``call_deadline_s`` bounds this
        client call's wall clock (default: the client's ``deadline_s``).
        ``request_id`` makes the submit idempotent across any number of
        retries, resets, and replica deaths — omitted, the client
        generates one."""
        req_id = request_id or f"c-{uuid.uuid4().hex[:16]}"
        meta = {
            "req_id": req_id, "tenant": str(tenant), "model": str(model),
            "fit_kwargs": json.loads(json.dumps(dict(fit_kwargs))),
            "priority": int(priority),
            "deadline_s": None if deadline_s is None else float(deadline_s),
        }
        blob = transport.encode_request_blob(np.asarray(values), meta)
        header = {"op": "submit"}
        with obs.trace_scope(obs.trace_for_request(req_id, "client")):
            obs.event("client.submit", req_id=req_id, tenant=str(tenant),
                      op="submit")
            reply, _ = self._call(header, blob, what=f"submit({req_id})",
                                  deadline_s=call_deadline_s, write=True)
        got = reply.get("req_id")
        if got != req_id:
            raise transport.TransportError(
                f"submit ack names {got!r}, expected {req_id!r}")
        obs.counter("client.submitted").inc()
        return RemoteTicket(self, req_id, (header, blob))

    def submit_forecast(self, tenant: str, values, fitted, *,
                        model: str = "arima", horizon: int = 1,
                        model_kwargs: Optional[dict] = None,
                        status=None, intervals: bool = False,
                        level: float = 0.9, n_samples: int = 256,
                        seed: Optional[int] = None, priority: int = 0,
                        deadline_s: Optional[float] = None,
                        request_id: Optional[str] = None,
                        call_deadline_s: Optional[float] = None
                        ) -> RemoteTicket:
        """Admit one panel forecast over the wire — the
        ``run_backtest(server=client)`` surface.  ``fitted`` follows
        :meth:`~.server.FitServer.submit_forecast` semantics (a fit
        result or a raw ``[rows, k]`` array); augmentation happens
        server-side so the durable record matches an in-process
        submit's byte for byte."""
        req_id = request_id or f"c-{uuid.uuid4().hex[:16]}"
        if hasattr(fitted, "params"):
            params = np.asarray(fitted.params)
            if status is None:
                status = getattr(fitted, "status", None)
        else:
            params = np.asarray(fitted)
        meta = {
            "req_id": req_id, "tenant": str(tenant),
            "priority": int(priority),
            "deadline_s": None if deadline_s is None else float(deadline_s),
            "forecast": {
                "model": str(model), "horizon": int(horizon),
                "model_kwargs": {
                    k: (list(v) if isinstance(v, tuple) else v)
                    for k, v in (model_kwargs or {}).items()},
                "intervals": bool(intervals), "level": float(level),
                "n_samples": int(n_samples),
                "seed": None if seed is None else int(seed),
            },
        }
        buf = io.BytesIO()
        arrays = {"values": np.ascontiguousarray(np.asarray(values)),
                  "fitted": np.ascontiguousarray(params),
                  "meta": np.frombuffer(
                      json.dumps(meta, sort_keys=True).encode(),
                      dtype=np.uint8)}
        if status is not None:
            arrays["status"] = np.ascontiguousarray(np.asarray(status))
        np.savez(buf, **arrays)
        blob = buf.getvalue()
        # deliberately a READ-class call: forecasts derive from journaled
        # params with a content-derived base seed, so ANY replica (a
        # standby included) answers them bitwise-identically — this is
        # the read load the standbys exist to carry
        header = {"op": "submit_forecast"}
        with obs.trace_scope(obs.trace_for_request(req_id, "client")):
            obs.event("client.submit", req_id=req_id, tenant=str(tenant),
                      op="submit_forecast")
            reply, _ = self._call(header, blob,
                                  what=f"submit_forecast({req_id})",
                                  deadline_s=call_deadline_s)
        got = reply.get("req_id")
        if got != req_id:
            raise transport.TransportError(
                f"submit ack names {got!r}, expected {req_id!r}")
        obs.counter("client.submitted").inc()
        return RemoteTicket(self, req_id, (header, blob))

    def result_for(self, req_id: str,
                   timeout: Optional[float] = None) -> TenantFitResult:
        """Poll a request's stored result by id (how a restarted CLIENT
        re-attaches: the id is the identity, not the ticket object).
        Raises ``KeyError`` for an id the fleet has never admitted."""
        return self._poll_result(req_id, None, timeout)

    # -- polling internals ---------------------------------------------------

    def _poll_once(self, req_id: str,
                   resubmit: Optional[Tuple[dict, bytes]]
                   ) -> Optional[TenantFitResult]:
        """One poll: the result, None while pending.  An
        ``unknown_request`` reply means the admitting replica died
        before its write-ahead record landed — resubmit the identical
        bytes (idempotent) and report pending."""
        with obs.trace_scope(obs.trace_for_request(req_id, "client")):
            try:
                reply, rblob = self._call({"op": "result", "req_id": req_id},
                                          what=f"result({req_id})")
            except KeyError:
                if resubmit is None:
                    raise
                header, blob = resubmit
                obs.event("client.resubmit", req_id=req_id)
                self._call(header, blob, what=f"resubmit({req_id})")
                obs.counter("client.resubmitted").inc()
                return None
            if reply.get("done"):
                res = transport.decode_result_blob(rblob)
                if resubmit is not None:
                    # THE terminal of the request's causal timeline: a
                    # submitted ticket observed the durable answer
                    # (obs_report --trace gates on exactly one of these
                    # per stormed request).  result_for() re-reads pass
                    # resubmit=None and stay terminal-silent — a probe
                    # loop re-polling a done id is a READ, not the
                    # request completing again.
                    obs.event("client.result", req_id=req_id)
                return res
            return None

    def _poll_result(self, req_id: str,
                     resubmit: Optional[Tuple[dict, bytes]],
                     timeout: Optional[float]) -> TenantFitResult:
        budget = self.deadline_s if timeout is None else float(timeout)
        t0 = time.monotonic()
        hedging = False
        with obs.trace_scope(obs.trace_for_request(req_id, "client")):
            while True:
                res = self._poll_once(req_id, resubmit)
                if res is not None:
                    return res
                if (self.hedge_after_s is not None
                        and len(self.endpoints) > 1
                        and time.monotonic() - t0 >= self.hedge_after_s):
                    if not hedging:
                        hedging = True
                        obs.counter("client.hedge_launched").inc()
                        obs.event("client.hedge", req_id=req_id)
                    res = self._hedge_poll_once(req_id)
                    if res is not None:
                        obs.counter("client.hedge_won").inc()
                        if resubmit is not None:  # same terminal contract
                            obs.event("client.result", req_id=req_id,
                                      hedged=True)
                        return res
                if budget is not None and \
                        time.monotonic() - t0 + self.poll_interval_s > budget:
                    raise ClientDeadlineError(f"result({req_id})", budget)
                time.sleep(self.poll_interval_s)

    def _hedge_poll_once(self, req_id: str) -> Optional[TenantFitResult]:
        """One hedged result poll against the best endpoint OTHER than
        the current connection's, over a throwaway connection.  Results
        are durable bytes — identical from every replica — so whichever
        side answers first is the answer.  Any failure just records
        endpoint health and returns None; the main poll loop is the
        arbiter of deadlines."""
        with self._io_lock:
            cur = self._cur_ep
        alt = next((ep for ep in self.endpoint_health.order()
                    if ep != cur), None)
        if alt is None:
            return None
        try:
            s = socket.create_connection(alt,
                                         timeout=self.connect_timeout_s)
        except OSError:
            self.endpoint_health.record_failure(alt)
            return None
        try:
            s.settimeout(self.io_timeout_s)
            if self._wire_wrap is not None:
                s = self._wire_wrap(s)
            decoder = transport.FrameDecoder()
            transport.send_msg(
                s, {"op": "result", "req_id": req_id, "msg_id": "hedge"},
                secret=self._secret)
            while True:
                msg = transport.recv_msg(s, decoder, secret=self._secret)
                if msg is None:
                    return None
                reply, rblob = msg
                if reply.get("msg_id") in (None, "hedge"):
                    break
            self.endpoint_health.record_success(alt)
            if reply.get("done"):
                return transport.decode_result_blob(rblob)
            return None
        except (transport.TransportError, transport.WireAuthError,
                OSError):
            self.endpoint_health.record_failure(alt)
            return None
        finally:
            try:
                s.close()
            except OSError:
                pass
