"""Admission control: bounded queue, per-tenant quotas, overload shedding.

Spark's scheduler admitted unbounded work and let executors die of it; a
resident serving process cannot.  This module is the server's front door
and enforces three invariants:

1. **Bounded memory**: the queue holds at most ``max_queue_rows`` panel
   rows / ``max_queue_requests`` requests.  Past the bound a new request
   is REJECTED with :class:`~.session.RejectedError` carrying a
   ``retry_after_s`` backpressure estimate (queued rows over the recent
   drain rate) — overload is an explicit signal, never an allocator
   failure.
2. **Priority shedding**: when the queue is full and a HIGHER-priority
   request arrives, the lowest-priority queued work is shed (its ticket
   resolves to ``RejectedError(shed=True)``) until the newcomer fits —
   the degradation ladder drops the least important work first, loudly.
3. **Per-tenant quotas** (:class:`TenantQuota`): one tenant cannot starve
   the rest — at most ``max_inflight_per_tenant`` requests /
   ``max_rows_per_tenant`` rows admitted-but-unanswered per tenant, and
   ``max_rows_per_request`` bounds any single panel.

Everything is host-side and lock-protected; the serve loop is the single
consumer, caller threads are concurrent producers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from .session import FitRequest, RejectedError

__all__ = ["AdmissionQueue", "TenantQuota"]


class TenantQuota:
    """Per-tenant in-flight budget (requests + rows, admission to answer)."""

    # lock-discipline contract (tools/lint lock-map): caller threads
    # acquire, the serve loop releases — the ledger mutates under _lock.
    _protected_by_ = {"_inflight": "_lock"}

    def __init__(self, max_inflight_per_tenant: Optional[int] = None,
                 max_rows_per_tenant: Optional[int] = None,
                 max_rows_per_request: Optional[int] = None):
        self.max_inflight = max_inflight_per_tenant
        self.max_rows = max_rows_per_tenant
        self.max_rows_per_request = max_rows_per_request
        self._lock = threading.Lock()
        self._inflight: dict = {}  # tenant -> [n_requests, n_rows]

    def try_acquire(self, tenant: str, rows: int,
                    force: bool = False) -> None:
        """Admit ``rows`` for ``tenant`` or raise :class:`RejectedError`.

        ``force=True`` records the acquisition even past the limits
        (restart recovery re-admits work the dead server already
        accepted; quotas may transiently overcommit, but the
        acquire/release ledger stays symmetric so steady-state
        accounting is exact)."""
        if (not force and self.max_rows_per_request is not None
                and rows > self.max_rows_per_request):
            raise RejectedError(
                f"request of {rows} rows exceeds the per-request cap "
                f"{self.max_rows_per_request}", retry_after_s=0.0)
        with self._lock:
            n, r = self._inflight.get(tenant, (0, 0))
            if not force:
                if self.max_inflight is not None and n >= self.max_inflight:
                    raise RejectedError(
                        f"tenant {tenant!r} already has {n} requests in "
                        f"flight (quota {self.max_inflight})",
                        retry_after_s=0.5)
                if self.max_rows is not None and r + rows > self.max_rows:
                    raise RejectedError(
                        f"tenant {tenant!r} would hold {r + rows} rows in "
                        f"flight (quota {self.max_rows})", retry_after_s=0.5)
            self._inflight[tenant] = (n + 1, r + rows)

    def release(self, tenant: str, rows: int) -> None:
        with self._lock:
            n, r = self._inflight.get(tenant, (0, 0))
            n, r = max(0, n - 1), max(0, r - rows)
            if n == 0 and r == 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = (n, r)

    def snapshot(self) -> dict:
        with self._lock:
            return {t: {"requests": n, "rows": r}
                    for t, (n, r) in sorted(self._inflight.items())}


class AdmissionQueue:
    """Bounded FIFO of admitted requests with priority-aware shedding.

    Producers call :meth:`offer`; the serve loop calls
    :meth:`take_batch`.  FIFO order is by admission sequence so batching
    is fair; priorities only matter under overload (who gets shed).
    """

    # lock-discipline contract (tools/lint lock-map): producers offer
    # from caller threads, the serve loop consumes; ``_not_empty`` is a
    # Condition BUILT ON ``_lock``, so either spelling holds the same
    # lock — both are declared as acceptable guards.
    _protected_by_ = {
        "_q": ("_lock", "_not_empty"),
        "_rows": ("_lock", "_not_empty"),
        "shed_total": ("_lock", "_not_empty"),
        "rejected_total": ("_lock", "_not_empty"),
        "admitted_total": ("_lock", "_not_empty"),
        "last_refusal_at": ("_lock", "_not_empty"),
        "_drain_rows_per_s": ("_lock", "_not_empty"),
        "_closed": ("_lock", "_not_empty"),
    }

    def __init__(self, max_queue_rows: int = 65_536,
                 max_queue_requests: int = 1024):
        self.max_queue_rows = int(max_queue_rows)
        self.max_queue_requests = int(max_queue_requests)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._q: List[FitRequest] = []
        self._rows = 0
        self.shed_total = 0
        self.rejected_total = 0
        self.admitted_total = 0
        self.last_refusal_at: Optional[float] = None
        # drain-rate EMA (rows/s) feeding the retry_after estimate; seeded
        # pessimistically so a cold server does not promise instant retries
        self._drain_rows_per_s = 1000.0
        self._closed = False

    # -- producer side -------------------------------------------------------

    def offer(self, req: FitRequest,
              on_shed: Optional[Callable] = None) -> None:
        """Enqueue ``req`` or raise :class:`RejectedError`.

        A full queue first tries to shed strictly-lower-priority queued
        requests (lowest priority first, newest first within a priority —
        the work least likely to matter and least far along).  Shed
        requests' tickets are rejected with ``shed=True`` and ``on_shed``
        is called for each (the server refunds quotas/durable state
        there).  If shedding cannot make room, the OFFER is rejected.
        """
        if req.rows > self.max_queue_rows:
            # no amount of shedding admits a panel bigger than the queue —
            # refuse before evicting anyone for nothing
            raise RejectedError(
                f"request of {req.rows} rows exceeds the queue bound "
                f"{self.max_queue_rows}", retry_after_s=0.0)
        with self._lock:
            if self._closed:
                raise RejectedError("server is draining", retry_after_s=5.0)
            shed: List[FitRequest] = []
            while self._over_capacity(req.rows - sum(s.rows for s in shed),
                                      1 - len(shed)):
                victim = self._shed_candidate(req.priority, exclude=shed)
                if victim is None:
                    self.rejected_total += 1
                    self.last_refusal_at = time.monotonic()
                    raise RejectedError(
                        f"queue full ({self._rows} rows / {len(self._q)} "
                        "requests queued)",
                        retry_after_s=self._retry_after(req.rows))
                shed.append(victim)
            for victim in shed:
                self._q.remove(victim)
                self._rows -= victim.rows
                self.shed_total += 1
                self.last_refusal_at = time.monotonic()
                victim.ticket._reject(RejectedError(
                    f"shed for priority-{req.priority} work",
                    retry_after_s=self._retry_after(victim.rows),
                    shed=True))
                if on_shed is not None:
                    on_shed(victim)
            self._q.append(req)
            self._rows += req.rows
            self.admitted_total += 1
            self._not_empty.notify()

    def _over_capacity(self, extra_rows: int, extra_reqs: int) -> bool:
        return (self._rows + extra_rows > self.max_queue_rows
                or len(self._q) + extra_reqs > self.max_queue_requests)

    def _shed_candidate(self, priority: int,
                        exclude: List[FitRequest]) -> Optional[FitRequest]:
        victims = [r for r in self._q
                   if r.priority < priority and r not in exclude]
        if not victims:
            return None
        return min(victims, key=lambda r: (r.priority, -r.seq))

    def _retry_after(self, rows: int) -> float:
        backlog = self._rows + rows
        est = backlog / max(self._drain_rows_per_s, 1e-6)
        return min(60.0, max(0.05, est))

    def cancel(self, req_id: str) -> Optional[FitRequest]:
        """Remove a queued request (caller cancellation); None if it is
        not in the queue (already dispatched, answered, or shed)."""
        with self._lock:
            for r in self._q:
                if r.req_id == req_id:
                    self._q.remove(r)
                    self._rows -= r.rows
                    return r
        return None

    # -- consumer side -------------------------------------------------------

    def take_batch(self, key_fn: Callable, max_rows: int,
                   window_s: float = 0.01,
                   timeout_s: Optional[float] = 0.25,
                   rows_fn: Optional[Callable] = None) -> List[FitRequest]:
        """Pop the next micro-batch: wait up to ``timeout_s`` for a first
        request, linger ``window_s`` for company to coalesce with, then
        greedily collect FIFO requests sharing the first one's batch key
        up to ``max_rows``.  ``rows_fn`` overrides how a request's rows
        count against the cap (the server passes the CELL-PADDED size so
        the packed panel, not just the payload, honors
        ``max_batch_rows``).  Returns ``[]`` on timeout (the serve
        loop's idle tick)."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._not_empty:
            while not self._q:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return []
                if not self._not_empty.wait(timeout=rem):
                    return []
        if window_s > 0:
            # linger OUTSIDE the lock: producers must be able to add the
            # company this window exists to collect
            time.sleep(window_s)
        cost = rows_fn if rows_fn is not None else (lambda r: r.rows)
        with self._lock:
            if not self._q:
                return []
            head = self._q[0]
            key = key_fn(head)
            batch, rows = [], 0
            for r in list(self._q):
                if rows + cost(r) > max_rows and batch:
                    break
                if key_fn(r) == key:
                    batch.append(r)
                    rows += cost(r)
                    if rows >= max_rows:
                        break
            for r in batch:
                self._q.remove(r)
                self._rows -= r.rows
            return batch

    def record_drain(self, rows: int, wall_s: float) -> None:
        """Feed the drain-rate EMA after a batch completes (the
        retry_after backpressure estimate)."""
        if wall_s <= 0 or rows <= 0:
            return
        rate = rows / wall_s
        with self._lock:
            self._drain_rows_per_s = (0.7 * self._drain_rows_per_s
                                      + 0.3 * rate)

    # -- lifecycle / introspection -------------------------------------------

    def close(self) -> List[FitRequest]:
        """Refuse new offers; return (and clear) whatever is still queued."""
        with self._lock:
            self._closed = True
            drained, self._q = self._q, []
            self._rows = 0
            self._not_empty.notify_all()
            return drained

    def depth(self) -> dict:
        with self._lock:
            return {"requests": len(self._q), "rows": self._rows,
                    "max_rows": self.max_queue_rows,
                    "max_requests": self.max_queue_requests,
                    "admitted_total": self.admitted_total,
                    "shed_total": self.shed_total,
                    "rejected_total": self.rejected_total}
