"""Resident serving loop (ISSUE 12 / ROADMAP item 1).

Everything below this package was one-shot — ``panel.fit`` built a plan,
walked it, and exited.  :class:`FitServer` is the long-lived caller the
journal, watchdog, elastic-lane, and obs planes were built for: a daemon
that admits concurrent tenant fit requests under bounded queues and
per-tenant quotas, coalesces compatible panels into micro-batched chunked
walks (demuxed per tenant, bitwise-identical to solo fits), enforces
per-request deadlines through the watchdog, sheds lowest-priority work
under overload with explicit retry-after rejections, quarantines failing
batches, keeps one process-level staging pool and the compile cache warm
across requests, journals every batch so a SIGKILLed server resumes
in-flight work bitwise on restart, and streams its health and metrics
through the Prometheus-textfile sink (``obs.promsink``).

Quickstart::

    from spark_timeseries_tpu import serving

    with serving.FitServer("/srv/fits", max_batch_rows=8192,
                           prom_path="/metrics/fits.prom") as srv:
        ticket = srv.submit("tenant-a", y, "arima", order=(1, 1, 1),
                            deadline_s=30.0)
        res = ticket.result()          # TenantFitResult, rows == y rows
        res.status                     # per-row FitStatus, TIMEOUT capped

- :mod:`.session` — requests, tickets, results, the error vocabulary
  (:class:`RejectedError` with ``retry_after_s`` is the backpressure
  signal).
- :mod:`.admission` — the bounded queue, priority shedding, tenant
  quotas.
- :mod:`.batcher` — micro-batch packing/demux and the durable batch
  membership records recovery replays.
- :mod:`.server` — the :class:`FitServer` daemon itself.
- :mod:`.transport` — the length-prefixed socket wire protocol
  (ISSUE 16): CRC-framed messages carrying the durable npz+JSON request
  spelling verbatim, and :class:`TransportServer`, the per-replica
  socket front end.
- :mod:`.client` — :class:`FitClient`: kill-tolerant remote access with
  idempotent resubmit on existing request ids, bounded deterministic
  backoff, per-call deadlines, and reconnect-safe result polling.
- :mod:`.health` — :class:`EndpointHealthCache` (ISSUE 17): the client's
  per-endpoint circuit breaker / primary belief / latency EWMA; writes
  prefer the believed primary, reads fan to healthy standbys, failing
  endpoints cool down on a seeded deterministic schedule.
- :mod:`.profiles` — :class:`TenantProfileStore` (ISSUE 19): durable
  per-tenant auto-fit profiles with TTL/count eviction; repeat tenants
  route to warm stepwise searches.
- :mod:`.tickloop` — :class:`TickLoop` (ISSUE 20): the tick-to-forecast
  streaming loop — record tick batch, idempotent shard append,
  delta-warm refit, forecast, publish through a write-back sink, all as
  one journaled cycle that resumes bitwise after SIGKILL.
- :mod:`.fleet` — :class:`FleetReplica`: N replicas on one checkpoint
  root under a lease/fencing protocol; a SIGKILLed primary's write-ahead
  requests are taken over and re-answered bitwise by a surviving peer,
  and stale-token zombies lose loudly (:class:`FencedError`).  ISSUE 17
  adds the degradation ladder: standbys serve forecast READS from a
  private scratch root, leaderless windows answer typed ``read_only``,
  and a primary whose disk refuses writes steps down cleanly
  (:class:`StorageError` backpressure, ``storage_degraded`` on the wire).
"""

from . import (admission, batcher, client, fleet, health, profiles, server,
               session, tickloop, transport)
from .admission import AdmissionQueue, TenantQuota
from .batcher import MicroBatch, batch_key
from .client import ClientDeadlineError, FitClient, RemoteTicket, backoff_schedule
from .fleet import FleetReplica, discover_endpoints
from .health import EndpointHealthCache, cooldown_schedule
from .profiles import TenantProfileStore
from .server import FORECAST_MODEL, FitServer
from .session import (CancelledError, FitRequest, FitTicket, RejectedError,
                      ServerClosedError, StorageError, TenantFitResult)
from .tickloop import CycleResult, TickLoop, TickLoopError
from .transport import (FrameError, NotLeaderError, ReadOnlyError,
                        TransportError, TransportServer, WireAuthError,
                        resolve_wire_secret)

__all__ = [
    "AdmissionQueue",
    "CancelledError",
    "ClientDeadlineError",
    "CycleResult",
    "EndpointHealthCache",
    "FORECAST_MODEL",
    "FitClient",
    "FitRequest",
    "FitServer",
    "FitTicket",
    "FleetReplica",
    "FrameError",
    "MicroBatch",
    "NotLeaderError",
    "ReadOnlyError",
    "RejectedError",
    "RemoteTicket",
    "ServerClosedError",
    "StorageError",
    "TenantFitResult",
    "TenantProfileStore",
    "TenantQuota",
    "TickLoop",
    "TickLoopError",
    "TransportError",
    "TransportServer",
    "WireAuthError",
    "admission",
    "backoff_schedule",
    "batch_key",
    "batcher",
    "client",
    "cooldown_schedule",
    "discover_endpoints",
    "fleet",
    "health",
    "profiles",
    "resolve_wire_secret",
    "server",
    "session",
    "tickloop",
    "transport",
]
