"""Micro-batching: pack compatible tenant panels into ONE chunked walk.

The serving workload is many small panels (a tenant's dozens-to-thousands
of series), and dispatching each alone wastes the device the same way
PR 9's per-order walks wasted it before fusion: launch overhead and
underfull programs dominate.  The batcher is the order-axis packing idea
applied to TENANTS — requests sharing a batch key (model, panel width,
dtype, fit kwargs, align mode, resilience knobs, deadline-ness) are
concatenated row-wise into one panel, walked once through
``reliability.fit_chunked``, and demuxed back per request.

**The cell grid is what makes batching bitwise-safe.**  Per-row results
of the bundled fits are independent BETWEEN chunks but carry low-order
bits of their chunk's SHAPE within one (the lockstep batched L-BFGS and
its straggler compaction see the whole chunk), so naive concatenation
would make a tenant's numbers depend on who it was batched with.  The
batcher therefore quantizes: every request is padded (repeating its last
row; pad rows dropped at demux) to a multiple of the server's
``cell_rows``, the packed walk runs at ``chunk_rows == cell_rows``, and
every chunk thus holds rows of exactly ONE request with
position-identical bytes whether the request rides a big batch or goes
solo — the demuxed slice is bitwise-identical to the same request
submitted alone (and to a direct ``fit_chunked(chunk_rows=cell_rows)``
walk whenever the request's row count is already a cell multiple), the
property ``tests/test_serving.py`` pins.  The key includes the
per-request align mode (computed host-side at admission) because the
align plan selects the compiled program: same-mode panels concatenate to
the same mode, so the hint the batch walk runs under is exactly the hint
each solo walk would run under.

A batch's membership is DURABLE before its walk starts
(:meth:`MicroBatch.save_members`): the batch id is a deterministic hash of
the member request ids, the walk journals under
``<root>/batches/<batch_id>/journal``, and a SIGKILLed server re-forms the
batch from its members record on restart — the journal then resumes
bitwise, replaying only uncommitted chunks.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Sequence

import numpy as np

from ..reliability.status import STATUS_DTYPE, FitStatus, status_counts
from .session import FitRequest, TenantFitResult

__all__ = ["MicroBatch", "batch_key", "pack", "timeout_result"]

MEMBERS_FILE = "members.json"
COMPLETE_FILE = "COMPLETE"


def batch_key(req: FitRequest) -> tuple:
    """Requests coalesce iff their keys are equal.

    Everything that selects the compiled program or changes per-row
    semantics is in the key; the tenant, priority, and row count are not
    (those are what batching is supposed to mix).  Deadline-BEARING
    requests only coalesce with other deadline-bearing ones: a batch's
    job budget is the earliest member deadline, and budgetless requests
    must never inherit someone else's clock.
    """
    model = req.model if isinstance(req.model, str) else repr(req.model)
    return (
        model,
        int(req.values.shape[1]),
        str(req.values.dtype),
        json.dumps(req.fit_kwargs, sort_keys=True, default=repr),
        req.align_mode,
        req.resilient,
        req.policy,
        req.deadline_s is not None,
    )


class MicroBatch:
    """An ordered bundle of requests packed onto one cell-quantized panel.

    Each member occupies ``ceil(rows / cell_rows)`` whole cells starting
    at a cell boundary (short members padded by repeating their last
    row); ``spans`` are the members' REAL row spans inside the padded
    panel, and :meth:`demux` drops the pad rows.  The walk must run at
    ``chunk_rows == cell_rows`` so chunk bytes per request are
    position-identical across batch compositions (module docstring).
    """

    __slots__ = ("members", "spans", "values", "batch_id", "seq",
                 "cell_rows", "pad_rows")

    def __init__(self, members: Sequence[FitRequest], seq: int,
                 cell_rows: int = 1):
        if not members:
            raise ValueError("a micro-batch needs at least one request")
        self.members: List[FitRequest] = list(members)
        self.seq = int(seq)
        self.cell_rows = max(1, int(cell_rows))
        cell = self.cell_rows
        spans, parts, lo, pad_total = [], [], 0, 0
        for r in self.members:
            spans.append((lo, lo + r.rows))
            parts.append(np.asarray(r.values))
            pad = (-r.rows) % cell
            if pad:
                parts.append(np.repeat(np.asarray(r.values)[-1:], pad,
                                       axis=0))
            lo += r.rows + pad
            pad_total += pad
        self.spans = spans
        self.pad_rows = pad_total
        self.values = (np.ascontiguousarray(parts[0]) if len(parts) == 1
                       else np.concatenate(parts))
        # deterministic identity: the same membership (the unit recovery
        # replays) names the same journal directory on every process
        h = hashlib.sha256(
            "\n".join(m.req_id for m in self.members).encode())
        self.batch_id = f"b{h.hexdigest()[:16]}"

    @property
    def rows(self) -> int:
        return int(self.values.shape[0])

    @property
    def payload_rows(self) -> int:
        """Real (unpadded) rows across members."""
        return self.rows - self.pad_rows

    @property
    def tenants(self) -> tuple:
        return tuple(dict.fromkeys(m.tenant for m in self.members))

    def job_budget_s(self) -> Optional[float]:
        """The batch walk's wall budget: the earliest member deadline
        still outstanding (None when no member carries one — the batch
        key keeps the two populations apart)."""
        rems = [m.remaining_s() for m in self.members
                if m.deadline_s is not None]
        rems = [r for r in rems if r is not None]
        if not rems:
            return None
        return max(0.0, min(rems))

    # -- durable membership record -------------------------------------------

    def dir(self, root: str) -> str:
        return os.path.join(root, "batches", self.batch_id)

    def save_members(self, root: str, knobs: dict) -> str:
        """Write the membership + walk knobs record (atomic) BEFORE the
        walk: restart recovery re-forms exactly this batch with exactly
        these knobs, so the journal's config hash matches and committed
        chunks replay instead of recomputing."""
        d = self.dir(root)
        os.makedirs(d, exist_ok=True)
        rec = {
            "batch_id": self.batch_id,
            "seq": self.seq,
            "cell_rows": self.cell_rows,
            "members": [{"req_id": m.req_id, "tenant": m.tenant,
                         "rows": m.rows} for m in self.members],
            "knobs": knobs,
        }
        path = os.path.join(d, MEMBERS_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def mark_complete(self, root: str) -> None:
        """Every member's result is durable: the batch never re-runs."""
        path = os.path.join(self.dir(root), COMPLETE_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("complete\n")
        os.replace(tmp, path)

    # -- demux ---------------------------------------------------------------

    def demux(self, res) -> List[TenantFitResult]:
        """Slice a ``ResilientFitResult`` of the packed panel back into
        per-request results (copies: a request's arrays must not pin the
        whole batch panel alive in the server)."""
        out = []
        batch_meta = {
            "batch_id": self.batch_id,
            "batch_rows": self.rows,
            "batch_members": len(self.members),
            "chunk_rows_final": res.meta.get("chunk_rows_final"),
            "degraded": res.meta.get("degraded", False),
        }
        if "journal" in res.meta:
            batch_meta["journal"] = {
                k: res.meta["journal"].get(k)
                for k in ("dir", "run_id", "chunks_committed",
                          "chunks_resumed", "chunks_timeout")}
        for m, (lo, hi) in zip(self.members, self.spans):
            status = np.array(res.status[lo:hi])
            out.append(TenantFitResult(
                params=np.array(res.params[lo:hi]),
                neg_log_likelihood=np.array(res.neg_log_likelihood[lo:hi]),
                converged=np.array(res.converged[lo:hi]),
                iters=np.array(res.iters[lo:hi]),
                status=status,
                meta={**batch_meta, "req_id": m.req_id, "tenant": m.tenant,
                      "status_counts": status_counts(status)},
            ))
        return out


def pack(members: Sequence[FitRequest], seq: int,
         cell_rows: int = 1) -> MicroBatch:
    """Build a :class:`MicroBatch` (members must share a batch key —
    the admission queue's ``take_batch`` guarantees it)."""
    return MicroBatch(members, seq, cell_rows)


def timeout_result(req: FitRequest, reason: str) -> TenantFitResult:
    """An all-TIMEOUT answer for a request whose deadline expired before
    its batch dispatched — the serving twin of the chunk driver's
    undispatched-chunk TIMEOUT marks (params NaN, status TIMEOUT, never a
    hang).  ``k`` degenerates to one NaN column exactly like an
    all-TIMEOUT walk."""
    n = req.rows
    dtype = req.values.dtype
    status = np.full(n, FitStatus.TIMEOUT, STATUS_DTYPE)
    return TenantFitResult(
        params=np.full((n, 1), np.nan, dtype),
        neg_log_likelihood=np.full(n, np.nan, dtype),
        converged=np.zeros(n, bool),
        iters=np.zeros(n, np.int32),
        status=status,
        meta={"req_id": req.req_id, "tenant": req.tenant,
              "deadline_expired": True, "reason": reason,
              "status_counts": status_counts(status)},
    )
