"""Client-side endpoint health: the cache that turns a dumb endpoint
list into informed routing (ISSUE 17).

PR 16's :class:`~.client.FitClient` treated its endpoints as a blind
rotation: every failure advanced a cursor, every caller re-discovered
the same dead replica by timing out on it.  ROADMAP item 1 names the
fix — client-side endpoint health caching — and this module is it:

- **consecutive-failure circuit breaker**: an endpoint that fails
  ``failure_threshold`` calls in a row has its circuit opened for a
  cooldown; while open it sorts LAST (tried only when everything else
  is worse), and when the cooldown elapses exactly one call probes it
  (half-open) before the circuit fully closes again.
- **seeded deterministic cooldowns**: the cooldown for the N-th
  consecutive opening is exponential with multiplicative jitter derived
  from ``sha256(seed, endpoint, opening)`` — the same seed replays the
  same schedule in every process, so failover timing is testable
  byte-for-byte (the same construction as
  :func:`~.client.backoff_schedule`).
- **EWMA latency**: successful calls fold their wall clock into an
  exponentially-weighted moving average per endpoint, the tiebreak
  among equally-healthy endpoints (rounded to 10 ms so measurement
  noise cannot flap the order).
- **primary belief**: a successful WRITE marks its endpoint as the
  believed primary; a ``not_leader`` redirect clears the belief.
  :meth:`order` puts the believed primary first for writes and is
  indifferent for reads — reads fan out to whatever is healthy,
  which is what lets standbys carry read load.

Everything here is bitwise-neutral: the cache only changes WHERE a
request lands, never what bytes answer it (results are durable npz
records, identical from every replica).  ``now`` is injectable on every
mutating call so tests drive the clock explicitly.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import obs

__all__ = ["EndpointHealthCache", "cooldown_schedule"]

Endpoint = Tuple[str, int]


def cooldown_schedule(seed: int, endpoint: Endpoint, openings: int, *,
                      base_s: float = 0.25,
                      max_s: float = 8.0) -> List[float]:
    """The deterministic circuit-open cooldowns for one endpoint: the
    N-th consecutive opening waits ``min(max_s, base_s * 2**N)`` scaled
    by jitter in ``[0.5, 1.0)`` from ``sha256(seed, endpoint, N)`` —
    same seed, same schedule, every process (mirrors
    :func:`~.client.backoff_schedule`)."""
    out = []
    for n in range(int(openings)):
        cap = min(float(max_s), float(base_s) * (2.0 ** n))
        digest = hashlib.sha256(
            f"cooldown:{int(seed)}:{endpoint[0]}:{endpoint[1]}:{n}"
            .encode()).digest()
        frac = 0.5 + (int.from_bytes(digest[:8], "big") / 2.0 ** 64) * 0.5
        out.append(cap * frac)
    return out


class _EndpointRecord:
    __slots__ = ("consec_failures", "open_until", "openings", "ewma_s",
                 "successes", "failures", "probing", "redirected_until")

    def __init__(self):
        self.consec_failures = 0
        self.open_until: Optional[float] = None  # monotonic; None=closed
        self.openings = 0  # consecutive circuit openings (cooldown index)
        self.ewma_s: Optional[float] = None
        self.successes = 0
        self.failures = 0
        self.probing = False  # half-open: one in-flight probe
        self.redirected_until: Optional[float] = None  # "not primary" memo


class EndpointHealthCache:
    """Per-endpoint health state shared by one client (see module doc).

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): many caller
        threads poll tickets concurrently and every one of them reads
        and mutates the shared records — all record and primary-belief
        mutation happens under the cache lock.
    """

    _protected_by_ = {
        "_records": "_lock",
        "_primary": "_lock",
    }

    def __init__(self, endpoints, *, seed: int = 0,
                 failure_threshold: int = 3,
                 cooldown_base_s: float = 0.25,
                 cooldown_max_s: float = 8.0,
                 ewma_alpha: float = 0.3,
                 redirect_memo_s: float = 1.0):
        self.endpoints: List[Endpoint] = [
            (str(h), int(p)) for (h, p) in endpoints]
        if not self.endpoints:
            raise ValueError("EndpointHealthCache needs >= 1 endpoint")
        self.seed = int(seed)
        self.failure_threshold = int(failure_threshold)
        self.cooldown_base_s = float(cooldown_base_s)
        self.cooldown_max_s = float(cooldown_max_s)
        self.ewma_alpha = float(ewma_alpha)
        self.redirect_memo_s = float(redirect_memo_s)
        self._lock = threading.Lock()
        self._records: Dict[Endpoint, _EndpointRecord] = {
            ep: _EndpointRecord() for ep in self.endpoints}
        self._primary: Optional[Endpoint] = None

    # -- clock ---------------------------------------------------------------

    @staticmethod
    def _now(now: Optional[float]) -> float:
        return time.monotonic() if now is None else float(now)

    # -- routing -------------------------------------------------------------

    def order(self, *, write: bool = False,
              now: Optional[float] = None) -> List[Endpoint]:
        """Every endpoint, best-first.  Healthy circuits sort before
        probe-due ones before open ones; among healthy, a write prefers
        the believed primary, then fewer recent failures, then the
        rounded EWMA latency, then index.  Never empty — with every
        circuit open the least-bad endpoint still gets knocked on
        (refusing to try anything is strictly worse than probing)."""
        t = self._now(now)
        with self._lock:
            primary = self._primary

            def key(item):
                idx, ep = item
                rec = self._records[ep]
                if rec.open_until is None:
                    state = 0  # closed: healthy
                elif t >= rec.open_until:
                    state = 1  # cooldown elapsed: probe half-open
                else:
                    state = 2  # open: last resort
                primary_rank = 0 if (write and ep == primary) else 1
                # a write avoids endpoints that RECENTLY said not_leader
                # (the memo expires on the lease-TTL scale, so an
                # elected ex-standby gets re-knocked on soon enough)
                redirected = (write and rec.redirected_until is not None
                              and t < rec.redirected_until)
                lat = (float("inf") if rec.ewma_s is None
                       else round(rec.ewma_s, 2))
                return (state, primary_rank, int(redirected),
                        rec.consec_failures, lat, idx)

            ranked = sorted(enumerate(self.endpoints), key=key)
            first = ranked[0][1]
            rec = self._records[first]
            probing = (rec.open_until is not None and t >= rec.open_until)
            if probing:
                rec.probing = True
                obs.counter("client.endpoint_health.probes").inc()
        if probing:
            obs.event("client.endpoint_half_open", endpoint=list(first))
        return [ep for _, ep in ranked]

    def believed_primary(self) -> Optional[Endpoint]:
        with self._lock:
            return self._primary

    # -- outcome recording ---------------------------------------------------

    def record_success(self, ep: Endpoint, latency_s: Optional[float] = None,
                       now: Optional[float] = None) -> None:
        with self._lock:
            rec = self._records.get(ep)
            if rec is None:
                return
            reopened = rec.open_until is not None
            rec.successes += 1
            rec.consec_failures = 0
            rec.open_until = None
            rec.openings = 0
            rec.probing = False
            if latency_s is not None:
                lat = float(latency_s)
                rec.ewma_s = (lat if rec.ewma_s is None else
                              self.ewma_alpha * lat +
                              (1.0 - self.ewma_alpha) * rec.ewma_s)
        if reopened:
            obs.counter("client.endpoint_health.recovered").inc()
            obs.event("client.endpoint_recovered", endpoint=list(ep))

    def record_failure(self, ep: Endpoint,
                       now: Optional[float] = None) -> None:
        t = self._now(now)
        opened = False
        with self._lock:
            rec = self._records.get(ep)
            if rec is None:
                return
            was_probing = rec.probing
            rec.failures += 1
            rec.consec_failures += 1
            rec.probing = False
            if self._primary == ep:
                self._primary = None
            if rec.consec_failures >= self.failure_threshold:
                cooldown = cooldown_schedule(
                    self.seed, ep, rec.openings + 1,
                    base_s=self.cooldown_base_s,
                    max_s=self.cooldown_max_s)[rec.openings]
                rec.open_until = t + cooldown
                rec.openings += 1
                rec.consec_failures = 0
                opened = True
        obs.counter("client.endpoint_health.failures").inc()
        if was_probing:
            # a half-open probe that failed: the cooldown re-arms below
            obs.event("client.endpoint_probe_failed", endpoint=list(ep))
        if opened:
            obs.counter("client.endpoint_health.opened").inc()
            obs.event("client.endpoint_circuit_open", endpoint=list(ep))

    def record_redirect(self, ep: Endpoint,
                        now: Optional[float] = None) -> None:
        """A ``not_leader`` reply: the endpoint is ALIVE (it answered)
        but is not the primary — clear any stale primary belief and
        memo "not primary" for a lease-TTL-ish window, without dinging
        its health (reads still route here happily)."""
        t = self._now(now)
        with self._lock:
            rec = self._records.get(ep)
            if rec is not None:
                rec.consec_failures = 0
                rec.redirected_until = t + self.redirect_memo_s
            if self._primary == ep:
                self._primary = None
        obs.counter("client.endpoint_health.redirects").inc()
        obs.event("client.endpoint_redirected", endpoint=list(ep))

    def set_primary(self, ep: Endpoint) -> None:
        with self._lock:
            changed = self._primary != ep
            self._primary = ep
            rec = self._records.get(ep)
            if rec is not None:
                rec.redirected_until = None
        if changed:
            obs.event("client.primary_learned", endpoint=list(ep))

    # -- introspection -------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> dict:
        t = self._now(now)
        with self._lock:
            return {
                "primary": (list(self._primary)
                            if self._primary is not None else None),
                "endpoints": {
                    f"{h}:{p}": {
                        "open": (rec.open_until is not None
                                 and t < rec.open_until),
                        "consec_failures": rec.consec_failures,
                        "openings": rec.openings,
                        "successes": rec.successes,
                        "failures": rec.failures,
                        "ewma_s": (None if rec.ewma_s is None
                                   else round(rec.ewma_s, 4)),
                    }
                    for (h, p), rec in self._records.items()},
            }
