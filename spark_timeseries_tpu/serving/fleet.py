"""Lease-fenced FitServer replicas sharing one checkpoint root.

ISSUE 16's failover half.  The durable story under ``<root>`` (write-
ahead requests, batch journals, results) is a single-writer protocol, so
a fleet of N replicas must elect exactly one writer — and keep a
SIGKILLed writer's ZOMBIE (the same process restarted, or a stalled
thread waking up mid-write) from ever splicing bytes over its
successor's.  Both come from ``reliability.journal``'s lease records:

- a replica becomes **primary** by winning :func:`~..reliability.journal.
  acquire_lease` (an ``O_EXCL`` claim manifest allocates a strictly
  monotonic fencing token); it then constructs a :class:`~.server.
  FitServer` on the shared root — whose normal crash RECOVERY is what
  re-answers the dead peer's write-ahead requests, bitwise — and
  heartbeats the lease while serving.
- every durable write the primary performs is **fenced**: the journal
  commit hook and the result store both re-check the token first, so a
  stale holder dies with :class:`~..reliability.journal.FencedError`
  mid-write instead of corrupting the root (stale-token writers lose
  loudly).
- **standbys** poll the lease and serve the transport meanwhile:
  submits answer ``not_leader`` (the client rotates and retries), but
  result polls are answered FROM THE DURABLE FILES — a completed
  request's result is readable through any replica, which is what makes
  client polling survive the primary's death without waiting out the
  lease TTL.

ISSUE 17 widens the standby story from "polls only" into a degradation
LADDER (full → read_only → storage_degraded → fenced):

- **standby reads**: a standby also answers ``submit_forecast`` — a
  forecast derives from the request's own params with a content-derived
  interval seed, so ANY replica computes it bitwise-identically; the
  standby runs the walk on a private per-owner scratch root
  (``<root>/standby_scratch/<owner>``) that never touches the
  single-writer namespaces, answering straight from the shared durable
  results when the id was already answered.  During a LEADERLESS window
  plain submits degrade from ``not_leader`` ("retry elsewhere") to the
  typed ``read_only`` ("retry later — an election is in flight") while
  reads keep flowing.
- **storage-fault tolerance**: a primary whose root refuses writes —
  write-ahead refused at admission (typed ``storage_degraded``
  backpressure, see :class:`~.session.StorageError`), a heartbeat that
  cannot land, a result store that dies with ``OSError`` — steps DOWN
  cleanly through the fence instead of crashing opaque, then sits out
  elections for a cooldown while its disk is suspect (reads still
  served).  A torn stored result is discarded and downgraded to
  recompute-or-redirect, never served.

Topology: every replica runs its own :class:`~.transport.TransportServer`
and advertises its endpoint under ``<root>/endpoints/`` so clients (and
the ci fleet smoke) can discover the fleet from the root alone.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..reliability import journal as journal_mod
from ..reliability.journal import FencedError
from . import transport as transport_mod
from .server import FitServer
from .session import FitTicket, TenantFitResult
from .transport import NotLeaderError, TransportServer

__all__ = [
    "FleetReplica",
    "advertise_endpoint",
    "discover_endpoints",
    "withdraw_endpoint",
]

ENDPOINTS_DIR = "endpoints"
SCRATCH_DIR = "standby_scratch"

# the degradation ladder, as the `fleet.state` gauge spells it (rising
# numbers = rising degradation; dashboards alert on a raw threshold)
STATE_CODES = {
    "full": 0,          # primary, serving writes and reads
    "recovering": 1,    # primary-elect replaying the dead peer's queue
    "standby": 2,       # a live leader exists elsewhere; reads served here
    "read_only": 3,     # leaderless window: reads only, writes wait
    "storage_degraded": 4,  # this replica's disk is suspect; sitting out
    "retired": 5,
    "stopped": 6,
}


# ---------------------------------------------------------------------------
# endpoint advertisement (fleet discovery from the root alone)
# ---------------------------------------------------------------------------


def advertise_endpoint(root: str, owner: str, host: str, port: int) -> None:
    """Durably advertise a replica's transport endpoint under the root
    (atomic: a discovering client never reads a torn advert)."""
    d = os.path.join(os.path.abspath(root), ENDPOINTS_DIR)
    os.makedirs(d, exist_ok=True)
    journal_mod._atomic_write_bytes(
        os.path.join(d, f"{owner}.json"),
        (json.dumps({"owner": str(owner), "host": str(host),
                     "port": int(port), "pid": os.getpid()},
                    sort_keys=True) + "\n").encode())


def withdraw_endpoint(root: str, owner: str) -> None:
    try:
        os.remove(os.path.join(os.path.abspath(root), ENDPOINTS_DIR,
                               f"{owner}.json"))
    except OSError:
        pass


def discover_endpoints(root: str) -> List[Tuple[str, int]]:
    """Every advertised ``(host, port)`` under the root, owner-sorted.
    Stale adverts (a SIGKILLed replica never withdraws) are harmless:
    clients treat a refused connection as one more rotate-and-retry."""
    d = os.path.join(os.path.abspath(root), ENDPOINTS_DIR)
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, fn)) as f:
                rec = json.load(f)
            out.append((str(rec["host"]), int(rec["port"])))
        except (OSError, json.JSONDecodeError, KeyError, ValueError):
            continue
    return out


def _load_result_file(path: str) -> TenantFitResult:
    """A stored result, loaded WITHOUT a server instance (the standby
    poll path): same npz spelling as ``FitServer._store_result``."""
    with open(path, "rb") as f:
        return transport_mod.decode_result_blob(f.read())


# ---------------------------------------------------------------------------
# the fenced server: every durable write re-checks the token first
# ---------------------------------------------------------------------------


class _FencedFitServer(FitServer):
    """A FitServer whose durable writes are gated by a fleet lease.

    Two fences cover every byte the server lands on the shared root:
    the journal commit hook (checked at each durable chunk commit, so a
    zombie's batch walk dies mid-batch) and the result store (so a walk
    that finished before the fence flipped still cannot splice its
    result file over the new primary's).  Both raise
    :class:`FencedError` — the crash path, not a degrade."""

    def __init__(self, root: str, lease: journal_mod.Lease, **kwargs):
        self._fleet_lease = lease
        user_hook = kwargs.pop("_commit_hook", None)

        def fenced_hook(event: str, lo: int) -> None:
            if event == "committed":
                lease.check()
            if user_hook is not None:
                user_hook(event, lo)

        super().__init__(root, _commit_hook=fenced_hook, **kwargs)
        # third fence (ISSUE 19): tenant profiles are warm-start state on
        # the SHARED root — a zombie's late profile write would poison
        # the survivor's routing, so it obeys the same token discipline
        self.profiles.fence = lease.check

    def _store_result(self, req_id: str, res) -> None:
        self._fleet_lease.check()
        super()._store_result(req_id, res)


# ---------------------------------------------------------------------------
# the replica
# ---------------------------------------------------------------------------


class FleetReplica:
    """One member of a FitServer fleet on a shared checkpoint root.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): the control
        thread elects/demotes while transport handler threads read the
        role and delegate to the leased server, and ``stop()`` may come
        from any thread — the role/lease/server triple and the counters
        mutate only under their locks.

    Lifecycle: ``start()`` brings up the transport (standbys answer),
    advertises the endpoint, and runs the control thread — a loop of
    ``acquire_lease`` → serve-as-primary (heartbeating every ``ttl/3``)
    → demote on crash/fence/stop.  ``server_kwargs`` configure the
    FitServer a primary constructs (fault hooks ride ``_commit_hook``
    exactly as on a standalone server).  ``retire_on_crash=True`` keeps
    a crashed replica down instead of re-electing it — what the
    deterministic failover tests use to pin WHO takes over.
    """

    _protected_by_ = {
        "_server": "_state_lock",
        "_lease": "_state_lock",
        "_role": "_state_lock",
        "_storage_degraded_until": "_state_lock",
        "_scratch": "_scratch_lock",
        "counters": "_counters_lock",
    }

    def __init__(self, root: str, *,
                 owner: Optional[str] = None,
                 ttl_s: float = 5.0,
                 host: str = "127.0.0.1",
                 port: int = 0,
                 standby_poll_s: Optional[float] = None,
                 server_kwargs: Optional[dict] = None,
                 retire_on_crash: bool = False,
                 storage_cooldown_s: float = 5.0,
                 server_ready_timeout_s: float = 300.0):
        self.root = os.path.abspath(root)
        self.owner = owner or f"replica-{uuid.uuid4().hex[:8]}"
        self.ttl_s = float(ttl_s)
        self.standby_poll_s = (self.ttl_s / 4.0 if standby_poll_s is None
                               else float(standby_poll_s))
        self.server_kwargs = dict(server_kwargs or {})
        self.retire_on_crash = bool(retire_on_crash)
        self.storage_cooldown_s = float(storage_cooldown_s)
        self.server_ready_timeout_s = float(server_ready_timeout_s)
        self._requests_dir = os.path.join(self.root, "requests")
        self._results_dir = os.path.join(self.root, "results")
        self._transport = TransportServer(self, host=host, port=port)
        self._state_lock = threading.Lock()
        self._server: Optional[FitServer] = None
        self._lease: Optional[journal_mod.Lease] = None
        self._role = "standby"
        self._storage_degraded_until = 0.0
        self._scratch: Optional[FitServer] = None
        self._scratch_lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "elections": 0, "fenced_demotions": 0, "crash_demotions": 0,
            "storage_demotions": 0, "heartbeats": 0, "standby_reads": 0,
            "torn_results": 0,
        }
        self._counters_lock = threading.Lock()
        self._stop = threading.Event()
        self._control: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetReplica":
        if self._control is not None:
            raise RuntimeError("FleetReplica.start() called twice")
        self._transport.start()
        host, port = self._transport.address
        advertise_endpoint(self.root, self.owner, host, port)
        self._control = threading.Thread(
            target=self._control_loop, daemon=True,
            name=f"fleet-control-{self.owner}")
        self._control.start()
        return self

    def stop(self, timeout_s: float = 300.0) -> None:
        self._stop.set()
        t = self._control
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        self._transport.stop()
        with self._scratch_lock:
            scratch, self._scratch = self._scratch, None
        if scratch is not None:
            try:
                scratch.stop(drain=False)
            except Exception:  # noqa: BLE001 - teardown must complete
                pass
        withdraw_endpoint(self.root, self.owner)
        self._publish_state()

    def __enter__(self) -> "FleetReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def address(self) -> Tuple[str, int]:
        return self._transport.address

    def role(self) -> str:
        with self._state_lock:
            return self._role

    def lease_token(self) -> Optional[int]:
        with self._state_lock:
            return None if self._lease is None else self._lease.token

    def wait_role(self, role: str, timeout_s: float = 60.0) -> bool:
        """Poll until this replica reports ``role`` (tests/orchestration)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if self.role() == role:
                return True
            time.sleep(0.02)
        return self.role() == role

    # -- the degradation ladder ----------------------------------------------

    def state(self) -> str:
        """Where this replica sits on the degradation ladder:
        ``full`` (primary serving) → ``standby`` (a live leader exists;
        reads served here) → ``read_only`` (leaderless window) →
        ``storage_degraded`` (own disk suspect; sitting out elections)
        → ``retired``/``stopped``.  Distinct from :meth:`role`, which
        stays the raw election role for orchestration."""
        with self._state_lock:
            role = self._role
            degraded_until = self._storage_degraded_until
        if role == "primary":
            return "full"
        if role in ("recovering", "retired", "stopped"):
            return role
        if time.monotonic() < degraded_until:
            return "storage_degraded"
        if not journal_mod.lease_is_live(self.root):
            return "read_only"
        return "standby"

    def _publish_state(self) -> str:
        state = self.state()
        obs.gauge("fleet.state").set(float(STATE_CODES.get(state, -1.0)))
        return state

    def _note_storage_degraded(self, why: str, **fields) -> None:
        """A write on the shared root failed with OSError: mark the disk
        suspect for a cooldown (no elections, reads still served)."""
        until = time.monotonic() + self.storage_cooldown_s
        with self._state_lock:
            self._storage_degraded_until = until
        obs.counter("fleet.storage_degraded").inc()
        obs.event("fleet.step_down", owner=self.owner, reason="storage",
                  why=why, cooldown_s=self.storage_cooldown_s, **fields)
        self._publish_state()

    # -- the control loop (election / heartbeat / demotion) ------------------

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            with self._state_lock:
                degraded_until = self._storage_degraded_until
            if time.monotonic() < degraded_until:
                # suspect disk: a win here would just step down again —
                # sit out elections (reads keep flowing) until cooldown
                self._stop.wait(self.standby_poll_s)
                continue
            try:
                lease = journal_mod.acquire_lease(self.root, self.owner,
                                                  ttl_s=self.ttl_s)
            except OSError as e:
                # could not even WRITE a claim: the root refuses us
                self._note_storage_degraded("acquire_lease",
                                            error=repr(e)[:200])
                continue
            if lease is None:
                self._stop.wait(self.standby_poll_s)
                continue
            srv = _FencedFitServer(self.root, lease, **self.server_kwargs)
            with self._counters_lock:
                self.counters["elections"] += 1
            obs.event("fleet.elected", owner=self.owner, token=lease.token)
            try:
                srv.start(wait_ready=False)
            except RuntimeError:
                pass  # start() raced stop(); the loop below settles it
            with self._state_lock:
                self._lease = lease
                self._server = srv
                self._role = "recovering"
            self._publish_state()
            outcome = self._serve_as_primary(srv, lease)
            # demotion: tear the server down first, then settle the lease
            try:
                srv.stop(drain=(outcome == "stopping"))
            except Exception:  # noqa: BLE001 - demotion must complete
                pass
            try:
                lease.release()
            except (FencedError, OSError):
                # the successor already owns the root, or the disk that
                # just demoted us refuses the release too — either way
                # the lease record expires by TTL
                pass
            with self._state_lock:
                self._lease = None
                self._server = None
                self._role = "standby"
            if outcome == "fenced":
                with self._counters_lock:
                    self.counters["fenced_demotions"] += 1
                obs.event("fleet.fenced", owner=self.owner,
                          token=lease.token)
            elif outcome == "storage":
                with self._counters_lock:
                    self.counters["storage_demotions"] += 1
                self._note_storage_degraded("step_down", token=lease.token)
            elif outcome == "crashed":
                with self._counters_lock:
                    self.counters["crash_demotions"] += 1
                if self.retire_on_crash:
                    with self._state_lock:
                        self._role = "retired"
                    self._publish_state()
                    return
            self._publish_state()
        with self._state_lock:
            if self._role != "retired":
                self._role = "stopped"

    def _serve_as_primary(self, srv: FitServer,
                          lease: journal_mod.Lease) -> str:
        """Heartbeat until stop/crash/fence; returns the demotion cause.
        The heartbeat runs DURING recovery too — a takeover whose replay
        outlives the ttl must not lose the lease it is replaying under."""
        beat = max(0.01, self.ttl_s / 3.0)
        last = 0.0
        ready_at: Optional[float] = None
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last >= beat:
                try:
                    lease.heartbeat()
                except FencedError:
                    return "fenced"
                except OSError:
                    # a heartbeat that cannot LAND is a storage fault,
                    # not a lost election: step down before the stale
                    # record fences us mid-write
                    return "storage"
                last = now
                with self._counters_lock:
                    self.counters["heartbeats"] += 1
            state = srv.state()
            if state == "crashed":
                if isinstance(getattr(srv, "_crash_error", None), OSError):
                    return "storage"  # serve loop died on a disk write
                return "crashed"
            if state in ("ready", "degraded"):
                with self._state_lock:
                    if self._role == "recovering":
                        self._role = "primary"
                ready_at = ready_at or now
            elif ready_at is None and srv._ready.is_set():
                # recovery finished but crashed/stopped settles next tick
                ready_at = now
            self._stop.wait(min(beat / 2.0, 0.05))
        return "stopping"

    # -- serving backend facade (what TransportServer dispatches into) -------

    def _primary(self) -> FitServer:
        with self._state_lock:
            srv, role = self._server, self._role
        if srv is None or role not in ("primary", "recovering"):
            holder = journal_mod.read_lease(self.root) or {}
            if not journal_mod.lease_is_live(self.root):
                # leaderless window: there is no "elsewhere" to redirect
                # to — typed read_only tells the client to retry LATER
                # (an election is in flight) while reads keep flowing
                raise transport_mod.ReadOnlyError(
                    f"replica {self.owner!r} is {role} and the fleet is "
                    "leaderless (election in flight); reads are served, "
                    "writes must wait",
                    retry_after_s=max(0.1, self.ttl_s / 2.0))
            raise NotLeaderError(
                f"replica {self.owner!r} is {role}; current lease holder "
                f"is {holder.get('owner')!r} (token {holder.get('token')})")
        return srv

    def _scratch_server(self) -> FitServer:
        """The standby's private compute root for READ-class requests
        (``<root>/standby_scratch/<owner>``): per-owner, never under the
        single-writer namespaces, so a scratch walk cannot collide with
        the primary's fenced writes.  Lazy — a standby that never serves
        a read never pays for it — and kept across promotions (a primary
        still answers polls for reads it computed as a standby)."""
        with self._scratch_lock:
            if self._scratch is None:
                kwargs = dict(self.server_kwargs)
                kwargs.pop("_commit_hook", None)  # fault hooks fence the
                # PRIMARY root; scratch walks are nobody's fencing domain
                srv = FitServer(
                    os.path.join(self.root, SCRATCH_DIR, self.owner),
                    **kwargs)
                srv.start(wait_ready=False)
                self._scratch = srv
            return self._scratch

    def submit(self, tenant, values, model="arima", **kwargs):
        return self._primary().submit(tenant, values, model, **kwargs)

    def submit_forecast(self, tenant, values, fitted, **kwargs):
        with self._state_lock:
            srv, role = self._server, self._role
        if srv is not None and role in ("primary", "recovering"):
            return srv.submit_forecast(tenant, values, fitted, **kwargs)
        if role in ("retired", "stopped"):
            # retired/stopped replicas serve nothing; the transport is
            # usually down too, but a racing in-flight call gets truth
            raise NotLeaderError(
                f"replica {self.owner!r} is {role}")
        # STANDBY READ: a forecast derives from the request's own params
        # with a content-derived interval seed, so any replica computes
        # it bitwise-identically — answer from the shared durable result
        # when one exists, else compute on the private scratch root
        req_id = kwargs.get("request_id")
        if req_id:
            path = os.path.join(self._results_dir, f"{req_id}.npz")
            if os.path.exists(path):
                try:
                    res = _load_result_file(path)
                except Exception as e:  # noqa: BLE001 - torn: downgrade
                    self._discard_torn(path, e)
                else:
                    with self._counters_lock:
                        self.counters["standby_reads"] += 1
                    obs.counter("fleet.standby_reads").inc()
                    ticket = FitTicket(req_id)
                    ticket._resolve(res)
                    return ticket
        with self._counters_lock:
            self.counters["standby_reads"] += 1
        obs.counter("fleet.standby_reads").inc()
        obs.event("fleet.standby_read", owner=self.owner,
                  req_id=req_id or "")
        return self._scratch_server().submit_forecast(tenant, values,
                                                      fitted, **kwargs)

    def request_pending(self, req_id: str) -> bool:
        with self._state_lock:
            srv = self._server
        if srv is not None and srv.request_pending(req_id):
            return True
        with self._scratch_lock:
            scratch = self._scratch
        if scratch is not None and scratch.request_pending(req_id):
            return True
        return os.path.exists(os.path.join(self._requests_dir,
                                           f"{req_id}.npz"))

    def _discard_torn(self, path: str, err: BaseException) -> None:
        """A stored result that fails to decode is TORN (a crashed or
        faulted writer): discard it so the id downgrades to
        recompute-or-redirect — a torn answer is never served."""
        with self._counters_lock:
            self.counters["torn_results"] += 1
        obs.counter("fleet.torn_results").inc()
        obs.event("fleet.torn_result", owner=self.owner,
                  file=os.path.basename(path), error=repr(err)[:200])
        try:
            os.remove(path)
        except OSError:
            pass

    def result_for(self, req_id: str) -> TenantFitResult:
        """Results are durable files: ANY replica answers a completed
        request's poll, so clients never wait out a lease TTL just to
        read an answer that already exists.  A torn file is discarded
        (the client's idempotent resubmit recomputes it); a scratch-
        computed standby read answers from the scratch server."""
        path = os.path.join(self._results_dir, f"{req_id}.npz")
        if os.path.exists(path):
            try:
                res = _load_result_file(path)
            except Exception as e:  # noqa: BLE001 - torn: downgrade
                self._discard_torn(path, e)
                raise KeyError(
                    f"stored result for {req_id!r} was torn and has been "
                    "discarded — resubmit (idempotent by id)") from e
            if self.role() != "primary":
                with self._counters_lock:
                    self.counters["standby_reads"] += 1
                obs.counter("fleet.standby_reads").inc()
                obs.event("fleet.standby_read", owner=self.owner,
                          req_id=req_id)
            return res
        with self._scratch_lock:
            scratch = self._scratch
        if scratch is not None:
            return scratch.result_for(req_id)
        raise KeyError(f"no stored result for request {req_id!r}")

    def health(self) -> dict:
        with self._state_lock:
            srv, role = self._server, self._role
            token = None if self._lease is None else self._lease.token
        with self._counters_lock:
            counters = dict(self.counters)
        state = self._publish_state()
        out = {
            "role": role,
            "state": state,
            "storage_degraded": state == "storage_degraded",
            "owner": self.owner,
            "lease_token": token,
            "fleet": counters,
            "lease": journal_mod.read_lease(self.root),
            "root": self.root,
        }
        if srv is not None and role in ("primary", "recovering"):
            out["server"] = srv.health()
        return out
