"""Lease-fenced FitServer replicas sharing one checkpoint root.

ISSUE 16's failover half.  The durable story under ``<root>`` (write-
ahead requests, batch journals, results) is a single-writer protocol, so
a fleet of N replicas must elect exactly one writer — and keep a
SIGKILLed writer's ZOMBIE (the same process restarted, or a stalled
thread waking up mid-write) from ever splicing bytes over its
successor's.  Both come from ``reliability.journal``'s lease records:

- a replica becomes **primary** by winning :func:`~..reliability.journal.
  acquire_lease` (an ``O_EXCL`` claim manifest allocates a strictly
  monotonic fencing token); it then constructs a :class:`~.server.
  FitServer` on the shared root — whose normal crash RECOVERY is what
  re-answers the dead peer's write-ahead requests, bitwise — and
  heartbeats the lease while serving.
- every durable write the primary performs is **fenced**: the journal
  commit hook and the result store both re-check the token first, so a
  stale holder dies with :class:`~..reliability.journal.FencedError`
  mid-write instead of corrupting the root (stale-token writers lose
  loudly).
- **standbys** poll the lease and serve the transport meanwhile:
  submits answer ``not_leader`` (the client rotates and retries), but
  result polls are answered FROM THE DURABLE FILES — a completed
  request's result is readable through any replica, which is what makes
  client polling survive the primary's death without waiting out the
  lease TTL.

Topology: every replica runs its own :class:`~.transport.TransportServer`
and advertises its endpoint under ``<root>/endpoints/`` so clients (and
the ci fleet smoke) can discover the fleet from the root alone.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..reliability import journal as journal_mod
from ..reliability.journal import FencedError
from . import transport as transport_mod
from .server import FitServer
from .session import TenantFitResult
from .transport import NotLeaderError, TransportServer

__all__ = [
    "FleetReplica",
    "advertise_endpoint",
    "discover_endpoints",
    "withdraw_endpoint",
]

ENDPOINTS_DIR = "endpoints"


# ---------------------------------------------------------------------------
# endpoint advertisement (fleet discovery from the root alone)
# ---------------------------------------------------------------------------


def advertise_endpoint(root: str, owner: str, host: str, port: int) -> None:
    """Durably advertise a replica's transport endpoint under the root
    (atomic: a discovering client never reads a torn advert)."""
    d = os.path.join(os.path.abspath(root), ENDPOINTS_DIR)
    os.makedirs(d, exist_ok=True)
    journal_mod._atomic_write_bytes(
        os.path.join(d, f"{owner}.json"),
        (json.dumps({"owner": str(owner), "host": str(host),
                     "port": int(port), "pid": os.getpid()},
                    sort_keys=True) + "\n").encode())


def withdraw_endpoint(root: str, owner: str) -> None:
    try:
        os.remove(os.path.join(os.path.abspath(root), ENDPOINTS_DIR,
                               f"{owner}.json"))
    except OSError:
        pass


def discover_endpoints(root: str) -> List[Tuple[str, int]]:
    """Every advertised ``(host, port)`` under the root, owner-sorted.
    Stale adverts (a SIGKILLed replica never withdraws) are harmless:
    clients treat a refused connection as one more rotate-and-retry."""
    d = os.path.join(os.path.abspath(root), ENDPOINTS_DIR)
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, fn)) as f:
                rec = json.load(f)
            out.append((str(rec["host"]), int(rec["port"])))
        except (OSError, json.JSONDecodeError, KeyError, ValueError):
            continue
    return out


def _load_result_file(path: str) -> TenantFitResult:
    """A stored result, loaded WITHOUT a server instance (the standby
    poll path): same npz spelling as ``FitServer._store_result``."""
    with open(path, "rb") as f:
        return transport_mod.decode_result_blob(f.read())


# ---------------------------------------------------------------------------
# the fenced server: every durable write re-checks the token first
# ---------------------------------------------------------------------------


class _FencedFitServer(FitServer):
    """A FitServer whose durable writes are gated by a fleet lease.

    Two fences cover every byte the server lands on the shared root:
    the journal commit hook (checked at each durable chunk commit, so a
    zombie's batch walk dies mid-batch) and the result store (so a walk
    that finished before the fence flipped still cannot splice its
    result file over the new primary's).  Both raise
    :class:`FencedError` — the crash path, not a degrade."""

    def __init__(self, root: str, lease: journal_mod.Lease, **kwargs):
        self._fleet_lease = lease
        user_hook = kwargs.pop("_commit_hook", None)

        def fenced_hook(event: str, lo: int) -> None:
            if event == "committed":
                lease.check()
            if user_hook is not None:
                user_hook(event, lo)

        super().__init__(root, _commit_hook=fenced_hook, **kwargs)

    def _store_result(self, req_id: str, res) -> None:
        self._fleet_lease.check()
        super()._store_result(req_id, res)


# ---------------------------------------------------------------------------
# the replica
# ---------------------------------------------------------------------------


class FleetReplica:
    """One member of a FitServer fleet on a shared checkpoint root.

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): the control
        thread elects/demotes while transport handler threads read the
        role and delegate to the leased server, and ``stop()`` may come
        from any thread — the role/lease/server triple and the counters
        mutate only under their locks.

    Lifecycle: ``start()`` brings up the transport (standbys answer),
    advertises the endpoint, and runs the control thread — a loop of
    ``acquire_lease`` → serve-as-primary (heartbeating every ``ttl/3``)
    → demote on crash/fence/stop.  ``server_kwargs`` configure the
    FitServer a primary constructs (fault hooks ride ``_commit_hook``
    exactly as on a standalone server).  ``retire_on_crash=True`` keeps
    a crashed replica down instead of re-electing it — what the
    deterministic failover tests use to pin WHO takes over.
    """

    _protected_by_ = {
        "_server": "_state_lock",
        "_lease": "_state_lock",
        "_role": "_state_lock",
        "counters": "_counters_lock",
    }

    def __init__(self, root: str, *,
                 owner: Optional[str] = None,
                 ttl_s: float = 5.0,
                 host: str = "127.0.0.1",
                 port: int = 0,
                 standby_poll_s: Optional[float] = None,
                 server_kwargs: Optional[dict] = None,
                 retire_on_crash: bool = False,
                 server_ready_timeout_s: float = 300.0):
        self.root = os.path.abspath(root)
        self.owner = owner or f"replica-{uuid.uuid4().hex[:8]}"
        self.ttl_s = float(ttl_s)
        self.standby_poll_s = (self.ttl_s / 4.0 if standby_poll_s is None
                               else float(standby_poll_s))
        self.server_kwargs = dict(server_kwargs or {})
        self.retire_on_crash = bool(retire_on_crash)
        self.server_ready_timeout_s = float(server_ready_timeout_s)
        self._requests_dir = os.path.join(self.root, "requests")
        self._results_dir = os.path.join(self.root, "results")
        self._transport = TransportServer(self, host=host, port=port)
        self._state_lock = threading.Lock()
        self._server: Optional[FitServer] = None
        self._lease: Optional[journal_mod.Lease] = None
        self._role = "standby"
        self.counters: Dict[str, int] = {
            "elections": 0, "fenced_demotions": 0, "crash_demotions": 0,
            "heartbeats": 0,
        }
        self._counters_lock = threading.Lock()
        self._stop = threading.Event()
        self._control: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetReplica":
        if self._control is not None:
            raise RuntimeError("FleetReplica.start() called twice")
        self._transport.start()
        host, port = self._transport.address
        advertise_endpoint(self.root, self.owner, host, port)
        self._control = threading.Thread(
            target=self._control_loop, daemon=True,
            name=f"fleet-control-{self.owner}")
        self._control.start()
        return self

    def stop(self, timeout_s: float = 300.0) -> None:
        self._stop.set()
        t = self._control
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        self._transport.stop()
        withdraw_endpoint(self.root, self.owner)

    def __enter__(self) -> "FleetReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def address(self) -> Tuple[str, int]:
        return self._transport.address

    def role(self) -> str:
        with self._state_lock:
            return self._role

    def lease_token(self) -> Optional[int]:
        with self._state_lock:
            return None if self._lease is None else self._lease.token

    def wait_role(self, role: str, timeout_s: float = 60.0) -> bool:
        """Poll until this replica reports ``role`` (tests/orchestration)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if self.role() == role:
                return True
            time.sleep(0.02)
        return self.role() == role

    # -- the control loop (election / heartbeat / demotion) ------------------

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            lease = journal_mod.acquire_lease(self.root, self.owner,
                                              ttl_s=self.ttl_s)
            if lease is None:
                self._stop.wait(self.standby_poll_s)
                continue
            srv = _FencedFitServer(self.root, lease, **self.server_kwargs)
            with self._counters_lock:
                self.counters["elections"] += 1
            obs.event("fleet.elected", owner=self.owner, token=lease.token)
            try:
                srv.start(wait_ready=False)
            except RuntimeError:
                pass  # start() raced stop(); the loop below settles it
            with self._state_lock:
                self._lease = lease
                self._server = srv
                self._role = "recovering"
            outcome = self._serve_as_primary(srv, lease)
            # demotion: tear the server down first, then settle the lease
            try:
                srv.stop(drain=(outcome == "stopping"))
            except Exception:  # noqa: BLE001 - demotion must complete
                pass
            try:
                lease.release()
            except FencedError:
                pass  # the successor already owns the root
            with self._state_lock:
                self._lease = None
                self._server = None
                self._role = "standby"
            if outcome == "fenced":
                with self._counters_lock:
                    self.counters["fenced_demotions"] += 1
                obs.event("fleet.fenced", owner=self.owner,
                          token=lease.token)
            elif outcome == "crashed":
                with self._counters_lock:
                    self.counters["crash_demotions"] += 1
                if self.retire_on_crash:
                    with self._state_lock:
                        self._role = "retired"
                    return
        with self._state_lock:
            if self._role != "retired":
                self._role = "stopped"

    def _serve_as_primary(self, srv: FitServer,
                          lease: journal_mod.Lease) -> str:
        """Heartbeat until stop/crash/fence; returns the demotion cause.
        The heartbeat runs DURING recovery too — a takeover whose replay
        outlives the ttl must not lose the lease it is replaying under."""
        beat = max(0.01, self.ttl_s / 3.0)
        last = 0.0
        ready_at: Optional[float] = None
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last >= beat:
                try:
                    lease.heartbeat()
                except FencedError:
                    return "fenced"
                last = now
                with self._counters_lock:
                    self.counters["heartbeats"] += 1
            state = srv.state()
            if state == "crashed":
                return "crashed"
            if state in ("ready", "degraded"):
                with self._state_lock:
                    if self._role == "recovering":
                        self._role = "primary"
                ready_at = ready_at or now
            elif ready_at is None and srv._ready.is_set():
                # recovery finished but crashed/stopped settles next tick
                ready_at = now
            self._stop.wait(min(beat / 2.0, 0.05))
        return "stopping"

    # -- serving backend facade (what TransportServer dispatches into) -------

    def _primary(self) -> FitServer:
        with self._state_lock:
            srv, role = self._server, self._role
        if srv is None or role not in ("primary", "recovering"):
            holder = journal_mod.read_lease(self.root) or {}
            raise NotLeaderError(
                f"replica {self.owner!r} is {role}; current lease holder "
                f"is {holder.get('owner')!r} (token {holder.get('token')})")
        return srv

    def submit(self, tenant, values, model="arima", **kwargs):
        return self._primary().submit(tenant, values, model, **kwargs)

    def submit_forecast(self, tenant, values, fitted, **kwargs):
        return self._primary().submit_forecast(tenant, values, fitted,
                                               **kwargs)

    def request_pending(self, req_id: str) -> bool:
        with self._state_lock:
            srv = self._server
        if srv is not None:
            return srv.request_pending(req_id)
        return os.path.exists(os.path.join(self._requests_dir,
                                           f"{req_id}.npz"))

    def result_for(self, req_id: str) -> TenantFitResult:
        """Results are durable files: ANY replica answers a completed
        request's poll, so clients never wait out a lease TTL just to
        read an answer that already exists."""
        path = os.path.join(self._results_dir, f"{req_id}.npz")
        if not os.path.exists(path):
            raise KeyError(f"no stored result for request {req_id!r}")
        return _load_result_file(path)

    def health(self) -> dict:
        with self._state_lock:
            srv, role = self._server, self._role
            token = None if self._lease is None else self._lease.token
        with self._counters_lock:
            counters = dict(self.counters)
        out = {
            "role": role,
            "owner": self.owner,
            "lease_token": token,
            "fleet": counters,
            "lease": journal_mod.read_lease(self.root),
            "root": self.root,
        }
        if srv is not None and role in ("primary", "recovering"):
            out["server"] = srv.health()
        return out
