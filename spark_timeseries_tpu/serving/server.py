"""The resident fit server: a long-lived serving loop over the chunk driver.

ROADMAP item 1 (ISSUE 12): every caller surface before this PR was
one-shot — build a plan, walk it, exit — but a production service holds
state BETWEEN requests.  :class:`FitServer` is that state:

- **admission** (:mod:`.admission`): caller threads ``submit()`` tenant
  panels; a bounded queue + per-tenant quotas keep memory finite, and
  overload sheds lowest-priority work with explicit
  :class:`~.session.RejectedError` (retry-after backpressure) — never an
  OOM, never an unbounded queue.
- **micro-batching** (:mod:`.batcher`): compatible requests coalesce into
  ONE chunked walk (tenants packed on the row axis the way PR 9 packed
  candidate orders), demuxed per tenant afterwards — bitwise-identical to
  fitting each tenant alone.
- **deadlines**: a request's ``deadline_s`` bounds its wall clock —
  expired-in-queue requests answer all-TIMEOUT rows immediately, and a
  dispatched batch runs under ``job_budget_s`` = the earliest member
  deadline, riding the chunk driver's watchdog (TIMEOUT rows, never a
  hang).
- **graceful degradation**: a batch walk that raises quarantines only
  that batch — its members re-run SOLO so one poisoned tenant panel
  cannot take down its co-batched neighbors (the serving rung of the
  PR 10 quarantine ladder; sharded walks additionally quarantine failing
  LANES inside the walk) — and the server keeps serving.
- **crash recovery**: requests are durable at admission (write-ahead npz
  under ``<root>/requests/``), batch membership is durable before each
  walk (``<root>/batches/<id>/members.json``), and every batch walk
  journals under its batch directory.  A SIGKILLed server restarted on
  the same root re-forms the in-flight batches from their membership
  records, RESUMES their journals (replaying only uncommitted chunks —
  results bitwise-identical to an uninterrupted run), re-answers
  completed requests from ``<root>/results/``, and re-enqueues the rest.
- **warmth**: ONE process-level staging-pool family
  (``reliability.source.StagingPool``) is shared across every request's
  walk, and the per-program compile cache
  (``utils.compile_cache.program_cache_stats``) spans requests — repeat
  fits of a shape skip straight to execute, and both hit rates are
  exposed (and asserted to climb in the tests).
- **observability**: health/readiness state (``health()``), obs-plane
  gauges/counters, and a streaming Prometheus-textfile sink
  (``obs.promsink``) rewritten after every batch so the server is
  scrapeable MID-run.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Union

import numpy as np

from .. import obs
from ..reliability import fit_chunked
from ..reliability import source as source_mod
from ..reliability import watchdog as watchdog_mod
from ..reliability.faultinject import SimulatedCrash
from ..reliability.status import FitStatus
from ..utils import compile_cache
from . import batcher
from .admission import AdmissionQueue, TenantQuota
from ..reliability.journal import consult_disk_fault, tear_after_replace
from .session import (CancelledError, FitRequest, FitTicket, RejectedError,
                      ServerClosedError, StorageError, TenantFitResult)

__all__ = ["AUTO_MODEL", "FORECAST_MODEL", "FitServer"]

# registry name of the chunked forecast walk's fit function — forecast
# requests reference it BY NAME so they survive restarts like model fits
FORECAST_MODEL = "panel_forecast"

# registry name of the auto order-search workload (ISSUE 19): requests
# run models.auto.auto_fit per tenant instead of a micro-batched single-
# order walk, warm-routed through the tenant's durable profile — see
# _run_auto_request
AUTO_MODEL = "panel_auto"

# fit_kwargs of an AUTO request that only steer the fit itself (ride to
# auto_fit / the warm refit); everything routes through config_key so a
# changed knob re-searches instead of trusting a stale profile
_AUTO_FIT_KNOBS = ("max_iters", "tol", "backend", "method")


def _align_mode_host(values: np.ndarray) -> str:
    """The panel's static align mode, probed host-side at admission (the
    same vocabulary as ``models.base.align_mode_on_host``).  Part of the
    batch key: same-mode panels concatenate to the same mode, so a
    micro-batched walk runs the exact program each solo walk would."""
    nan_last = bool(np.isnan(values[:, -1]).any())
    if nan_last:
        return "general"
    return "no-trailing" if bool(np.isnan(values).any()) else "dense"


def _load_online_advisor() -> Optional[Callable]:
    """``tools/advise_budget.py``'s knob inference, imported by file path
    (ISSUE 12: run ONLINE between batches instead of post-mortem).  The
    tools directory is a repo-checkout artifact, not a package — absence
    degrades to no adaptation, never to a serving failure."""
    try:
        import importlib.util
        import sys

        tools_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools")
        path = os.path.join(tools_dir, "advise_budget.py")
        if not os.path.exists(path):
            return None
        if tools_dir not in sys.path:  # advise_budget imports a sibling
            sys.path.append(tools_dir)
        spec = importlib.util.spec_from_file_location(
            "_ststpu_online_advise", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.advise
    except Exception:  # noqa: BLE001 - advisory only
        return None


def _profile_winner_specs(prof: dict) -> list:
    """Distinct winning ``(p, d, q)`` tuples recorded in a tenant profile
    (sorted — the drifted route's stepwise seed neighborhood)."""
    orders = np.asarray(prof["orders"], np.int64).reshape(-1, 3)
    idx = np.asarray(prof["order_index"], np.int64)
    seen = {tuple(int(v) for v in orders[g]) for g in idx if g >= 0}
    return sorted(seen)


def _auto_result(req: FitRequest, route: str, *, stability, orders,
                 order_index, criterion, params, nll, converged, iters,
                 status, criterion_name, include_intercept,
                 selection_counts, stepwise) -> TenantFitResult:
    """Assemble the AUTO_MODEL :class:`TenantFitResult` — one meta shape
    for all three route legs, so clients and the failover smoke compare
    results without caring which leg produced them."""
    from ..reliability.status import status_counts

    status = np.asarray(status, np.int8)
    meta = {
        "model": AUTO_MODEL,
        "req_id": req.req_id,
        "tenant": req.tenant,
        "status_counts": status_counts(status),
        "auto": {
            "route": str(route),
            "stability": int(stability),
            "orders": [[int(v) for v in o]
                       for o in np.asarray(orders).reshape(-1, 3)],
            "order_index": [int(v) for v in np.asarray(order_index)],
            "criterion": [float(v) for v in np.asarray(criterion, float)],
            "criterion_name": str(criterion_name),
            "include_intercept": bool(include_intercept),
            "selection_counts": dict(selection_counts),
        },
    }
    if stepwise is not None:
        meta["auto"]["stepwise"] = stepwise
    return TenantFitResult(
        params=np.asarray(params),
        neg_log_likelihood=np.asarray(nll),
        converged=np.asarray(converged, bool),
        iters=np.asarray(iters, np.int32),
        status=status,
        meta=meta)


class FitServer:
    """A long-lived in-process fit daemon (see module docstring).

    .. attribute:: _protected_by_

        Lock-discipline contract (tools/lint lock-map): caller threads
        submit/cancel while the serve loop batches, delivers, and
        recovers — the five shared maps/counters below mutate only
        under their declared locks.  Serve-loop-private state
        (``_batch_seq``, ``_prom_last``, ``_degraded_until``,
        ``_crash_error``) and caller-set flags (``_drain``) have a
        single writing role and stay undeclared.

    ``root`` is the server-owned checkpoint root — requests, batch
    journals, and results live under it, and a restarted server on the
    same root recovers everything in flight.  ``models`` extends the
    built-in model registry (name -> fit callable); requests reference
    models BY NAME so they stay durable/re-resolvable across restarts.

    Thread model: ``submit()`` is safe from any thread; ONE serve-loop
    thread forms and walks batches (the walk itself pipelines
    stage/compute/commit internally, and ``shard=True`` in
    ``walk_kwargs`` adds elastic mesh lanes).
    """

    _protected_by_ = {
        "counters": "_counters_lock",
        "_live": "_live_lock",
        "_seq": "_seq_lock",
        "_pools": "_pools_lock",
        "_state": "_state_lock",
    }

    def __init__(self, root: str, *,
                 models: Optional[Dict[str, Callable]] = None,
                 batch_window_s: float = 0.01,
                 max_batch_rows: int = 4096,
                 max_queue_rows: int = 65_536,
                 max_queue_requests: int = 1024,
                 max_inflight_per_tenant: Optional[int] = None,
                 max_rows_per_tenant: Optional[int] = None,
                 max_rows_per_request: Optional[int] = None,
                 cell_rows: int = 256,
                 pipeline_depth: int = 2,
                 prefetch_depth: int = 1,
                 chunk_budget_s: Optional[float] = None,
                 default_deadline_s: Optional[float] = None,
                 resilient: bool = False,
                 policy: str = "impute",
                 warm_routing: bool = True,
                 autotune: bool = True,
                 prom_path: Optional[str] = None,
                 prom_interval_s: float = 2.0,
                 degraded_window_s: float = 5.0,
                 walk_kwargs: Optional[dict] = None,
                 compile_cache_dir: Optional[str] = None,
                 _commit_hook: Optional[Callable] = None):
        self.root = os.path.abspath(root)
        self._requests_dir = os.path.join(self.root, "requests")
        self._results_dir = os.path.join(self.root, "results")
        self._batches_dir = os.path.join(self.root, "batches")
        # per-request auto-search journals: <root>/auto/<req_id>/ — a
        # deterministic dir, so a recovered AUTO request resumes its
        # own stepwise/grid journals mid-walk
        self._auto_dir = os.path.join(self.root, "auto")
        for d in (self._requests_dir, self._results_dir, self._batches_dir):
            os.makedirs(d, exist_ok=True)
        from .profiles import TenantProfileStore

        # tenant profiles on the (possibly fleet-shared) root; the fleet's
        # fenced server subclass points .fence at its lease check
        self.profiles = TenantProfileStore(
            os.path.join(self.root, "profiles"))
        self._models = dict(models or {})
        self.batch_window_s = float(batch_window_s)
        self.max_batch_rows = int(max_batch_rows)
        self.chunk_budget_s = chunk_budget_s
        self.default_deadline_s = default_deadline_s
        self.resilient = bool(resilient)
        self.policy = str(policy)
        self.warm_routing = bool(warm_routing)
        self.autotune = bool(autotune)
        self.degraded_window_s = float(degraded_window_s)
        self.walk_kwargs = dict(walk_kwargs or {})
        self._commit_hook = _commit_hook
        self.queue = AdmissionQueue(max_queue_rows=max_queue_rows,
                                    max_queue_requests=max_queue_requests)
        self.quota = TenantQuota(
            max_inflight_per_tenant=max_inflight_per_tenant,
            max_rows_per_tenant=max_rows_per_tenant,
            max_rows_per_request=max_rows_per_request)
        # adaptive walk knobs: seeded from config, then advise_budget's
        # inference updates them ONLINE after each journaled batch; a
        # restart reloads the last adaptation so warmup is not re-paid.
        # cell_rows is both the batcher's padding quantum and the batch
        # walk's chunk size — one request per chunk cell is what keeps
        # micro-batched results bitwise-identical to solo fits.
        self._knobs = {"cell_rows": max(1, min(int(cell_rows),
                                               self.max_batch_rows)),
                       "pipeline_depth": int(pipeline_depth),
                       "prefetch_depth": int(prefetch_depth)}
        self._knobs_path = os.path.join(self.root, "knobs.json")
        if self.autotune and os.path.exists(self._knobs_path):
            try:
                with open(self._knobs_path) as f:
                    saved = json.load(f)
                self._knobs.update({k: saved[k] for k in self._knobs
                                    if saved.get(k) is not None})
            except (OSError, json.JSONDecodeError, KeyError):
                pass
        self._advise = _load_online_advisor() if self.autotune else None
        # ONE process-level staging-pool family shared across requests
        # (keyed by panel geometry — a pool's buffers are [*, T] dtype)
        self._pools: Dict[tuple, source_mod.StagingPool] = {}
        self._pools_lock = threading.Lock()
        if compile_cache_dir:
            compile_cache.enable_compile_cache(compile_cache_dir)
        # prom sink (obs.promsink): rewritten after every batch + idle tick
        self._prom = None
        self._prom_interval_s = float(prom_interval_s)
        self._prom_last = 0.0
        if prom_path:
            self._prom = obs.PromTextfileSink(prom_path)
        self._state = "starting"
        self._state_lock = threading.Lock()
        self._degraded_until = 0.0
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self._crash_error: Optional[BaseException] = None
        self._seq_lock = threading.Lock()
        self._seq = self._next_seq_floor()
        self._batch_seq = 0
        self._live: Dict[str, FitRequest] = {}  # req_id -> admitted request
        self._live_lock = threading.Lock()
        self.counters = {
            "admitted": 0, "completed": 0, "rejected": 0, "shed": 0,
            "cancelled": 0, "timeout_requests": 0, "deadline_expired": 0,
            "batches_run": 0, "batch_failures": 0, "solo_retries": 0,
            "rows_fitted": 0, "recovered_requests": 0,
            "recovered_batches": 0, "autotune_updates": 0,
            "storage_errors": 0, "torn_results": 0,
            "auto_requests": 0, "route_stable": 0, "route_drifted": 0,
            "route_new": 0, "route_cold": 0, "profile_updates": 0,
        }
        self._counters_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_ready: bool = True,
              timeout_s: float = 300.0) -> "FitServer":
        """Start the serve loop (recovery first, then steady state).
        ``wait_ready=True`` blocks until recovery finished and the server
        reports ready."""
        if self._thread is not None:
            raise RuntimeError("FitServer.start() called twice")
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="fit-server")
        self._thread.start()
        if wait_ready and not self._ready.wait(timeout=timeout_s):
            raise TimeoutError("FitServer recovery did not finish in "
                               f"{timeout_s}s")
        if self._crash_error is not None:
            raise ServerClosedError(
                f"server crashed during startup: {self._crash_error!r}")
        return self

    def stop(self, drain: bool = True, timeout_s: float = 300.0) -> None:
        """Stop serving.  ``drain=True`` answers everything already
        queued first; ``drain=False`` abandons the queue (requests stay
        durable for the next start on this root)."""
        self._drain = drain
        self._set_state("draining" if drain else "stopping")
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        # ALWAYS close the queue, drained or not: a submit() racing the
        # state check can land an offer after the serve loop exits, and
        # an enqueued-but-never-served ticket would hang its caller —
        # reject it explicitly (the durable request record survives for
        # the next start on this root)
        for req in self.queue.close():
            req.ticket._reject(ServerClosedError(
                "server stopped before serving this request; it is "
                "durable — restart the server on the same root"))
        self._set_state("stopped")
        self._write_server_state()
        self._write_prom(force=True)

    def __enter__(self) -> "FitServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission (caller threads) ------------------------------------------

    def submit(self, tenant: str, values, model: Union[str, Callable] = "arima",
               *, priority: int = 0, deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               warm_routing: Optional[bool] = None,
               **fit_kwargs) -> FitTicket:
        """Admit one tenant panel fit; returns a :class:`FitTicket`.

        ``values`` is a host ``[rows, T]`` array (copied to the durable
        request record).  ``model`` must be a registry NAME (built-in
        model module or a name passed via ``models=`` at construction) so
        the request survives a restart.  ``deadline_s`` bounds the
        request's wall clock from NOW (default: the server's
        ``default_deadline_s``); ``priority`` (higher = keep longer under
        overload) drives shedding.  ``request_id`` makes the submit
        idempotent: re-submitting a completed id returns its stored
        result instantly.

        ``model="panel_auto"`` runs a per-tenant order SEARCH
        (``models.auto.auto_fit``) instead of a micro-batched
        single-order walk: remaining ``fit_kwargs`` ride to ``auto_fit``
        (``orders``, ``stepwise``, ``criterion``, ...), and
        ``warm_routing`` selects the routing mode — ``True`` classifies
        the panel against the tenant's durable profile (stable submits
        skip stage 1 entirely), ``False`` is EXACT mode (bitwise the
        plain exhaustive search, no profile reads), ``None`` (default)
        uses the server's ``warm_routing`` setting.  The knob rides the
        durable request record, so recovery re-routes identically.

        Raises :class:`RejectedError` (queue full / quota — carries
        ``retry_after_s``) or :class:`ServerClosedError`.
        """
        if self._state in ("draining", "stopping", "stopped", "crashed"):
            raise ServerClosedError(f"server is {self._state}")
        if warm_routing is not None:
            if model != AUTO_MODEL:
                raise ValueError(
                    "warm_routing only applies to model="
                    f"{AUTO_MODEL!r} submits, got model={model!r}")
            fit_kwargs["warm_routing"] = bool(warm_routing)
        if callable(model):
            name = next((k for k, v in self._models.items() if v is model),
                        None)
            if name is None:
                raise TypeError(
                    "model callables must be registered by name "
                    "(FitServer(models={'name': fn})) so requests stay "
                    "durable across restarts")
            model = name
        self._resolve_model(model)  # unknown model fails at the door
        arr = np.ascontiguousarray(np.asarray(values))
        if arr.ndim != 2 or arr.shape[0] < 1 or arr.shape[1] < 1:
            raise ValueError(f"expected a non-empty [rows, T] panel, "
                             f"got {arr.shape}")
        if request_id is not None:
            prior = self._try_stored(request_id)
            if prior is not None:
                return prior
            with self._live_lock:
                dup = request_id in self._live
            if dup:
                self._count_rejected()
                raise RejectedError(
                    f"request {request_id!r} is already in flight; poll "
                    "its ticket or result_for()", retry_after_s=0.5)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        try:
            self.quota.try_acquire(tenant, arr.shape[0])
        except RejectedError:
            self._count_rejected()
            raise
        try:
            with self._seq_lock:
                self._seq += 1
                seq = self._seq
            req_id = request_id or f"r{seq:08d}-{uuid.uuid4().hex[:8]}"
            req = FitRequest(
                req_id, seq, tenant, arr, model, fit_kwargs,
                priority=priority, deadline_s=deadline_s,
                align_mode=_align_mode_host(arr),
                resilient=self.resilient, policy=self.policy)
            req.ticket._canceller = self._cancel
            # write-ahead: the request is durable BEFORE the caller holds
            # a ticket for it — a crash after this line re-answers it.
            # A disk that refuses the record (EIO/ENOSPC) refuses the
            # ADMISSION: an un-journaled acceptance would be silently
            # lost by the next crash, so the typed StorageError (a
            # RejectedError: the handlers below refund quota and count
            # it) tells the client to retry on a replica whose disk works
            try:
                req.save(self._request_path(req_id))
            except OSError as e:
                with self._counters_lock:
                    self.counters["storage_errors"] += 1
                obs.counter("server.storage_errors").inc()
                obs.event("server.storage_refusal", req_id=req_id,
                          error=repr(e)[:200])
                raise StorageError(
                    f"write-ahead record refused: {e}") from e
            # live BEFORE the queue sees it: the moment offer() returns,
            # the serve loop (or a shedding offer on another thread) may
            # complete the request and call _forget — registering after
            # the fact would leak a stale entry (and its panel) forever
            with self._live_lock:
                self._live[req.req_id] = req
            try:
                self.queue.offer(req, on_shed=self._on_shed)
            except RejectedError:
                with self._live_lock:
                    self._live.pop(req.req_id, None)
                self._remove_request_file(req_id)
                raise
        except RejectedError:
            self.quota.release(tenant, arr.shape[0])
            self._count_rejected()
            raise
        with self._counters_lock:
            self.counters["admitted"] += 1
        obs.counter("server.admitted").inc()
        # the server-side hop of the request's causal timeline: a
        # transport dispatch establishes the trace scope, so a traced
        # admission is stamped with the fleet-wide trace id (a resubmit
        # after failover emits this again on the survivor — expected:
        # the timeline shows BOTH admissions, one terminal)
        obs.event("server.admit", req_id=req.req_id, tenant=str(tenant),
                  seq=seq)
        return req.ticket

    def submit_forecast(self, tenant: str, values, fitted, *,
                        model: str = "arima",
                        horizon: int = 1,
                        model_kwargs: Optional[dict] = None,
                        status=None,
                        intervals: bool = False, level: float = 0.9,
                        n_samples: int = 256,
                        seed: Optional[int] = None,
                        priority: int = 0,
                        deadline_s: Optional[float] = None,
                        request_id: Optional[str] = None) -> FitTicket:
        """Admit one tenant panel FORECAST (fit-once / forecast-many: the
        serving half users actually call).

        ``values`` is the tenant's ``[rows, T]`` history and ``fitted``
        its per-row params (a fit result, a raw ``[rows, k]`` array, or
        a journal path — ``forecasting.forecast_chunked`` semantics).
        The request rides the NORMAL admission/batching/durability
        machinery as a ``panel_forecast`` walk over the AUGMENTED panel
        (``forecasting.augment``): compatible forecast requests (same
        model/horizon/config/width) coalesce into ONE journaled chunk
        walk on the cell grid and demux bitwise-identically to solo
        submits; the write-ahead request record carries the augmented
        panel, so a SIGKILLed server re-answers forecasts bitwise like
        fits.  Interval keys are counter-based per request-local row
        with a base seed derived from the request's own content (or
        ``seed``), so batching composition cannot move a row's bands.

        The result's ``params`` is the packed ``[point | lo | hi]``
        forecast block — unpack with ``forecasting.as_result(res,
        horizon, intervals)``.
        """
        from .. import forecasting as _forecasting
        from ..forecasting import kernels as _fkernels
        from ..reliability import journal as _journal

        if int(horizon) < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        mk = _fkernels.normalize_model_kwargs(model, model_kwargs or {})
        cfg = dict(mk)
        k = _fkernels.param_width(model, cfg)
        if isinstance(fitted, str):
            fitted = _forecasting.load_fit_result(fitted)
        if hasattr(fitted, "order_index"):
            raise ValueError(
                "an auto-fit selection mixes parameter layouts per row; "
                "forecast it with forecasting.ensemble_forecast("
                "auto_root=..., temperature=0), not a single-order "
                "forecast request")
        if hasattr(fitted, "params"):
            params = np.asarray(fitted.params)
            if status is None:
                status = getattr(fitted, "status", None)
        else:
            params = np.asarray(fitted)
        if params.ndim != 2 or params.shape[1] < k:
            raise ValueError(
                f"model {model!r} needs [rows, >={k}] params, got "
                f"{params.shape}")
        params = np.ascontiguousarray(params[:, :k])
        arr = np.ascontiguousarray(np.asarray(values))
        if arr.ndim != 2 or arr.shape[0] != params.shape[0]:
            raise ValueError(
                f"values {arr.shape} and params {params.shape} disagree "
                "on rows")
        st = _forecasting.augment.derive_status(params, status)
        aug = _forecasting.augment.augmented_host(arr, params, st)
        base_seed = 0
        if intervals:
            base_seed = (int(seed) if seed is not None
                         else _forecasting.walk._derive_base_seed(
                             _journal.panel_fingerprint(aug)))
        return self.submit(
            tenant, aug, FORECAST_MODEL,
            priority=priority, deadline_s=deadline_s,
            request_id=request_id,
            forecast_model=model, horizon=int(horizon),
            n_time=int(arr.shape[1]), k=int(k),
            model_kwargs={key: (list(v) if isinstance(v, tuple) else v)
                          for key, v in cfg.items()},
            intervals=bool(intervals), level=float(level),
            n_samples=int(n_samples), base_seed=int(base_seed))

    def _count_rejected(self) -> None:
        """Every refusal — queue, quota, duplicate — is load evidence:
        it must show in the counters and flip the degraded signal, or a
        saturated server reads as healthy."""
        with self._counters_lock:
            self.counters["rejected"] += 1
        self._note_degraded()
        obs.counter("server.rejected").inc()

    def _cancel(self, req_id: str) -> bool:
        req = self.queue.cancel(req_id)
        if req is None:
            return False
        self._forget(req)
        self._remove_request_file(req_id)
        with self._counters_lock:
            self.counters["cancelled"] += 1
        obs.counter("server.cancelled").inc()
        return True

    def _on_shed(self, req: FitRequest) -> None:
        """Queue eviction callback: refund the quota and durable record."""
        self._forget(req)
        self._remove_request_file(req.req_id)
        with self._counters_lock:
            self.counters["shed"] += 1
        self._note_degraded()
        obs.counter("server.shed").inc()
        obs.event("server.shed", req_id=req.req_id, tenant=req.tenant,
                  priority=req.priority)

    def _try_stored(self, request_id: str) -> Optional[FitTicket]:
        path = os.path.join(self._results_dir, f"{request_id}.npz")
        if not os.path.exists(path):
            return None
        try:
            res = self._load_result(path)
        except Exception as e:  # noqa: BLE001 - torn bytes, not a bug
            # a torn stored result must never be SERVED; discard it and
            # fall through to a fresh admission (recompute)
            self._discard_torn_result(path, e)
            return None
        t = FitTicket(request_id)
        t._resolve(res)
        return t

    # -- results / durable paths ---------------------------------------------

    def _request_path(self, req_id: str) -> str:
        return os.path.join(self._requests_dir, f"{req_id}.npz")

    def _remove_request_file(self, req_id: str) -> None:
        try:
            os.remove(self._request_path(req_id))
        except OSError:
            pass

    def _store_result(self, req_id: str, res: TenantFitResult) -> None:
        path = os.path.join(self._results_dir, f"{req_id}.npz")
        # disk-fault seam: a refused result store (EIO/ENOSPC) raises
        # into the serve loop's crash path — the request record is still
        # durable, so a takeover/restart on a WORKING disk re-answers it
        verdict = consult_disk_fault(path, "result")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, params=res.params, nll=res.neg_log_likelihood,
                     converged=res.converged, iters=res.iters,
                     status=res.status,
                     meta=np.frombuffer(
                         json.dumps(res.meta, default=repr).encode(),
                         dtype=np.uint8))
        os.replace(tmp, path)
        if verdict == "torn":
            tear_after_replace(path)

    def _load_result(self, path: str) -> TenantFitResult:
        with np.load(path) as z:
            return TenantFitResult(
                params=np.array(z["params"]),
                neg_log_likelihood=np.array(z["nll"]),
                converged=np.array(z["converged"]),
                iters=np.array(z["iters"]),
                status=np.array(z["status"]),
                meta=json.loads(bytes(z["meta"].tobytes()).decode()))

    def _discard_torn_result(self, path: str, err: BaseException) -> None:
        """A stored result whose bytes do not parse (torn-at-fsync) is
        worse than no result: remove it so recovery/resubmission
        recomputes instead of any reader trusting half a file."""
        with self._counters_lock:
            self.counters["torn_results"] += 1
        obs.counter("server.torn_results").inc()
        obs.event("server.torn_result", path=os.path.basename(path),
                  error=repr(err)[:200])
        try:
            os.remove(path)
        except OSError:
            pass

    def result_for(self, req_id: str) -> TenantFitResult:
        """Load a completed request's stored result — how a client
        re-attaches after a server restart re-answered its request.
        A torn stored file downgrades to ``KeyError`` (recompute /
        resubmit), never to serving corrupt bytes."""
        path = os.path.join(self._results_dir, f"{req_id}.npz")
        if not os.path.exists(path):
            raise KeyError(f"no stored result for request {req_id!r}")
        try:
            return self._load_result(path)
        except KeyError:
            raise
        except Exception as e:  # noqa: BLE001 - torn bytes, not a bug
            self._discard_torn_result(path, e)
            raise KeyError(
                f"stored result for {req_id!r} was torn and has been "
                "discarded — resubmit (idempotent by request id)") from None

    def request_pending(self, req_id: str) -> bool:
        """Whether ``req_id`` is admitted and still in flight (live in
        this instance, or durable under ``requests/`` awaiting recovery)
        — the transport layer's idempotent-resubmit probe (ISSUE 16): a
        pending id is acked, not re-admitted."""
        with self._live_lock:
            if req_id in self._live:
                return True
        return os.path.exists(self._request_path(req_id))

    # -- the serve loop ------------------------------------------------------

    def _serve(self) -> None:
        try:
            self._recover()
            self._set_state("ready")
            self._ready.set()
            while True:
                if self._stop.is_set() and not self._drain:
                    break
                cell = self._knobs["cell_rows"]
                members = self.queue.take_batch(
                    batcher.batch_key, self.max_batch_rows,
                    window_s=self.batch_window_s, timeout_s=0.25,
                    # the PADDED size is what the walk stages and fits:
                    # max_batch_rows must bound the packed panel, not
                    # just the payload
                    rows_fn=lambda r: -(-r.rows // cell) * cell)
                if not members:
                    if self._stop.is_set():
                        break  # drained
                    self._idle_tick()
                    continue
                self._run_members(members)
        except BaseException as e:  # noqa: BLE001 - crash path below
            self._crash_error = e
            self._set_state("crashed")
            self._ready.set()
            # pending tickets must not hang forever on a dead loop: the
            # durable state re-answers them on the next start
            with self._live_lock:
                live = list(self._live.values())
            for req in live:
                req.ticket._reject(ServerClosedError(
                    f"server crashed ({type(e).__name__}); the request is "
                    "durable — restart the server on this root to "
                    "re-answer it"))
            if not isinstance(e, (SimulatedCrash, KeyboardInterrupt)):
                obs.event("server.crash", error=repr(e)[:300])
                raise

    def _run_members(self, members) -> None:
        # deadline triage: a request that expired while queued answers
        # all-TIMEOUT rows NOW — it never costs a dispatch
        ready = []
        for req in members:
            if req.ticket.done():  # cancelled while the batch formed
                self._forget(req)
                continue
            if req.expired():
                self._finalize(req, batcher.timeout_result(
                    req, "deadline expired while queued"))
                with self._counters_lock:
                    self.counters["deadline_expired"] += 1
                obs.counter("server.deadline_expired").inc()
                continue
            ready.append(req)
        if not ready:
            return
        if ready[0].model == AUTO_MODEL:
            # AUTO requests never micro-batch: each is a whole SEARCH
            # (per-tenant result layouts differ by winning order), run
            # solo under its own deterministic journal dir — the durable
            # request record plus journal resume is its crash recovery,
            # no batch membership record needed (batch_key groups only
            # same-model requests, so a mixed `ready` cannot occur)
            for req in ready:
                self._run_auto_request(req)
            return
        self._batch_seq += 1
        knobs = dict(self._knobs)
        batch = batcher.pack(ready, self._batch_seq,
                             cell_rows=knobs["cell_rows"])
        batch.save_members(self.root, knobs)
        t0 = time.perf_counter()
        try:
            res = self._execute_batch(batch, knobs)
        except Exception as e:  # noqa: BLE001 - batch quarantine below
            self._quarantine_batch(batch, e)
            return
        wall = time.perf_counter() - t0
        self._deliver(batch, res)
        self.queue.record_drain(batch.rows, wall)
        self._after_batch(batch, wall)

    def _execute_batch(self, batch: "batcher.MicroBatch", knobs: dict):
        fit_fn = self._resolve_model(batch.members[0].model)
        head = batch.members[0]
        from ..reliability.runner import _accepted_kwargs

        # the explicit align hint is what makes batched == solo bitwise
        # (same compiled program family either way); a registry fit that
        # does not take the hint simply runs its own per-chunk plan
        align = (head.align_mode
                 if "align_mode" in _accepted_kwargs(
                     fit_fn, {"align_mode": None}) else None)
        src = source_mod.HostChunkSource(
            batch.values, pool=self._pool_for(batch.values.shape[1],
                                              batch.values.dtype))
        ckpt = os.path.join(batch.dir(self.root), "journal")
        job_budget = batch.job_budget_s()
        # forecast walks NEVER run the resilient ladder: the augmented
        # panel's extra columns are fitted parameters, and the sanitizer
        # "repairing" them would corrupt the forecast inputs (the walk's
        # own status propagation is the forecast-side resilience)
        resilient = head.resilient and head.model != FORECAST_MODEL
        # the batch walk gets its OWN trace keyed on the content-derived
        # batch_id (recovery re-forms the identical batch on a survivor,
        # so the post-failover walk CONTINUES the same batch trace); the
        # join back to each member request's trace is the
        # server.batch_member event below, stamped per-request with the
        # batch_id attr — obs_report --trace follows that link
        for req in batch.members:
            with obs.trace_scope(
                    obs.trace_for_request(req.req_id, "server")):
                obs.event("server.batch_member", req_id=req.req_id,
                          batch_id=batch.batch_id, tenant=req.tenant)
        bctx = obs.trace_for_request(batch.batch_id, "server.batch")
        with watchdog_mod.request_context(batch.tenants), \
                obs.trace_scope(bctx):
            with obs.span("server.batch", batch_id=batch.batch_id,
                          members=len(batch.members), rows=batch.rows):
                return fit_chunked(
                    fit_fn, src,
                    chunk_rows=batch.cell_rows,
                    resilient=resilient,
                    policy=head.policy,
                    checkpoint_dir=ckpt,
                    chunk_budget_s=self.chunk_budget_s,
                    job_budget_s=job_budget,
                    pipeline_depth=int(knobs.get("pipeline_depth") or 2),
                    prefetch_depth=int(knobs.get("prefetch_depth") or 1),
                    align_mode=align,
                    _journal_commit_hook=self._commit_hook,
                    **{**self.walk_kwargs, **head.fit_kwargs})

    def _deliver(self, batch: "batcher.MicroBatch", res) -> None:
        # counters BEFORE tickets resolve: a caller that reads health()
        # the moment its result() unblocks must see this batch counted
        with self._counters_lock:
            self.counters["batches_run"] += 1
            self.counters["rows_fitted"] += batch.rows
        obs.counter("server.batches").inc()
        obs.counter("server.rows_fitted").add(batch.rows)
        obs.histogram("server.batch_members").observe(len(batch.members))
        for req, tres in zip(batch.members, batch.demux(res)):
            self._finalize(req, tres)
        batch.mark_complete(self.root)

    def _quarantine_batch(self, batch: "batcher.MicroBatch",
                          error: Exception) -> None:
        """A failed batch walk takes down ONLY this batch: members re-run
        solo so a poisoned tenant panel is isolated to its own request
        (the serving rung of the PR 10 quarantine ladder); a solo failure
        lands on that request's ticket alone.  The server keeps serving
        either way."""
        with self._counters_lock:
            self.counters["batch_failures"] += 1
        self._note_degraded()
        obs.counter("server.batch_failures").inc()
        obs.event("server.batch_quarantined", batch_id=batch.batch_id,
                  members=len(batch.members), error=repr(error)[:200])
        if len(batch.members) == 1:
            req = batch.members[0]
            self._forget(req)
            req.ticket._reject(error)
            return
        for req in batch.members:
            if req.ticket.done():
                self._forget(req)
                continue
            with self._counters_lock:
                self.counters["solo_retries"] += 1
            self._batch_seq += 1
            knobs = dict(self._knobs)
            solo = batcher.pack([req], self._batch_seq,
                                cell_rows=knobs["cell_rows"])
            solo.save_members(self.root, knobs)
            try:
                res = self._execute_batch(solo, knobs)
            except Exception as e:  # noqa: BLE001 - per-request terminal
                self._forget(req)
                req.ticket._reject(e)
                continue
            self._deliver(solo, res)

    # -- the auto order search (ISSUE 19) ------------------------------------

    def _run_auto_request(self, req: FitRequest) -> None:
        """One tenant's auto-fit search, warm-routed through its durable
        profile.

        The ladder: **cold** (``warm_routing=False`` — exact mode, the
        plain search with no profile reads, bitwise today's behavior),
        **stable** (fingerprint/config match — skip stage 1 entirely: a
        warm-started refit of each row's known winning order), **drifted**
        (content moved — stepwise expansion seeded from the profile's
        winners), **new** (full stepwise).  The decision lands on the
        request's trace (``server.route``) and in the result meta; the
        profile update after completion is FENCED on a fleet root, so a
        zombie primary dies loudly instead of clobbering warm state.
        """
        from ..reliability.journal import FencedError
        from . import profiles as profiles_mod

        fk = dict(req.fit_kwargs)
        warm = bool(fk.pop("warm_routing", self.warm_routing))
        cfg_key = profiles_mod.config_key(fk)
        route, prof = "cold", None
        if warm:
            route, prof = self.profiles.classify(req.tenant, req.values,
                                                 cfg_key)
        stability = int(prof.get("stability", 0)) if prof else 0
        with self._counters_lock:
            self.counters["auto_requests"] += 1
            self.counters[f"route_{route}"] += 1
        obs.counter(f"server.route_{route}").inc()
        t0 = time.perf_counter()
        try:
            with obs.trace_scope(
                    obs.trace_for_request(req.req_id, "server")):
                # the routing decision is a first-class hop on the
                # request's causal timeline — obs_report --trace renders
                # the attrs, and the fleet smoke asserts a takeover
                # continues warm from the dead primary's profile
                obs.event("server.route", req_id=req.req_id,
                          tenant=req.tenant, route=route, warm=warm,
                          stability=stability)
                with obs.span("server.route", req_id=req.req_id,
                              tenant=req.tenant, route=route,
                              stability=stability):
                    if route == "stable":
                        tres = self._auto_warm_refit(req, prof, fk)
                    else:
                        tres = self._auto_search(req, fk, route, prof)
        except FencedError:
            # zombie primary: the fencing contract says die loudly — the
            # serve loop's crash path rejects live tickets and the
            # surviving primary re-answers from the durable records
            raise
        except Exception as e:  # noqa: BLE001 - per-request terminal
            with self._counters_lock:
                self.counters["batch_failures"] += 1
            self._note_degraded()
            obs.event("server.auto_failed", req_id=req.req_id,
                      route=route, error=repr(e)[:200])
            self._forget(req)
            req.ticket._reject(e)
            return
        wall = time.perf_counter() - t0
        with self._counters_lock:
            self.counters["rows_fitted"] += req.rows
        obs.counter("server.rows_fitted").add(req.rows)
        self._finalize(req, tres)
        if warm:
            # AFTER the result is durable: the profile is warm-start
            # state, so losing an update costs the next pass a search,
            # never an answer.  The write is fenced (FencedError
            # propagates — see above); a refused disk degrades to a cold
            # next pass.
            try:
                self._update_profile(req, tres, cfg_key, route)
                with self._counters_lock:
                    self.counters["profile_updates"] += 1
                obs.counter("server.profile_updates").inc()
            except FencedError:
                raise
            except OSError as e:
                with self._counters_lock:
                    self.counters["storage_errors"] += 1
                obs.event("server.profile_refused", req_id=req.req_id,
                          error=repr(e)[:200])
        self.queue.record_drain(req.rows, wall)
        self._write_server_state()
        self._write_prom()

    def _auto_search(self, req: FitRequest, fk: dict, route: str,
                     prof) -> TenantFitResult:
        """The search leg of the ladder: exhaustive for exact/cold mode
        (bitwise the direct ``auto_fit`` call), stepwise for new tenants,
        stepwise seeded from the profile's distinct winners for drifted
        ones.  Journals under ``<root>/auto/<req_id>/`` — deterministic,
        so a recovered request resumes mid-search."""
        from ..models import auto as auto_mod

        kw = dict(fk)
        if route == "new":
            # default to the stepwise economy unless the caller pinned
            # the mode or passed a seasonal grid (stepwise is (p, d, q)
            # only — seasonal grids keep the exhaustive sweep)
            seasonal = any(len(tuple(o)) == 4
                           for o in (kw.get("orders") or ()))
            if not seasonal:
                kw.setdefault("stepwise", True)
        elif route == "drifted":
            seeds = _profile_winner_specs(prof)
            if seeds:
                kw["stepwise"] = True
                kw["orders"] = seeds
            else:
                kw.setdefault("stepwise", True)
        if kw.get("stepwise"):
            # the seed neighborhood must fit under the expansion cap —
            # profile winners (or caller seeds) can sit at the cap edge
            span = max((max(o[0], o[2]) for o in
                        (kw.get("orders") or ((0, 0, 0),))), default=0)
            kw["stepwise_max_order"] = max(
                int(kw.get("stepwise_max_order", 3)), int(span))
        kw.setdefault("chunk_rows", self._knobs["cell_rows"])
        kw.setdefault("resilient", req.resilient)
        kw.setdefault("policy", req.policy)
        kw.setdefault("align_mode", req.align_mode)
        res = auto_mod.auto_fit(
            req.values,
            checkpoint_dir=os.path.join(self._auto_dir, req.req_id),
            job_budget_s=req.remaining_s(),
            _journal_commit_hook=self._commit_hook, **kw)
        return _auto_result(req, route,
                            stability=(int(prof.get("stability", 0))
                                       if prof else 0),
                            orders=[list(s.order) for s in res.orders],
                            order_index=res.order_index,
                            criterion=res.criterion,
                            params=res.params,
                            nll=res.neg_log_likelihood,
                            converged=res.converged, iters=res.iters,
                            status=res.status,
                            criterion_name=kw.get("criterion", "aicc"),
                            include_intercept=kw.get("include_intercept",
                                                     True),
                            selection_counts=res.meta["auto_fit"]
                            ["selection_counts"],
                            stepwise=res.meta["auto_fit"].get("stepwise"))

    def _auto_warm_refit(self, req: FitRequest, prof: dict,
                         fk: dict) -> TenantFitResult:
        """The stable leg: skip stage 1 entirely — refit each row's KNOWN
        winning order, warm-started from the profile's params
        (``reliability.delta.WarmstartFit``, one compacted dispatch per
        winning-order basin).  Deterministic in (panel, profile), so a
        takeover re-answers it bitwise from the shared root."""
        import functools as _ft

        import jax.numpy as jnp

        from ..models import arima as arima_mod
        from ..models import auto as auto_mod
        from ..reliability import delta as delta_mod

        y = np.asarray(req.values)
        b, t = y.shape
        orders = np.asarray(prof["orders"], np.int32).reshape(-1, 3)
        order_index = np.asarray(prof["order_index"], np.int32)
        p_params = np.asarray(prof["params"])
        include_intercept = bool(fk.get("include_intercept", True))
        criterion = str(fk.get("criterion", "aicc"))
        fit_kw = {k: fk[k] for k in _AUTO_FIT_KNOBS
                  if fk.get(k) is not None}
        nv0 = auto_mod.panel_n_valid(y)
        dtype = p_params.dtype if p_params.dtype.kind == "f" else y.dtype
        out_params = np.full((b, p_params.shape[1]), np.nan, dtype)
        out_nll = np.full(b, np.nan, dtype)
        out_conv = np.zeros(b, bool)
        out_iters = np.zeros(b, np.int32)
        # rows no candidate ever fit keep the profile's recorded status
        out_status = np.asarray(prof["status"], np.int8).copy()
        out_crit = np.full(b, np.nan, dtype)
        for g in sorted(int(v) for v in np.unique(order_index) if v >= 0):
            rows = np.nonzero(order_index == g)[0]
            spec = auto_mod.OrderSpec(tuple(int(v) for v in orders[g]))
            k = spec.n_params(include_intercept)
            init = p_params[rows, :k].astype(y.dtype, copy=False)
            aug = np.concatenate([y[rows], init], axis=1)
            fit_fn = _ft.partial(
                arima_mod.fit, order=spec.order,
                include_intercept=include_intercept, **fit_kw)
            wf = delta_mod.WarmstartFit(fit_fn, n_time=t, k=k)
            with obs.span("server.warm_basin", order=spec.label,
                          rows=int(rows.size)):
                r = wf(aug, align_mode=req.align_mode)
            out_params[rows, :k] = np.asarray(r.params)[:, :k]
            out_nll[rows] = np.asarray(r.neg_log_likelihood)
            out_conv[rows] = np.asarray(r.converged)
            out_iters[rows] = np.asarray(r.iters, np.int32)
            out_status[rows] = np.asarray(r.status, np.int8)
            p_full, _, d_full = spec.lag_span()
            crit = np.asarray(auto_mod._criterion_one(
                jnp.asarray(out_nll[rows]),
                jnp.asarray(np.asarray(nv0)[rows].astype(out_nll.dtype)),
                k, p_full, d_full, criterion))
            out_crit[rows] = np.where(np.isfinite(crit), crit, np.nan)
        counts = {auto_mod.OrderSpec(tuple(int(v) for v in o)).label:
                  int(np.sum(order_index == g))
                  for g, o in enumerate(orders)}
        counts["none"] = int(np.sum(order_index < 0))
        return _auto_result(req, "stable",
                            stability=int(prof.get("stability", 0)),
                            orders=orders.tolist(),
                            order_index=order_index,
                            criterion=out_crit, params=out_params,
                            nll=out_nll, converged=out_conv,
                            iters=out_iters, status=out_status,
                            criterion_name=criterion,
                            include_intercept=include_intercept,
                            selection_counts=counts, stepwise=None)

    def _update_profile(self, req: FitRequest, tres: TenantFitResult,
                        cfg_key: str, route: str) -> None:
        a = tres.meta.get("auto") or {}
        self.profiles.update(
            req.tenant, values=req.values,
            orders=a["orders"],
            order_index=np.asarray(a["order_index"], np.int32),
            params=np.asarray(tres.params),
            criterion=np.asarray(a["criterion"], float),
            status=np.asarray(tres.status, np.int8),
            cfg_key=cfg_key,
            criterion_name=str(a.get("criterion_name", "aicc")),
            include_intercept=bool(a.get("include_intercept", True)),
            route=route)

    def _finalize(self, req: FitRequest, tres: TenantFitResult) -> None:
        self._store_result(req.req_id, tres)
        self._remove_request_file(req.req_id)
        self._forget(req)
        with self._counters_lock:
            self.counters["completed"] += 1
            if int((tres.status == FitStatus.TIMEOUT).sum()):
                self.counters["timeout_requests"] += 1
        obs.counter("server.completed").inc()
        # server-side completion marker on the request's own trace.  NOT
        # the timeline's uniqueness terminal: a SIGKILL can land between
        # the durable os.replace and this flush, and the survivor skips
        # re-finalizing stored ids — the client's client.result event is
        # the exactly-once terminal obs_report gates on
        with obs.trace_scope(obs.trace_for_request(req.req_id, "server")):
            obs.event("server.result_stored", req_id=req.req_id,
                      tenant=req.tenant)
        req.ticket._resolve(tres)  # last: the caller may read health() now

    def _forget(self, req: FitRequest) -> None:
        with self._live_lock:
            self._live.pop(req.req_id, None)
        self.quota.release(req.tenant, req.rows)

    # -- recovery (restart on a used root) -----------------------------------

    def _recover(self) -> None:
        """Re-answer everything a dead server left in flight: re-form
        recorded batches (their journals resume bitwise), then re-enqueue
        admitted-but-unbatched requests."""
        pending: Dict[str, FitRequest] = {}
        for fn in sorted(os.listdir(self._requests_dir)):
            if not fn.endswith(".npz"):
                continue
            path = os.path.join(self._requests_dir, fn)
            try:
                req = FitRequest.load(path)
            except Exception:  # noqa: BLE001 - torn request record
                obs.event("server.recovery_torn_request", path=path)
                continue
            if os.path.exists(os.path.join(self._results_dir,
                                           f"{req.req_id}.npz")):
                self._remove_request_file(req.req_id)
                continue
            with self._live_lock:
                live = req.req_id in self._live
            if live:
                # already admitted to THIS instance (submitted before
                # start()): the queue owns it — recovery is for the
                # previous process's orphans only
                continue
            # recovery voids deadlines: the original clock died with the
            # original process, and the re-answer contract is bitwise
            # identity with an uninterrupted run, not latency
            req.deadline_s = None
            req.ticket._canceller = self._cancel
            pending[req.req_id] = req
        records = []
        if os.path.isdir(self._batches_dir):
            for bid in sorted(os.listdir(self._batches_dir)):
                d = os.path.join(self._batches_dir, bid)
                mpath = os.path.join(d, batcher.MEMBERS_FILE)
                if not os.path.exists(mpath) or os.path.exists(
                        os.path.join(d, batcher.COMPLETE_FILE)):
                    continue
                try:
                    with open(mpath) as f:
                        rec = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                ids = [m["req_id"] for m in rec.get("members", [])]
                if not ids or not all(i in pending for i in ids):
                    # some members already answered (results written
                    # before the crash finished the batch) or records
                    # torn: the remaining members re-enqueue below
                    continue
                records.append((rec.get("seq", 0), ids,
                                rec.get("knobs", {}),
                                int(rec.get("cell_rows", 1))))
        # a crash during batch quarantine leaves OVERLAPPING records (the
        # failed batch plus its solo re-runs name the same request);
        # replay in seq order and skip any record with a member an
        # earlier record already took, or this replay would execute the
        # same request twice and double-release its quota
        handled: set = set()
        for seq, ids, knobs, cell in sorted(records):
            if any(i in handled for i in ids):
                continue
            handled.update(ids)
            members = [pending[i] for i in ids]
            self._batch_seq = max(self._batch_seq, int(seq))
            batch = batcher.MicroBatch(members, int(seq), cell_rows=cell)
            # force=True (like the unbatched path below): _finalize/
            # _quarantine release per member, so every replayed member
            # must be acquired or the tenant ledger skews negative
            for m in members:
                self.quota.try_acquire(m.tenant, m.rows, force=True)
            with self._counters_lock:
                self.counters["recovered_batches"] += 1
                self.counters["recovered_requests"] += len(members)
            obs.event("server.recover_batch", batch_id=batch.batch_id,
                      members=len(members))
            try:
                res = self._execute_batch(batch, knobs or dict(self._knobs))
            except Exception as e:  # noqa: BLE001 - quarantine, as live
                self._quarantine_batch(batch, e)
                continue
            self._deliver(batch, res)
        for req in sorted(pending.values(), key=lambda r: r.seq):
            if req.req_id in handled:
                continue
            # force=True: the dead server already admitted this work, so
            # recovery never refuses it — and the acquire stays symmetric
            # with the release in _forget (an unbalanced ledger would
            # corrupt the tenant's quota for the server's lifetime)
            self.quota.try_acquire(req.tenant, req.rows, force=True)
            with self._counters_lock:
                self.counters["recovered_requests"] += 1
            with self._live_lock:
                self._live[req.req_id] = req
            try:
                self.queue.offer(req, on_shed=self._on_shed)
            except RejectedError as e:
                with self._live_lock:
                    self._live.pop(req.req_id, None)
                self.quota.release(req.tenant, req.rows)
                req.ticket._reject(e)
                continue

    def _next_seq_floor(self) -> int:
        """Request sequence numbers survive restarts (monotonic ids)."""
        floor = 0
        try:
            for fn in os.listdir(self._requests_dir):
                if fn.startswith("r") and "-" in fn:
                    try:
                        floor = max(floor, int(fn[1:].split("-", 1)[0]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return floor

    # -- adaptation / warmth -------------------------------------------------

    def _pool_for(self, n_cols: int, dtype) -> source_mod.StagingPool:
        key = (int(n_cols), str(np.dtype(dtype)))
        with self._pools_lock:
            pool = self._pools.get(key)
            if pool is None:
                pool = source_mod.StagingPool(n_cols, dtype)
                self._pools[key] = pool
            return pool

    def _after_batch(self, batch: "batcher.MicroBatch", wall: float) -> None:
        self._autotune_from(os.path.join(batch.dir(self.root), "journal"))
        self._write_server_state()
        self._write_prom()

    def _autotune_from(self, ckpt: str) -> None:
        """ISSUE 12: ``tools/advise_budget.py``'s knob inference, run
        online — the finished batch's manifest suggests the NEXT batch's
        ``chunk_rows``/``pipeline_depth`` instead of waiting for a
        post-mortem."""
        if self._advise is None:
            return
        try:
            with open(os.path.join(ckpt, "manifest.json")) as f:
                m = json.load(f)
            a = self._advise(m)
            s = a.get("suggest") or {}
        except Exception:  # noqa: BLE001 - advisory only
            return
        changed = False
        cr = s.get("chunk_rows")
        if cr:
            # the suggested chunk size becomes the NEXT batches' cell (the
            # sustained-size logic only ever shrinks it, e.g. after OOM
            # backoff); results are bitwise-stable per cell setting
            cr = max(1, min(int(cr), self.max_batch_rows))
            if cr != self._knobs["cell_rows"]:
                self._knobs["cell_rows"] = cr
                changed = True
        pd = s.get("pipeline_depth")
        if pd:
            pd = max(1, min(int(pd), 8))
            if pd != self._knobs["pipeline_depth"]:
                self._knobs["pipeline_depth"] = pd
                changed = True
        pf = s.get("prefetch_depth")
        if pf:
            pf = max(0, min(int(pf), 4))
            if pf != self._knobs["prefetch_depth"]:
                self._knobs["prefetch_depth"] = pf
                changed = True
        if changed:
            with self._counters_lock:
                self.counters["autotune_updates"] += 1
            obs.counter("server.autotune_updates").inc()
            obs.event("server.autotune", **self._knobs)
            try:
                tmp = self._knobs_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(self._knobs, f)
                os.replace(tmp, self._knobs_path)
            except OSError:
                pass

    def _resolve_model(self, model: str) -> Callable:
        fn = self._models.get(model)
        if fn is not None:
            return fn
        if model == FORECAST_MODEL:
            # the chunked forecast walk's fit function: requests carry
            # an augmented panel + the forecast config in fit_kwargs
            # (submit_forecast) — a built-in name so forecast requests
            # stay durable/re-resolvable across restarts like model fits
            from ..forecasting import walk as _fwalk

            return _fwalk.forecast_fit
        if model == AUTO_MODEL:
            # the auto order search: resolvable at the door like any
            # model, but executed per request by _run_auto_request (the
            # serve loop intercepts AUTO batches before packing)
            from ..models import auto as _auto

            return _auto.auto_fit
        from .. import models as _models

        mod = getattr(_models, model, None)
        if mod is None or not hasattr(mod, "fit"):
            raise ValueError(f"unknown model {model!r} (not in the server "
                             "registry or the bundled model set)")
        return mod.fit

    # -- health / observability ----------------------------------------------

    def _set_state(self, state: str) -> None:
        with self._state_lock:
            if self._state == "crashed":
                return  # terminal: stop()/__exit__ must not mask a crash
            if self._state == "stopped" and state != "stopped":
                return
            self._state = state

    def _note_degraded(self) -> None:
        self._degraded_until = time.monotonic() + self.degraded_window_s

    def state(self) -> str:
        """Lifecycle/health state: ``starting`` → ``ready`` (``degraded``
        while shedding/rejecting/failing recently or the queue is near its
        bound) → ``draining``/``stopping`` → ``stopped``; ``crashed``
        terminal on a serve-loop crash."""
        with self._state_lock:
            s = self._state
        if s == "ready":
            depth = self.queue.depth()
            if (time.monotonic() < self._degraded_until
                    or depth["rows"] > 0.8 * depth["max_rows"]):
                return "degraded"
        return s

    def ready(self) -> bool:
        return self.state() in ("ready", "degraded")

    def health(self) -> dict:
        """Readiness + load + warmth in one scrape-able dict (also
        exported through the Prometheus sink)."""
        depth = self.queue.depth()
        with self._counters_lock:
            counters = dict(self.counters)
        with self._pools_lock:
            pools = {f"{t}x{dt}": p.stats()
                     for (t, dt), p in self._pools.items()}
        with self._live_lock:
            inflight = len(self._live)
        return {
            "state": self.state(),
            "ready": self.ready(),
            "degraded": self.state() == "degraded",
            "queue": depth,
            "inflight_requests": inflight,
            "tenants": self.quota.snapshot(),
            "counters": counters,
            "knobs": dict(self._knobs),
            "staging_pools": pools,
            "compile_cache": compile_cache.program_cache_stats(),
            "root": self.root,
        }

    def _numeric_health(self) -> dict:
        """Flat numeric gauges for the prom sink / obs plane."""
        h = self.health()
        out = {
            "server_ready": 1.0 if h["ready"] else 0.0,
            "server_degraded": 1.0 if h["degraded"] else 0.0,
            "server_queue_rows": float(h["queue"]["rows"]),
            "server_queue_requests": float(h["queue"]["requests"]),
            "server_inflight_requests": float(h["inflight_requests"]),
        }
        for k, v in h["counters"].items():
            out[f"server_{k}_total"] = float(v)
        pool_hits = sum(p["pool_hits"] for p in h["staging_pools"].values())
        pool_miss = sum(p["pool_misses"]
                        for p in h["staging_pools"].values())
        out["server_staging_pool_hits_total"] = float(pool_hits)
        out["server_staging_pool_misses_total"] = float(pool_miss)
        cc = h["compile_cache"]
        out["server_compile_cache_hits_total"] = float(cc["hits"])
        out["server_compile_cache_misses_total"] = float(cc["misses"])
        return out

    def _idle_tick(self) -> None:
        self._write_prom()

    def _write_prom(self, force: bool = False) -> None:
        if self._prom is None:
            return
        now = time.monotonic()
        if not force and now - self._prom_last < self._prom_interval_s:
            return
        self._prom_last = now
        nm = self._numeric_health()
        # registry first: the sink snapshot then carries the fresh values
        # and its renderer dedupes the extra copies by family name
        obs.gauge("server.queue_rows").set(nm["server_queue_rows"])
        obs.gauge("server.inflight_requests").set(
            nm["server_inflight_requests"])
        obs.gauge("server.degraded").set(nm["server_degraded"])
        try:
            self._prom.write(extra=nm)
        except Exception:  # noqa: BLE001 - the sink must never stop serving
            pass

    def _write_server_state(self) -> None:
        """``<root>/server.json``: the serving-level record the budget
        advisor's ``--serving`` mode reads (shed/reject counts, knobs,
        state) — atomic, best-effort."""
        try:
            path = os.path.join(self.root, "server.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "state": self.state(),
                    "counters": dict(self.counters),
                    "queue": self.queue.depth(),
                    "knobs": dict(self._knobs),
                    "max_batch_rows": self.max_batch_rows,
                    "batch_window_s": self.batch_window_s,
                }, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass
