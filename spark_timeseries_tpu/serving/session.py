"""Serving primitives: requests, tickets, per-tenant results, and errors.

The reference's serving story was a long-lived Spark driver holding a
``TimeSeriesRDD`` across many actions; callers handed it work and got
futures back.  The resident :class:`~.server.FitServer` needs the same
vocabulary, host-side and zero-dep:

- :class:`FitRequest` — one tenant's admitted panel fit (rows, model,
  kwargs, deadline, priority), with a durable npz spelling
  (:meth:`FitRequest.save` / :meth:`FitRequest.load`) so a SIGKILLed
  server can re-answer it on restart.
- :class:`FitTicket` — the caller's handle: a small future resolved by
  the serve loop (``result(timeout=)`` blocks, ``cancel()`` withdraws a
  queued request, a shed request resolves to :class:`RejectedError`).
- :class:`TenantFitResult` — the demuxed slice of a micro-batched walk:
  the same field layout as ``reliability.ResilientFitResult``, rows
  aligned with the request's panel.
- The error vocabulary: :class:`RejectedError` (admission control said
  no — carries ``retry_after_s``, the serving layer's backpressure
  signal; never an OOM), :class:`CancelledError`,
  :class:`ServerClosedError`.

Nothing here touches a device: requests carry host ``np.ndarray`` panels
and results carry host arrays, exactly like the resilient runner's output.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, NamedTuple, Optional, Union

import numpy as np

from ..reliability.journal import consult_disk_fault, tear_after_replace

__all__ = [
    "CancelledError",
    "FitRequest",
    "FitTicket",
    "RejectedError",
    "ServerClosedError",
    "StorageError",
    "TenantFitResult",
]


class RejectedError(RuntimeError):
    """Admission control refused (or shed) a request.

    ``retry_after_s`` is the server's backpressure estimate — how long
    until the queue has likely drained enough to admit this work; clients
    should back off at least that long.  ``shed=True`` means the request
    WAS admitted and later evicted to make room for higher-priority work
    (overload shedding); ``shed=False`` means it was refused at the door.
    Raised instead of queueing unboundedly: the server's memory ceiling is
    enforced here, so overload degrades to explicit rejections, never to
    an OOM.
    """

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 shed: bool = False):
        super().__init__(
            f"fit request rejected ({reason}); retry after "
            f"{retry_after_s:.2f}s")
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.shed = bool(shed)


class StorageError(RejectedError):
    """The server's durable root refused a write (EIO / ENOSPC / a torn
    fsync) so the request cannot be admitted SAFELY — an admission whose
    write-ahead record did not land would be lost by the next crash,
    which would break the re-answer contract.  Subclasses
    :class:`RejectedError` so every quota-release / backpressure path
    treats it as a refusal at the door; the wire serializes it as its
    own ``storage_degraded`` kind so clients know to prefer OTHER
    replicas rather than merely waiting out a queue."""

    def __init__(self, reason: str, retry_after_s: float = 5.0):
        super().__init__(f"storage degraded: {reason}",
                         retry_after_s=retry_after_s, shed=False)
        self.reason = reason


class CancelledError(RuntimeError):
    """The caller withdrew the request before it produced a result."""


class ServerClosedError(RuntimeError):
    """The server is draining, stopped, or crashed; resubmit elsewhere (a
    crashed server's admitted requests are durable — a restart on the same
    root re-answers them)."""


class TenantFitResult(NamedTuple):
    """One request's demuxed fit output (host arrays, rows aligned with
    the request's panel) — the per-tenant slice of
    ``reliability.ResilientFitResult``."""

    params: np.ndarray  # [rows, k]
    neg_log_likelihood: np.ndarray  # [rows]
    converged: np.ndarray  # [rows] bool
    iters: np.ndarray  # [rows]
    status: np.ndarray  # [rows] int8 FitStatus codes
    meta: dict


class FitRequest:
    """One admitted fit request: a tenant's ``[rows, T]`` panel plus the
    fit configuration.  Instances are created by ``FitServer.submit`` and
    by restart recovery (:meth:`load`)."""

    __slots__ = ("req_id", "seq", "tenant", "values", "model", "fit_kwargs",
                 "priority", "deadline_s", "admitted_at", "align_mode",
                 "resilient", "policy", "ticket")

    def __init__(self, req_id: str, seq: int, tenant: str,
                 values: np.ndarray, model: Union[str, Callable],
                 fit_kwargs: dict, *, priority: int = 0,
                 deadline_s: Optional[float] = None,
                 align_mode: str = "general", resilient: bool = False,
                 policy: str = "impute"):
        self.req_id = req_id
        self.seq = int(seq)
        self.tenant = str(tenant)
        self.values = values
        self.model = model
        # canonicalized through a JSON round trip at ADMISSION: the durable
        # request record is JSON, and the journal's config hash covers the
        # kwargs by repr — a live run fitting `order=(1,0,0)` while its
        # restarted twin fits `order=[1,0,0]` would hash as two different
        # configs and refuse to resume its own journal.  Non-JSON kwargs
        # (device arrays, callables) are refused loudly here: they could
        # not survive a restart either.
        try:
            self.fit_kwargs = json.loads(json.dumps(dict(fit_kwargs)))
        except (TypeError, ValueError) as e:
            raise TypeError(
                "serving fit kwargs must be JSON-serializable (they are "
                f"journaled for crash recovery): {e}") from None
        self.priority = int(priority)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.admitted_at = time.monotonic()
        self.align_mode = align_mode
        self.resilient = bool(resilient)
        self.policy = str(policy)
        self.ticket = FitTicket(req_id)

    @property
    def rows(self) -> int:
        return int(self.values.shape[0])

    def remaining_s(self) -> Optional[float]:
        """Seconds until this request's deadline; None when unbounded."""
        if self.deadline_s is None:
            return None
        return self.deadline_s - (time.monotonic() - self.admitted_at)

    def expired(self) -> bool:
        rem = self.remaining_s()
        return rem is not None and rem <= 0.0

    # -- durability ----------------------------------------------------------
    # One npz per request, written at admission BEFORE the caller's ticket
    # is returned: the request is the serving layer's write-ahead record
    # (the batch journals cover compute; this covers the QUEUE).  Model
    # callables are referenced by registry NAME so a restarted server can
    # re-resolve them — an unnamed callable is refused at submit.

    def save(self, path: str) -> None:
        # disk-fault seam: the write-ahead record is the admission
        # contract's durability — an injected EIO/ENOSPC raises HERE,
        # before the caller's ticket exists, so the server can refuse
        # admission with a typed StorageError instead of losing work
        verdict = consult_disk_fault(path, "write_ahead")
        meta = {
            "req_id": self.req_id, "seq": self.seq, "tenant": self.tenant,
            "model": self.model, "fit_kwargs": self.fit_kwargs,
            "priority": self.priority, "deadline_s": self.deadline_s,
            "align_mode": self.align_mode, "resilient": self.resilient,
            "policy": self.policy,
        }
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, values=self.values,
                     meta=np.frombuffer(
                         json.dumps(meta).encode(), dtype=np.uint8))
        os.replace(tmp, path)
        if verdict == "torn":
            tear_after_replace(path)

    @classmethod
    def load(cls, path: str) -> "FitRequest":
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"].tobytes()).decode())
            values = np.array(z["values"])
        req = cls(meta["req_id"], meta["seq"], meta["tenant"], values,
                  meta["model"], meta["fit_kwargs"],
                  priority=meta["priority"], deadline_s=meta["deadline_s"],
                  align_mode=meta["align_mode"], resilient=meta["resilient"],
                  policy=meta["policy"])
        return req


class FitTicket:
    """The caller's future for one request.

    Exactly one terminal transition ever lands (result, error, cancelled,
    shed); ``result(timeout=)`` blocks until it does.  Tickets are
    process-local — after a server crash the durable request is re-answered
    through ``FitServer.result_for`` on the restarted server, not through
    the dead process's ticket objects.
    """

    __slots__ = ("req_id", "_done", "_result", "_error", "_cancelled",
                 "_lock", "_canceller")

    # lock-discipline contract (tools/lint lock-map): the serve loop,
    # shedding offers on other caller threads, and cancel() all race to
    # land the ONE terminal transition; _lock arbitrates, _done.set()
    # is the (atomic) publication.
    _protected_by_ = {
        "_result": "_lock",
        "_error": "_lock",
        "_cancelled": "_lock",
    }

    def __init__(self, req_id: str):
        self.req_id = req_id
        self._done = threading.Event()
        self._result: Optional[TenantFitResult] = None
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._lock = threading.Lock()
        self._canceller = None  # set by the server at admission

    # -- serve-loop side -----------------------------------------------------

    def _resolve(self, result: TenantFitResult) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._result = result
            self._done.set()

    def _reject(self, error: BaseException) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
            self._done.set()

    def _mark_cancelled(self) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._cancelled = True
            self._error = CancelledError(
                f"request {self.req_id} cancelled before completion")
            self._done.set()

    # -- caller side ---------------------------------------------------------

    def cancel(self) -> bool:
        """Withdraw the request.  Returns True when the cancellation took
        effect (the request was still queued — it will never dispatch and
        ``result()`` raises :class:`CancelledError`).  A request already
        IN a dispatched batch cannot be cancelled mid-walk (XLA dispatch
        is not interruptible — the same contract as the watchdog's
        abandonment): the walk completes and the result is delivered;
        False is returned."""
        c = self._canceller
        if c is not None and c(self.req_id):
            self._mark_cancelled()
            return True
        return self._done.is_set() and self._cancelled

    def done(self) -> bool:
        return self._done.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def result(self, timeout: Optional[float] = None) -> TenantFitResult:
        """Block for the demuxed result (raises the terminal error for a
        shed/cancelled/failed request; ``TimeoutError`` if ``timeout``
        elapses first — the request itself stays in flight)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.req_id} still in flight after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def error(self) -> Optional[BaseException]:
        """The terminal error, if the ticket resolved to one (non-blocking)."""
        return self._error if self._done.is_set() else None
